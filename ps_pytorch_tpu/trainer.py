"""Training drivers: the host-side loop around the jitted PS train step.

This is the TPU-native collapse of the reference's three role runtimes
(SURVEY.md sections 1-3). `SyncReplicasMaster_NN.start()` (sync_replicas_
master_nn.py:133-197), `DistributedWorker.train()` (distributed_worker.py:
104-180) and the single-machine `NN_Trainer.train_and_validate` (nn_ops.py:
48-88) all become ONE driver: under SPMD there is no master process, no
worker processes, no step handshake — a single host loop dispatches one
fused XLA program per global step over the whole mesh. `num_workers=1` on
one chip is exactly the reference's single_machine.py baseline.

The driver owns everything the reference's role runtimes owned that is not
the step itself: epoch iteration, per-iteration reference-format log lines
(utils/logging.py), eval cadence, single-writer checkpoints, and resume
(which the reference lacks — sync_replicas_master_nn.py:102 always restarts
at step 1).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

import jax
import numpy as np

from . import checkpoint as ckpt
from .data import (
    BatchIterator,
    Dataset,
    make_preprocessor,
    prefetch_to_device,
    prepare_data,
    shard_for_worker,
)
from .models import build_model, input_shape_for, param_count
from .optim import build_optimizer
from .parallel import (
    FlatVector,
    PSConfig,
    batch_sharding,
    init_ps_state,
    make_mesh,
    make_ps_eval_step,
    make_ps_train_step,
    shard_state,
)
from .obs import (
    NULL_TRACER,
    ProfileWindow,
    Tracer,
    new_run_id,
    run_header,
    validate_event,
)
from .resilience import AdaptiveMaskController, resolve_fault_plan
from .resilience import elastic
from .resilience.precision import PrecisionController
from .utils import PhaseTimer, format_eval_line, format_iter_line, get_logger

logger = get_logger()


def append_metrics_line(path: Optional[str], record: dict) -> None:
    """Structured metrics sink (one JSON object per line). The reference
    has only parseable log text (SURVEY.md section 5 'no TensorBoard/CSV');
    this is the machine-readable channel next to it.

    THE write choke point for every event emitter: each record is
    validated/normalized against the observability event registry
    (obs/schema.py — unknown kinds and missing required fields raise,
    declared counter fields are coerced to int) and stamped with a
    ``t_wall`` wall-clock second, so the JSONL stream merges onto the
    span-trace timeline (tools/trace_report.py overlays)."""
    if not path:
        return
    record = validate_event(record)
    record.setdefault("t_wall", round(time.time(), 6))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def _shared_run_id() -> str:
    """One run id for ALL processes of a multihost run.

    ``new_run_id()`` is per-process RNG, so each host would stamp its
    metrics run header and span-trace file with a DIFFERENT id, breaking
    the cross-process correlation tools/trace_report.py merges on
    (PSL007). Process 0's draw is broadcast as bytes so every host
    carries the same id."""
    rid = np.frombuffer(new_run_id().encode("ascii"), dtype=np.uint8)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        rid = multihost_utils.broadcast_one_to_all(rid)
    return np.asarray(rid).tobytes().decode("ascii")


def average_metrics(step_fn, batches) -> dict:
    """Uniform average of per-batch metric dicts (batches are equal-sized:
    BatchIterator drops partial tails). Shared by Trainer.validate and the
    out-of-band Evaluator."""
    sums, count = {}, 0
    for batch in batches:
        # eval is off the hot path; fetching every batch is the point here
        m = jax.device_get(step_fn(batch))  # psl: sync-ok
        for k, v in m.items():
            sums[k] = sums.get(k, 0.0) + float(v)
        count += 1
    return {k: v / max(count, 1) for k, v in sums.items()}


@dataclasses.dataclass
class TrainConfig:
    """Host-loop configuration, mirroring the reference CLI surface
    (/root/reference/src/distributed_nn.py:24-68). Engine-level knobs
    (num_aggregate, compression, placement, BN mode) live in PSConfig."""

    network: str = "LeNet"
    dataset: str = "MNIST"
    batch_size: int = 128  # per-worker batch, reference --batch-size
    test_batch_size: int = 500
    epochs: int = 100
    max_steps: int = 10000
    lr: float = 0.01
    momentum: float = 0.5
    weight_decay: float = 0.0
    optimizer: str = "sgd"  # sgd | adam (reference optim/)
    seed: int = 1
    log_interval: int = 10
    eval_freq: int = 50
    train_dir: str = "output/models/"
    save_checkpoints: bool = True
    compress_checkpoints: bool = False  # native C++ codec (ops/codec.py)
    resume: bool = False
    data_root: Optional[str] = None
    allow_synthetic: bool = True
    shard_mode: str = "reshuffle"  # reference parity; "disjoint" improvement
    dtype: str = "float32"  # compute dtype: float32 | bfloat16 (MXU-native)
    remat: bool = False  # per-block activation rematerialization (ResNets)
    metrics_file: Optional[str] = None  # append one JSON line per logged step
    # span tracing (obs/trace.py, --trace): write this process's host-
    # phase span stream (trace_train_p<i>.jsonl) into this directory.
    # None = the NULL tracer: zero overhead, zero host syncs (pslint
    # PSL004 patrols the instrumented paths). tools/trace_report.py
    # merges per-process files and summarizes p50/p99 per phase.
    trace_dir: Optional[str] = None
    profile_dir: Optional[str] = None  # jax.profiler trace output
    # bounded profiler capture window [profile_start, profile_start +
    # profile_steps): None = auto (one warmup step after the run's first
    # step, so compilation stays out of the capture)
    profile_start: Optional[int] = None
    profile_steps: int = 10
    # straggler watchdog (reference --kill-threshold, distributed_nn.py:52:
    # there it was meant to kill slow workers; under SPMD there is nothing
    # to kill, so the live semantics are detection + structured warning)
    straggler_threshold_s: Optional[float] = None
    # watchdog escalation: this many CONSECUTIVE straggler steps collapse
    # into one structured `straggler_storm` event (per-step warnings are
    # suppressed until the storm breaks — N slow steps is a condition,
    # not N incidents)
    straggler_storm_n: int = 3
    # non-finite guard abort: raise after this many consecutive skipped
    # steps (0 = never abort — count and log only). The guard itself is
    # PSConfig.nonfinite_guard; this is the host-side tripwire.
    max_consecutive_skips: int = 8
    # adaptive partial aggregation window (steps): with PSConfig.
    # num_aggregate_min/max set, the controller re-picks the aggregation
    # count every this-many steps from the straggler watchdog's timings
    # (resilience/elastic.AdaptiveMaskController; needs the watchdog
    # armed — straggler_threshold_s is the slow-step criterion)
    adapt_window: int = 20
    # adaptive per-bucket precision budget (bytes): with PSConfig.
    # precision_adapt on, caps the per-step EFFECTIVE gradient wire
    # bytes the PrecisionController may tag (resilience/precision.py;
    # None = density ladder only, no cap). Windows share adapt_window.
    wire_budget_bytes: Optional[int] = None
    # deterministic fault injection: a JSON FaultPlan ('@path' to read a
    # file), resilience/faults.py; PS_TPU_FAULTS env var when unset here
    fault_plan: Optional[str] = None


class Trainer:
    """Drives PS data-parallel training of one model on one mesh."""

    def __init__(self, tcfg: TrainConfig, pcfg: PSConfig, dataset: Optional[Dataset] = None):
        self.tcfg, self.pcfg = tcfg, pcfg
        if tcfg.straggler_storm_n < 1:
            # 0 would silently swallow BOTH the per-step straggler events
            # (streak < n never true) and the storm event (streak == n
            # never true) — reject it instead of losing observability
            raise ValueError(
                f"straggler_storm_n must be >= 1, got "
                f"{tcfg.straggler_storm_n} (1 = escalate immediately; "
                f"use a large value to effectively disable storms)"
            )
        self._stop_requested = False
        # straggler watchdog event counter (observable --mode action)
        self.straggler_steps = 0
        # storm escalation state (straggler_storm_n consecutive slow steps)
        self.straggler_storms = 0
        self._straggler_streak = 0
        # non-finite guard: skip count already reported to the host (the
        # device-side truth rides the metrics dict, fetched per window)
        self._skipped_seen = 0
        # adaptive partial aggregation: the host half that picks each
        # window's traced count (the train step takes it as an argument);
        # the controller itself rejects a missing watchdog threshold —
        # its policy consumes the watchdog's per-step walltimes
        self._adaptive = None
        if pcfg.adaptive_aggregate:
            self._adaptive = AdaptiveMaskController(
                pcfg,
                tcfg.straggler_threshold_s,
                tcfg.adapt_window,
                event_sink=lambda rec: append_metrics_line(
                    tcfg.metrics_file, rec
                ),
                # multi-host: hosts see different local walltimes but
                # must trace the SAME count into the global psum; the
                # controller applies this min-over-hosts at each window
                # close (boundaries are step-counted, so every host
                # reaches the collective together). One int32 DCN
                # allgather per window — noise next to the per-step
                # stop consensus.
                consensus=(
                    self._count_consensus
                    if jax.process_count() > 1
                    else None
                ),
            )
        self.faults = resolve_fault_plan(tcfg.fault_plan)
        if self.faults is not None:
            logger.warning("fault injection ACTIVE: %s", self.faults)
        self.dataset = dataset or prepare_data(
            tcfg.dataset, root=tcfg.data_root, allow_synthetic=tcfg.allow_synthetic
        )
        if pcfg.dcn_hosts > 1:
            from .parallel import make_hybrid_mesh

            self.mesh = make_hybrid_mesh(
                num_hosts=pcfg.dcn_hosts,
                per_host=pcfg.num_workers // pcfg.dcn_hosts,
                axis_names=pcfg.axis_name,
            )
        else:
            self.mesh = make_mesh(num_workers=pcfg.num_workers)
        import jax.numpy as jnp

        compute_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[tcfg.dtype]
        # compute in bf16 on the MXU when asked; params/optimizer state and
        # the loss stay f32 (flax dtype= is the compute dtype only)
        self.model = build_model(
            tcfg.network,
            num_classes=self.dataset.num_classes,
            dtype=compute_dtype,
            bn_axis_name=pcfg.axis_name if pcfg.bn_mode == "synced" else None,
            remat=tcfg.remat,
        )
        self.tx = build_optimizer(
            tcfg.optimizer,
            tcfg.lr,
            momentum=tcfg.momentum,
            weight_decay=tcfg.weight_decay,
            # flat state (the default) takes the whole-vector update
            # variants — same math, no per-leaf tree_map
            flat=(pcfg.state_layout == "flat"),
        )
        shape = input_shape_for(tcfg.network)
        state = init_ps_state(
            self.model, self.tx, pcfg, jax.random.key(tcfg.seed), shape
        )
        self.state = shard_state(state, self.mesh, pcfg)
        # adaptive per-bucket precision: the host half that picks each
        # window's traced tag vector (the train step takes it as an
        # argument — VALUES into one compiled program, never a retrace).
        # Sized from the SAME BucketPlan the wire carves (state_plan),
        # so tag b always names wire bucket b.
        self._precision = None
        if pcfg.precision_adapt:
            from .parallel.ps import state_plan

            n_params = (
                state.params.layout.total
                if isinstance(state.params, FlatVector)
                else param_count(state.params)
            )
            self._precision = PrecisionController(
                pcfg,
                state_plan(pcfg, n_params).sizes,
                tcfg.adapt_window,
                budget_bytes=tcfg.wire_budget_bytes,
                event_sink=lambda rec: append_metrics_line(
                    tcfg.metrics_file, rec
                ),
                # multi-host: telemetry is pmean'd (every host sees the
                # same stats in exact arithmetic) but the tag vector
                # feeds a traced collective, so a paranoid elementwise
                # min-over-hosts is applied at each window close —
                # coarsest lattice wins, consensus can only shrink the
                # effective bytes. One small int32 DCN allgather per
                # window, like the mask controller's.
                consensus=(
                    self._tags_consensus
                    if jax.process_count() > 1
                    else None
                ),
            )
        pre_train = make_preprocessor(tcfg.dataset, train=True)
        pre_eval = make_preprocessor(tcfg.dataset, train=False)
        self._train_step = make_ps_train_step(
            self.model, self.tx, pcfg, self.mesh, preprocess=pre_train,
            faults=self.faults,
        )
        self._eval_step = make_ps_eval_step(
            self.model, pcfg, self.mesh, preprocess=pre_eval
        )
        self._key = jax.random.key(tcfg.seed + 1)
        self._ckpt = ckpt.AsyncCheckpointer(
            event_sink=lambda rec: append_metrics_line(tcfg.metrics_file, rec),
            faults=self.faults,
        )
        # one run id ties this run's streams together (metrics JSONL run
        # header + the per-process span trace file) — broadcast from
        # process 0 so every host agrees on it
        self.run_id = _shared_run_id()
        self.tracer = NULL_TRACER
        if tcfg.trace_dir:
            self.tracer = Tracer(
                "train",
                path=os.path.join(
                    tcfg.trace_dir,
                    f"trace_train_p{jax.process_index()}.jsonl",
                ),
                run_id=self.run_id,
                pid=jax.process_index(),
                # host spans double as jax.profiler.TraceAnnotation
                # scopes, so a --profile-dir capture shows the named
                # phases on the profiler timeline too
                annotate=True,
                geometry=self._geometry(),
            )
        logger.info(
            "model %s (%d params), dataset %s%s, %d workers",
            tcfg.network,
            # flat layout: the true count is static metadata (the padded
            # buffer would over-count by the alignment tail, and
            # materializing the tree view just to count would waste a
            # params-sized device allocation)
            (
                state.params.layout.total
                if isinstance(state.params, FlatVector)
                else param_count(state.params)
            ),
            self.dataset.name,
            " [synthetic]" if self.dataset.synthetic else "",
            pcfg.num_workers,
        )

    def _geometry(self) -> dict:
        """The run-header geometry block: enough to interpret a stream
        without the CLI line that produced it."""
        return {
            "num_workers": self.pcfg.num_workers,
            "network": self.tcfg.network,
            "dataset": self.tcfg.dataset,
            "opt_placement": self.pcfg.opt_placement,
            "state_layout": self.pcfg.state_layout,
            "processes": jax.process_count(),
        }

    # ------------------------------------------------------------------ resume
    def try_resume(self) -> Optional[int]:
        """Restore the newest VALID checkpoint from train_dir, if any.

        A corrupt/truncated file (CRC trailer mismatch, torn bytes) is
        quarantined — renamed `*.corrupt`, out of the model_step_N
        namespace — and the next older checkpoint is tried: a damaged
        latest checkpoint costs one eval_freq window of progress, not the
        run. Transient read errors (already retried with backoff inside
        the read) skip the file WITHOUT quarantining it. Structure
        mismatches (e.g. comm_state for a disabled feature) still raise:
        they are configuration errors, not damage.

        Multi-host: the step is chosen ONCE (process 0 walks the list)
        and broadcast, because a file torn on only some replicas of a
        shared dir would otherwise send hosts down different fallbacks —
        and JAX never cross-checks replicated values, so the run would
        continue silently divergent.

        Elastic resume (resilience/elastic.py): when the dir's
        ``elastic.json`` manifest says the checkpoint was written under a
        DIFFERENT mesh geometry (worker count, optimizer placement, or a
        ZeRO-1 bucket/quant carving change), the raw state is reshaped
        into this run's geometry before restore — params and optimizer
        moments bit-exact, per-worker EF residuals and local BN stats
        re-distributed — and a ``resume_reshape`` event lands in the
        metrics JSONL."""
        steps = ckpt.available_steps(self.tcfg.train_dir)
        if jax.process_count() > 1:
            return self._try_resume_multihost(steps)
        if not steps:
            return None
        target = jax.device_get(self.state)
        for step in reversed(steps):
            try:
                restored = self._restore_step(target, step)
            except ckpt.CheckpointCorruptError as e:
                self._quarantine(step, e)
                continue
            except OSError as e:
                logger.warning(
                    "resume: checkpoint step %d unreadable (%s); trying "
                    "older (file left in place)", step, e,
                )
                continue
            self.state = shard_state(restored, self.mesh, self.pcfg)
            self._sync_guard_baseline()
            logger.info(
                "resumed from %s",
                ckpt.checkpoint_path(self.tcfg.train_dir, step),
            )
            return step
        return None

    def _restore_step(self, target, step: int):
        """Load checkpoint `step` into `target`'s structure, routing
        through the elastic reshape when the dir's geometry manifest says
        the file was written on a different mesh. Raises exactly what
        load_checkpoint raises (CheckpointCorruptError/OSError for
        damage, ValueError for config mismatches), so the resume loops'
        fallback handling is unchanged."""
        raw = ckpt.load_checkpoint_raw(self.tcfg.train_dir, step)
        src = elastic.load_geometry(self.tcfg.train_dir, step=step)
        dst = elastic.geometry_of(self.pcfg)
        if src is not None and elastic.needs_reshape(src, dst):
            logger.warning(
                "resume-reshape: checkpoint step %d was written on "
                "%d workers (%s placement); reshaping onto %d workers "
                "(%s placement)",
                step, src.num_workers, src.opt_placement,
                dst.num_workers, dst.opt_placement,
            )
            raw = elastic.reshape_raw_state(raw, src, self.pcfg, target)
            append_metrics_line(
                self.tcfg.metrics_file,
                {
                    "kind": "resume_reshape",
                    "step": step,
                    "from": src.to_json(),
                    "to": dst.to_json(),
                },
            )
            return ckpt.restore_from_raw(target, raw, step)
        try:
            restored = ckpt.restore_from_raw(target, raw, step)
        except ValueError as e:
            if src is None:
                # structure mismatch with no manifest to reshape by: a
                # pre-elastic checkpoint resumed on a changed mesh
                raise ValueError(
                    f"cannot restore checkpoint step {step}: {e}. No "
                    f"elastic.json manifest (or per-step entry) in "
                    f"{self.tcfg.train_dir!r} — if the mesh geometry "
                    f"changed since this checkpoint was written, resume "
                    f"once on the ORIGINAL geometry (which now writes "
                    f"the manifest) and then reshape."
                ) from e
            raise
        if src is None and self.pcfg.opt_placement == "sharded":
            # the one geometry change shapes canNOT catch: a ZeRO-1
            # bucket/quant re-carving keeps the stacked [n, shard]
            # moment shapes and only permutes the worker->region
            # mapping. Without a manifest we cannot verify it, so say
            # so instead of staying silent.
            logger.warning(
                "resumed checkpoint step %d without an elastic manifest "
                "entry: cannot verify its ZeRO-1 carving matches "
                "--bucket-bytes/--quant-block-size — if those changed "
                "since it was written, optimizer moments are silently "
                "mis-mapped; resume on the original settings if unsure",
                step,
            )
        return restored

    def _sync_guard_baseline(self) -> None:
        """A restored GuardState carries the LIFETIME skip count — seed
        the host's already-reported watermark from it, or the first
        metrics fetch of a healthy resumed run re-reports the old skips
        as a fresh grad_skip event."""
        if self.state.guard_state is not None:
            self._skipped_seen = int(
                jax.device_get(self.state.guard_state.skipped)
            )

    def _quarantine(self, step: int, err: BaseException) -> None:
        logger.warning(
            "resume: checkpoint step %d is corrupt (%s); quarantining "
            "and falling back", step, err,
        )
        quarantined = ckpt.quarantine_checkpoint(self.tcfg.train_dir, step)
        append_metrics_line(
            self.tcfg.metrics_file,
            {"kind": "ckpt_quarantined", "step": step,
             "path": quarantined, "error": str(err)},
        )

    def _try_resume_multihost(self, steps) -> Optional[int]:
        """Mesh-consensus resume: process 0 picks the newest step that
        passes an integrity check (quarantining corrupt ones — one
        renamer, so no os.replace race), the choice is broadcast, and
        every process restores that SAME step. A host whose own replica
        then fails the agreed load raises loudly — a crashed process
        beats silently divergent replicated state."""
        from jax.experimental import multihost_utils

        chosen = -1
        if jax.process_index() == 0:
            for step in reversed(steps):
                try:
                    ckpt.verify_checkpoint(self.tcfg.train_dir, step)
                    chosen = step
                    break
                except ckpt.CheckpointCorruptError as e:
                    self._quarantine(step, e)
                except OSError as e:
                    logger.warning(
                        "resume: checkpoint step %d unreadable (%s); "
                        "trying older (file left in place)", step, e,
                    )
        chosen = int(multihost_utils.broadcast_one_to_all(np.int32(chosen)))
        if chosen < 0:
            return None
        target = jax.device_get(self.state)
        restored = self._restore_step(target, chosen)
        self.state = shard_state(restored, self.mesh, self.pcfg)
        self._sync_guard_baseline()
        logger.info(
            "resumed from %s (mesh-consensus choice)",
            ckpt.checkpoint_path(self.tcfg.train_dir, chosen),
        )
        return chosen

    # ----------------------------------------------------------- guard (host)
    def _guard_check(self, m: dict, step_no: int, abort: bool = True) -> None:
        """Host half of the non-finite gradient guard. Runs wherever the
        metrics dict is already on host (log window / backpressure sync —
        the guard itself never forces a transfer): emits one structured
        `grad_skip` event per window that saw new skips, and aborts once
        the device-side skip streak crosses max_consecutive_skips — at
        that point the optimizer is the identity and "training" is a very
        expensive sleep; the operator should resume from the last good
        checkpoint with a smaller lr / different data shard."""
        if "skipped_steps" not in m:
            return
        skipped, streak = int(m["skipped_steps"]), int(m["skip_streak"])
        if skipped > self._skipped_seen:
            logger.warning(
                "non-finite gradients: %d step(s) skipped so far "
                "(current streak %d) — params were NOT updated on those",
                skipped, streak,
            )
            rec = {
                "kind": "grad_skip",
                "step": step_no,
                "skipped_steps": skipped,
                "skip_streak": streak,
            }
            if "loss_scale" in m:
                rec["loss_scale"] = float(m["loss_scale"])
            append_metrics_line(self.tcfg.metrics_file, rec)
            self._skipped_seen = skipped
        if not abort:
            return
        k = self.tcfg.max_consecutive_skips
        if k > 0 and streak >= k:
            raise RuntimeError(
                f"aborting at step {step_no}: {streak} consecutive steps "
                f"had non-finite gradients (threshold {k}) — every one "
                f"was skipped, so params are stuck at step "
                f"{step_no - streak}. Training has diverged or the input "
                f"shard is corrupt; resume from the last valid checkpoint "
                f"with --resume after fixing the cause."
            )

    def _maybe_end_storm(self, last_slow_step: int) -> None:
        """Close an open straggler storm with ONE structured event
        carrying the storm's true length. The storm-start event is
        emitted at streak == storm_n (so its `consecutive` is always
        exactly storm_n) and per-step records are suppressed while it
        lasts — without a closing record the storm's extent would be
        unrecoverable from the JSONL."""
        t = self.tcfg
        if self._straggler_streak < t.straggler_storm_n:
            return
        logger.warning(
            "straggler storm cleared: %d consecutive slow steps "
            "(steps %d-%d)",
            self._straggler_streak,
            last_slow_step - self._straggler_streak + 1,
            last_slow_step,
        )
        append_metrics_line(
            t.metrics_file,
            {
                "kind": "straggler_storm_end",
                "step": last_slow_step,
                "start_step": last_slow_step - self._straggler_streak + 1,
                "consecutive": self._straggler_streak,
            },
        )

    @staticmethod
    def _count_consensus(proposed: int) -> int:
        """Mesh-wide agreement on the next window's aggregation count:
        min over hosts of the local proposals — a straggler seen by ANY
        host shrinks the mask for everyone; recovery needs every host
        clean. Collective (host allgather): every host reaches the same
        window boundary on the same step, like _stop_consensus."""
        from jax.experimental import multihost_utils

        return int(np.min(multihost_utils.process_allgather(
            np.asarray([proposed], np.int32)
        )))

    @staticmethod
    def _tags_consensus(proposed: np.ndarray) -> np.ndarray:
        """Mesh-wide agreement on the next window's per-bucket precision
        tags: elementwise min over hosts' adopted vectors — the coarsest
        lattice ANY host wants wins, so consensus only ever shrinks the
        effective wire bytes (never breaks a budget a host enforced).
        Collective (host allgather): window boundaries are step-counted,
        so every host closes the same window on the same step, like
        _count_consensus."""
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(
            np.asarray(proposed, np.int32)
        )
        return np.min(gathered, axis=0).astype(np.int32)

    def _record_geometry(self, step_no: int) -> None:
        """Record this run's mesh geometry in the elastic.json manifest
        (single writer), keyed by checkpoint step — an elastically
        resumed dir holds mixed-geometry checkpoints, and a fallback
        resume must reshape each file by the geometry that WROTE it."""
        if jax.process_index() == 0:
            elastic.save_geometry(
                self.tcfg.train_dir, elastic.geometry_of(self.pcfg),
                step=step_no,
            )

    # ------------------------------------------------------------ graceful stop
    def request_stop(self) -> None:
        """Ask the training loop to stop after the current step (and write
        a final checkpoint). Safe from signal handlers/threads."""
        self._stop_requested = True

    @property
    def stop_requested(self) -> bool:
        return self._stop_requested

    def _stop_consensus(self) -> bool:
        """Mesh-wide agreement on the stop flag, checked once per step.

        Single-process: just the local flag. Multi-host: OR of every
        process's flag via a host allgather — a collective, so EVERY
        process must reach this same point each step (they do: the train
        loops run the same schedule). A SIGTERM delivered to any one host
        therefore stops all of them at the same step boundary, after
        which the (also collective) checkpoint save is safe. Cost is one
        scalar DCN allgather per step — noise next to the gradient psum.
        Promotes a remotely-raised stop into the local flag so the
        preemption exit path (skip validation, log) behaves identically
        on every host."""
        if jax.process_count() == 1:
            return self._stop_requested
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([1 if self._stop_requested else 0], np.int32)
        )
        if bool(np.any(flags)):
            self._stop_requested = True
            return True
        return False

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> graceful stop: finish the step, checkpoint,
        return — so a preempted run resumes exactly with --resume. (The
        reference's only recovery is killall + restart from step 1.)
        Call from the main thread; second signal falls back to the
        default handler (hard kill).

        Multi-host safe: the handler only sets the LOCAL flag; the train
        loop reaches mesh consensus on it every step (_stop_consensus), so
        a signal on one host stops every host at the same step boundary —
        a unilateral local stop would desert the other hosts' collectives
        mid-step and deadlock until the scheduler hard-killed everyone."""
        import signal

        def handler(signum, frame):
            logger.warning(
                "signal %d: stopping after current step (next one kills)",
                signum,
            )
            self.request_stop()
            signal.signal(signum, signal.SIG_DFL)

        self._prev_handlers = {
            signal.SIGTERM: signal.signal(signal.SIGTERM, handler),
            signal.SIGINT: signal.signal(signal.SIGINT, handler),
        }

    def restore_signal_handlers(self) -> None:
        """Put back whatever handlers were installed before
        install_signal_handlers (embedding applications keep theirs)."""
        import signal

        for signum, prev in getattr(self, "_prev_handlers", {}).items():
            if prev is None:
                # prior handler was installed from C (signal.signal
                # returned None) — we cannot re-install it; leave ours
                # replaced by the safe default instead of raising
                signal.signal(signum, signal.SIG_DFL)
            else:
                signal.signal(signum, prev)
        self._prev_handlers = {}

    # ------------------------------------------------------------------- train
    def train(self) -> dict:
        """Run up to epochs/max_steps. Returns final metrics. A stop
        requested BEFORE the loop starts (signal during setup) is honored
        at the first step — never silently cleared."""
        t = self.tcfg
        # the stream-opening run header: FIRST record, before resume can
        # emit resume_reshape/ckpt_quarantined events into the file
        append_metrics_line(
            t.metrics_file,
            run_header(
                "train", run_id=self.run_id, geometry=self._geometry(),
                pid=jax.process_index(),
            ),
        )
        if t.resume:
            self.try_resume()
        global_batch = t.batch_size * self.pcfg.num_workers
        # reference parity: each worker shuffles the full set independently
        # (loader.py docstring); the global batch stacks per-worker slices.
        iters = []
        for w in range(self.pcfg.num_workers):
            imgs, labels, seed = shard_for_worker(
                self.dataset.train_images,
                self.dataset.train_labels,
                w,
                self.pcfg.num_workers,
                mode=t.shard_mode,
                seed=t.seed,
            )
            iters.append(BatchIterator(imgs, labels, t.batch_size, seed=seed))
        total = iters[0].num_samples
        steps_per_epoch = len(iters[0])
        metrics = {}
        step_no = int(jax.device_get(self.state.step))
        first_step = step_no + 1  # pays XLA compilation (also after resume)
        timer = PhaseTimer()
        # metrics stay on device between log windows (the host loop never
        # blocks dispatch), so per-step timer.total measures dispatch, not
        # compute. The logged/recorded time_cost is therefore the window
        # average: (walltime since last log, measured AFTER the window's
        # device_get drained all in-flight steps) / steps in the window —
        # the honest steady-state per-step time analysis/ scripts expect.
        window_t0, window_steps = time.perf_counter(), 0
        # dispatch backpressure: without any per-step sync the host could
        # enqueue an unbounded run-ahead (every in-flight step pins its
        # sharded batch on device). Bound it independently of log_interval.
        unsynced, max_unsynced = 0, 32
        done = False
        # profiler window: profile_steps post-compile steps (obs/
        # profiler.py), parity role of the reference's per-phase wall
        # spans but with real device timelines (SURVEY.md section 5
        # "tracing"; view with tensorboard/xprof)
        pw = ProfileWindow(
            t.profile_dir,
            start_step=(
                t.profile_start if t.profile_start is not None
                else first_step + 1
            ),
            num_steps=t.profile_steps,
        )
        if t.profile_dir and (pw.start > t.max_steps or pw.stop <= first_step):
            # the window misses this run's steps entirely — starts past
            # max_steps, or (an explicit --profile-start on a resumed
            # run) ended before the resume point. Say so rather than
            # silently writing nothing.
            logger.info(
                "profile-dir set but the capture window [%d, %d) misses "
                "this run's steps [%d, %d] — no trace will be written",
                pw.start, pw.stop, first_step, t.max_steps,
            )
        tr = self.tracer
        last_saved = None
        try:
            for epoch in range(1, t.epochs + 1):
                if done:
                    break
                epochs_iters = [it.epoch() for it in iters]

                def _host_batches(eis=epochs_iters):
                    for _ in range(steps_per_epoch):
                        parts = [next(ei) for ei in eis]
                        yield {
                            k: np.concatenate([p[k] for p in parts])
                            for k in parts[0]
                        }

                # batches land on the mesh PRE-SHARDED (leading dim split
                # across workers), so the step consumes them directly
                # instead of re-laying-out a replicated batch. The
                # prefetch queue dispatches each device_put one batch
                # early — the TRANSFER overlaps compute, but the host
                # gather itself is synchronous and stays in the fetch
                # phase (prefetch_to_device is a plain generator, no
                # worker thread)
                prefetched = prefetch_to_device(
                    _host_batches(), size=2,
                    device=batch_sharding(self.mesh, self.pcfg),
                    tracer=tr,  # h2d dispatch spans, nested under fetch
                )
                for batch_idx in range(steps_per_epoch):
                    if step_no >= t.max_steps:
                        # check BEFORE stepping so a --resume of a finished run
                        # is a no-op instead of overshooting max_steps
                        done = True
                        break
                    pw.before_step(step_no + 1, sync=self.state.params)
                    timer.reset()
                    with timer.phase("fetch"), tr.span(
                        "fetch", step=step_no + 1
                    ):
                        sharded = next(prefetched)
                    with timer.phase("step"):
                        with tr.span("dispatch", step=step_no + 1):
                            # traced per-window controller outputs, in
                            # the step's declared extras order: same
                            # compiled program for every value
                            extras = []
                            if self._adaptive is not None:
                                extras.append(
                                    np.int32(self._adaptive.count)
                                )
                            if self._precision is not None:
                                extras.append(np.asarray(
                                    self._precision.tags, np.int32
                                ))
                            self.state, metrics = self._train_step(
                                self.state, sharded, self._key, *extras
                            )
                        if self.faults is not None:
                            # injected host stall, inside the timed phase
                            # so the watchdog sees it as a real slow step
                            self.faults.maybe_sleep(step_no + 1)
                        if t.straggler_threshold_s is not None:
                            # the watchdog times real step walltime, not
                            # dispatch — an intentional per-step barrier,
                            # only when the watchdog is armed (the span
                            # observes the EXISTING barrier; tracing off
                            # or on, the sync set is identical)
                            with tr.span("sync", step=step_no + 1):
                                jax.block_until_ready(metrics)
                    step_no += 1
                    if self.faults is not None:
                        # injected preemption: SIGTERM ourselves at the
                        # planned step boundary; the installed handler
                        # raises the stop flag and _stop_consensus below
                        # turns it into a graceful checkpointed stop
                        self.faults.maybe_sigterm(step_no)
                    window_steps += 1
                    if self._adaptive is not None and step_no != first_step:
                        # the controller eats the same walltime the
                        # watchdog reads (real: its barrier is armed);
                        # the compile step is exempt like the watchdog's
                        self._adaptive.record(step_no, timer.total)
                    if self._precision is not None:
                        # pop BEFORE any window fetch/float-sweep sees
                        # it: bucket_sqnorm is a vector row among scalar
                        # metrics. The fetch is an intentional per-step
                        # sync, armed only with precision_adapt — the
                        # controller's telemetry, same opt-in cost shape
                        # as the watchdog's barrier (a few dozen floats).
                        self._precision.record(
                            step_no,
                            jax.device_get(  # psl: sync-ok
                                metrics.pop("bucket_sqnorm")
                            ),
                        )
                    # counts even with the watchdog's per-step barrier:
                    # block_until_ready syncs but never FETCHES, and the
                    # guard's host half (skip events + the abort) needs
                    # values — the backpressure block below is what keeps
                    # it live when log windows don't fetch
                    unsynced += 1
                    if (
                        t.straggler_threshold_s is not None
                        and timer.total > t.straggler_threshold_s
                        and step_no != first_step  # compilation step exempt
                    ):
                        # watchdog ACTION (not just a log line): count the
                        # event and emit a machine-readable record, so
                        # --mode's semantics are observable — dashboards /
                        # the analysis layer aggregate straggler_steps the
                        # way the reference's notebooks scraped worker
                        # time-cost distributions. (Killing is meaningless
                        # under SPMD: there is no per-worker process to
                        # kill; slow steps indicate input stalls or host
                        # interference instead.)
                        self.straggler_steps += 1
                        self._straggler_streak += 1
                        if self._straggler_streak < t.straggler_storm_n:
                            logger.warning(
                                "straggler step: Step: %d took %.4fs (threshold %.4fs)",
                                step_no,
                                timer.total,
                                t.straggler_threshold_s,
                            )
                            append_metrics_line(
                                t.metrics_file,
                                {
                                    "kind": "straggler",
                                    "step": step_no,
                                    "time_cost": round(timer.total, 6),
                                    "threshold": t.straggler_threshold_s,
                                },
                            )
                        elif self._straggler_streak == t.straggler_storm_n:
                            # escalation: N consecutive slow steps is one
                            # CONDITION, not N incidents — emit a single
                            # storm event and go quiet until it breaks
                            # (straggler_steps keeps counting throughout)
                            self.straggler_storms += 1
                            logger.warning(
                                "straggler storm: %d consecutive slow steps "
                                "(through step %d, threshold %.4fs) — "
                                "suppressing per-step warnings until it "
                                "clears",
                                self._straggler_streak,
                                step_no,
                                t.straggler_threshold_s,
                            )
                            append_metrics_line(
                                t.metrics_file,
                                {
                                    "kind": "straggler_storm",
                                    "step": step_no,
                                    "start_step": (
                                        step_no - t.straggler_storm_n + 1
                                    ),
                                    "consecutive": self._straggler_streak,
                                    "threshold": t.straggler_threshold_s,
                                },
                            )
                    elif t.straggler_threshold_s is not None:
                        # a fast step breaks the streak: if a storm was
                        # open, close its window (last slow step was the
                        # previous one)
                        self._maybe_end_storm(step_no - 1)
                        self._straggler_streak = 0
                    if t.log_interval > 0 and (
                        step_no % t.log_interval == 0 or step_no == 1
                    ):
                        # the once-per-window transfer: draining here makes
                        # the window walltime below include every in-flight
                        # step, so the per-step average stays honest.
                        # (time_cost is the authoritative per-step number;
                        # the Fetch/Forward fields remain raw host phase
                        # durations — with the watchdog disarmed, Forward
                        # is dispatch time, not compute.)
                        with tr.span("sync", step=step_no):
                            metrics = jax.device_get(metrics)  # psl: sync-ok
                        unsynced = 0
                        step_time = (
                            time.perf_counter() - window_t0
                        ) / max(window_steps, 1)
                        window_t0, window_steps = time.perf_counter(), 0
                        logger.info(
                            format_iter_line(
                                rank="mesh",
                                step=step_no,
                                epoch=epoch,
                                seen=batch_idx * global_batch,
                                total=total * self.pcfg.num_workers,
                                loss=float(metrics["loss"]),
                                time_cost=step_time,
                                fetch=timer.durations.get("fetch", 0.0),
                                forward=timer.durations.get("step", 0.0),
                            )
                        )
                        append_metrics_line(
                            t.metrics_file,
                            {
                                "kind": "train",
                                "step": step_no,
                                "epoch": epoch,
                                "time_cost": round(step_time, 6),
                                **{k: float(v) for k, v in metrics.items()},
                            },
                        )
                        # guard host half piggybacks on the window fetch:
                        # skip events + the consecutive-skip abort. Runs
                        # AFTER the window's train record lands (unlike
                        # the backpressure block below) so an aborting
                        # window is still in the JSONL
                        with tr.span("guard", step=step_no):
                            self._guard_check(metrics, step_no)
                        # the per-window flush: span I/O lands where the
                        # host already stalled on the device fetch above
                        tr.flush()
                    if unsynced >= max_unsynced:
                        # backpressure barrier + periodic fetch (reached
                        # when no log window fetched recently, e.g.
                        # log_interval=0 or very large): bounds dispatch
                        # run-ahead and keeps the guard abort live when
                        # logging is off — with the watchdog armed the
                        # buffers are already ready, so this is fetch-only
                        with tr.span("sync", step=step_no):
                            metrics = jax.device_get(metrics)  # psl: sync-ok
                        with tr.span("guard", step=step_no):
                            self._guard_check(metrics, step_no)
                        unsynced = 0
                    if (
                        t.save_checkpoints
                        # 0 = no periodic saves (the final checkpoint after
                        # the loop still writes; use save_checkpoints=False
                        # to suppress every write)
                        and t.eval_freq > 0
                        and step_no % t.eval_freq == 0
                    ):
                        # the span covers the host half (state gather +
                        # submit); the write itself is async
                        with tr.span("ckpt_save", step=step_no):
                            self._record_geometry(step_no)
                            self._ckpt.save(
                                self.state,
                                t.train_dir,
                                step_no,
                                compress=t.compress_checkpoints,
                            )
                        last_saved = step_no
                    if step_no >= t.max_steps:
                        done = True
                        break
                    if self._stop_consensus():
                        logger.warning(
                            "graceful stop at step %d (resume with --resume)",
                            step_no,
                        )
                        done = True
                        break
            if t.save_checkpoints and metrics and last_saved != step_no:
                with tr.span("ckpt_save", step=step_no):
                    self._record_geometry(step_no)
                    self._ckpt.save(
                        self.state,
                        t.train_dir,
                        step_no,
                        compress=t.compress_checkpoints,
                    )
        finally:
            pw.close(self.state.params)  # run ended (or raised) mid-window
            # drain the async writer even on error, so a submitted
            # checkpoint is durable (or its failure raised) before the
            # caller observes the outcome
            self._ckpt.wait()
            tr.flush()  # trailing partial window's spans
        out = {k: float(v) for k, v in metrics.items()}
        if out:
            # final drain of the guard's host half: a skip in a trailing
            # partial window (or a whole run shorter than log_interval)
            # still lands its grad_skip event in the JSONL. No abort —
            # the run is already over, the counter just needs reporting.
            self._guard_check(out, step_no, abort=False)
            # a storm still open at run end gets its closing event too
            self._maybe_end_storm(step_no)
        if self.straggler_steps:
            out["straggler_steps"] = float(self.straggler_steps)
            out["straggler_storms"] = float(self.straggler_storms)
        if self._adaptive is not None:
            out["agg_count"] = float(self._adaptive.count)
            out["mask_adaptations"] = float(self._adaptive.adaptations)
        if self._precision is not None:
            out["precision_adaptations"] = float(
                self._precision.adaptations
            )
            out["effective_wire_bytes"] = float(
                self._precision.effective_bytes()
            )
        return out

    # ---------------------------------------------------------------- validate
    def validate(self) -> dict:
        """Full pass over the test split (parity: nn_ops.py:90-106).

        Eval batches ride the same prefetch path as training: one batch
        in flight, landing on the mesh PRE-SPLIT across workers
        (batch_sharding) instead of single-device-then-redistribute —
        the transfer of batch k+1 overlaps the eval step on batch k."""
        t = self.tcfg
        n = self.pcfg.num_workers
        bs = max(t.test_batch_size // n, 1) * n
        it = BatchIterator(
            self.dataset.test_images,
            self.dataset.test_labels,
            bs,
            shuffle=False,
        )
        prefetched = prefetch_to_device(
            iter(it), size=2, device=batch_sharding(self.mesh, self.pcfg)
        )
        out = average_metrics(
            lambda b: self._eval_step(self.state, b), prefetched
        )
        if out:
            step_no = int(jax.device_get(self.state.step))
            logger.info(
                format_eval_line(step_no, out["loss"], out["prec1"], out["prec5"])
            )
            append_metrics_line(
                t.metrics_file, {"kind": "eval", "step": step_no, **out}
            )
        return out
