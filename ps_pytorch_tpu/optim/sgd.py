"""SGD with PyTorch update semantics, as an optax GradientTransformation.

Capability parity with the reference PS-side SGD
(/root/reference/src/optim/sgd.py:59-92), which applies — to the *already
aggregated* gradient — weight decay, heavy-ball momentum with dampening, and
optional Nesterov:

    d_p = g + weight_decay * p
    buf = d_p                                  (first step)
    buf = momentum * buf + (1-dampening) * d_p (later steps)
    d_p = d_p + momentum * buf   if nesterov else   buf
    p  -= lr * d_p

Note this is the PyTorch formulation (velocity NOT pre-multiplied by lr),
which differs from optax.sgd's trace — hence a bespoke transform. The
reference's first momentum step skips dampening (sgd.py:82-84: the buffer is
initialized to zeros then `buf.mul_(momentum).add_(d_p)`); we reproduce that
with a step counter.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Union

import chex
import jax
import jax.numpy as jnp
import optax


class SGDState(NamedTuple):
    count: chex.Array
    momentum_buffer: Optional[chex.ArrayTree]


ScalarOrSchedule = Union[float, optax.Schedule]


def _lr_at(lr: ScalarOrSchedule, count):
    return lr(count) if callable(lr) else lr


def _unwrap_vec(x):
    """(vector, rewrap) for a flat-update operand: a bare jnp vector
    passes through; a ``parallel.buckets.FlatVector`` (state_layout=
    "flat" master params/moments) contributes its padded buffer and a
    rewrap that preserves the static layout metadata."""
    from ..parallel.buckets import FlatVector  # lazy: optim stays light

    if isinstance(x, FlatVector):
        return x.flat, lambda v, _x=x: _x.replace(flat=v)
    return x, lambda v: v


def sgd(
    learning_rate: ScalarOrSchedule,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        # parity: sgd.py:51-52
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init_fn(params):
        buf = (
            jax.tree_util.tree_map(jnp.zeros_like, params) if momentum != 0 else None
        )
        return SGDState(count=jnp.zeros([], jnp.int32), momentum_buffer=buf)

    def update_fn(updates, state, params=None):
        if weight_decay != 0:
            if params is None:
                raise ValueError("weight_decay requires params")
            updates = jax.tree_util.tree_map(
                lambda g, p: g + weight_decay * p, updates, params
            )
        if momentum != 0:
            damp = jnp.where(state.count == 0, 0.0, dampening)
            buf = jax.tree_util.tree_map(
                lambda b, d: momentum * b + (1.0 - damp) * d,
                state.momentum_buffer,
                updates,
            )
            if nesterov:
                updates = jax.tree_util.tree_map(
                    lambda d, b: d + momentum * b, updates, buf
                )
            else:
                updates = buf
        else:
            buf = None
        lr = _lr_at(learning_rate, state.count)
        updates = jax.tree_util.tree_map(lambda d: -lr * d, updates)
        return updates, SGDState(count=state.count + 1, momentum_buffer=buf)

    return optax.GradientTransformation(init_fn, update_fn)


def sgd_flat(
    learning_rate: ScalarOrSchedule,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """``sgd()`` specialized to ONE flat f32 vector — the fused update
    path for ``PSConfig.state_layout="flat"``.

    Identical math, identical ``SGDState`` skeleton (so checkpoints are
    interchangeable with the tree transform), but weight decay, the
    momentum buffer, and Nesterov are straight whole-vector arithmetic
    with no per-leaf ``tree_map`` traversal: one elementwise chain over
    the padded flat buffer. Operands may be bare jnp vectors (the ZeRO-1
    per-shard update) or ``FlatVector``s (replicated flat state); the
    padding tail stays zero because a zero gradient produces a zero
    update (g=0, p_pad=0 => d_p=0 through every branch).

    Bit-exactness vs ``sgd()`` is pinned by
    tests/test_flat_state.py::test_flat_optimizers_bit_match_tree."""
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init_fn(params):
        v, wrap = _unwrap_vec(params)
        buf = wrap(jnp.zeros_like(v)) if momentum != 0 else None
        return SGDState(count=jnp.zeros([], jnp.int32), momentum_buffer=buf)

    def update_fn(updates, state, params=None):
        d, wrap = _unwrap_vec(updates)
        if weight_decay != 0:
            if params is None:
                raise ValueError("weight_decay requires params")
            p, _ = _unwrap_vec(params)
            d = d + weight_decay * p
        if momentum != 0:
            damp = jnp.where(state.count == 0, 0.0, dampening)
            b, _ = _unwrap_vec(state.momentum_buffer)
            buf = momentum * b + (1.0 - damp) * d
            d = d + momentum * buf if nesterov else buf
            new_buf = wrap(buf)
        else:
            new_buf = None
        lr = _lr_at(learning_rate, state.count)
        return wrap(-lr * d), SGDState(
            count=state.count + 1, momentum_buffer=new_buf
        )

    return optax.GradientTransformation(init_fn, update_fn)
