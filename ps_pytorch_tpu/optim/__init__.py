"""Optimizers with PyTorch update semantics (reference: src/optim/).

`build_optimizer` mirrors the reference's optimizer wiring: the PS constructs
`SGD(model.parameters(), lr, momentum)` (sync_replicas_master_nn.py:122-123)
and workers use torch.optim.SGD (distributed_worker.py:97); Adam/AMSGrad is the
in-tree alternative (src/optim/adam.py).
"""

from __future__ import annotations

import optax

from .adam import AdamState, adam, adam_flat
from .sgd import SGDState, sgd, sgd_flat

OPTIMIZER_REGISTRY = ("sgd", "adam", "amsgrad")


def build_optimizer(
    name: str,
    learning_rate,
    momentum: float = 0.9,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    flat: bool = False,
) -> optax.GradientTransformation:
    """``flat=True`` returns the whole-vector variant (sgd_flat/adam_flat)
    for ``PSConfig.state_layout="flat"`` — bit-identical math on the
    padded flat state, no per-leaf tree_map. The tree transforms also
    ACCEPT flat operands (a tree_map over one vector leaf is one vector
    op), so flat is an explicitness/efficiency choice, not a correctness
    requirement."""
    name = name.lower()
    if name == "sgd":
        make = sgd_flat if flat else sgd
        return make(
            learning_rate,
            momentum=momentum,
            dampening=dampening,
            weight_decay=weight_decay,
            nesterov=nesterov,
        )
    if name in ("adam", "amsgrad"):
        make = adam_flat if flat else adam
        return make(
            learning_rate,
            b1=b1,
            b2=b2,
            eps=eps,
            weight_decay=weight_decay,
            amsgrad=(name == "amsgrad"),
        )
    raise ValueError(f"unknown optimizer {name!r}; choose from {OPTIMIZER_REGISTRY}")
