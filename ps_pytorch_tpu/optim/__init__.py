"""Optimizers with PyTorch update semantics (reference: src/optim/).

`build_optimizer` mirrors the reference's optimizer wiring: the PS constructs
`SGD(model.parameters(), lr, momentum)` (sync_replicas_master_nn.py:122-123)
and workers use torch.optim.SGD (distributed_worker.py:97); Adam/AMSGrad is the
in-tree alternative (src/optim/adam.py).
"""

from __future__ import annotations

import optax

from .adam import AdamState, adam
from .sgd import SGDState, sgd

OPTIMIZER_REGISTRY = ("sgd", "adam", "amsgrad")


def build_optimizer(
    name: str,
    learning_rate,
    momentum: float = 0.9,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    name = name.lower()
    if name == "sgd":
        return sgd(
            learning_rate,
            momentum=momentum,
            dampening=dampening,
            weight_decay=weight_decay,
            nesterov=nesterov,
        )
    if name in ("adam", "amsgrad"):
        return adam(
            learning_rate,
            b1=b1,
            b2=b2,
            eps=eps,
            weight_decay=weight_decay,
            amsgrad=(name == "amsgrad"),
        )
    raise ValueError(f"unknown optimizer {name!r}; choose from {OPTIMIZER_REGISTRY}")
