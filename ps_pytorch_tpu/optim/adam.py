"""Adam (with optional AMSGrad) with PyTorch update semantics, as an optax
GradientTransformation.

Capability parity with the reference PS-side Adam
(/root/reference/src/optim/adam.py:38-95):

    g       = g + weight_decay * p
    m       = beta1 * m + (1-beta1) * g
    v       = beta2 * v + (1-beta2) * g^2
    v_hat   = max(v_hat, v)              (amsgrad only; denom uses v_hat)
    denom   = sqrt(v or v_hat) + eps     (NB: eps added AFTER sqrt, and the
                                          bias correction multiplies the step
                                          size, not the moments — both match
                                          torch, and differ from optax.adam)
    step_sz = lr * sqrt(1-beta2^t) / (1-beta1^t)
    p      -= step_sz * m / denom
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import optax

from .sgd import ScalarOrSchedule, _lr_at, _unwrap_vec


class AdamState(NamedTuple):
    count: chex.Array
    exp_avg: chex.ArrayTree
    exp_avg_sq: chex.ArrayTree
    max_exp_avg_sq: Optional[chex.ArrayTree]


def adam(
    learning_rate: ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
) -> optax.GradientTransformation:
    def init_fn(params):
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return AdamState(
            count=jnp.zeros([], jnp.int32),
            exp_avg=zeros(),
            exp_avg_sq=zeros(),
            max_exp_avg_sq=zeros() if amsgrad else None,
        )

    def update_fn(updates, state, params=None):
        if weight_decay != 0:
            if params is None:
                raise ValueError("weight_decay requires params")
            updates = jax.tree_util.tree_map(
                lambda g, p: g + weight_decay * p, updates, params
            )
        count = state.count + 1
        m = jax.tree_util.tree_map(
            lambda m_, g: b1 * m_ + (1 - b1) * g, state.exp_avg, updates
        )
        v = jax.tree_util.tree_map(
            lambda v_, g: b2 * v_ + (1 - b2) * g * g, state.exp_avg_sq, updates
        )
        if amsgrad:
            vmax = jax.tree_util.tree_map(jnp.maximum, state.max_exp_avg_sq, v)
            denom_tree = vmax
        else:
            vmax = None
            denom_tree = v
        c = count.astype(jnp.float32)
        bias1 = 1 - b1**c
        bias2 = 1 - b2**c
        step_size = _lr_at(learning_rate, state.count) * jnp.sqrt(bias2) / bias1
        new_updates = jax.tree_util.tree_map(
            lambda m_, d: -step_size * m_ / (jnp.sqrt(d) + eps), m, denom_tree
        )
        return new_updates, AdamState(
            count=count, exp_avg=m, exp_avg_sq=v, max_exp_avg_sq=vmax
        )

    return optax.GradientTransformation(init_fn, update_fn)


def adam_flat(
    learning_rate: ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
) -> optax.GradientTransformation:
    """``adam()`` specialized to ONE flat f32 vector — the fused update
    path for ``PSConfig.state_layout="flat"`` (see optim/sgd.sgd_flat).

    Same math, same ``AdamState`` skeleton; both moments (and the
    AMSGrad max) are whole vectors, so the entire update is one fused
    elementwise chain instead of a ``tree_map`` per leaf. The padding
    tail stays zero: g=0 keeps m=v=0 and the update term is
    ``-step * 0 / (sqrt(0) + eps) = 0``."""

    def init_fn(params):
        v, wrap = _unwrap_vec(params)
        zeros = lambda: wrap(jnp.zeros_like(v))
        return AdamState(
            count=jnp.zeros([], jnp.int32),
            exp_avg=zeros(),
            exp_avg_sq=zeros(),
            max_exp_avg_sq=zeros() if amsgrad else None,
        )

    def update_fn(updates, state, params=None):
        g, wrap = _unwrap_vec(updates)
        if weight_decay != 0:
            if params is None:
                raise ValueError("weight_decay requires params")
            p, _ = _unwrap_vec(params)
            g = g + weight_decay * p
        count = state.count + 1
        m_prev, _ = _unwrap_vec(state.exp_avg)
        v_prev, _ = _unwrap_vec(state.exp_avg_sq)
        m = b1 * m_prev + (1 - b1) * g
        v = b2 * v_prev + (1 - b2) * g * g
        if amsgrad:
            vmax_prev, _ = _unwrap_vec(state.max_exp_avg_sq)
            vmax = jnp.maximum(vmax_prev, v)
            denom = vmax
            new_vmax = wrap(vmax)
        else:
            denom = v
            new_vmax = None
        c = count.astype(jnp.float32)
        bias1 = 1 - b1**c
        bias2 = 1 - b2**c
        step_size = _lr_at(learning_rate, state.count) * jnp.sqrt(bias2) / bias1
        new_updates = -step_size * m / (jnp.sqrt(denom) + eps)
        return wrap(new_updates), AdamState(
            count=count, exp_avg=wrap(m), exp_avg_sq=wrap(v),
            max_exp_avg_sq=new_vmax,
        )

    return optax.GradientTransformation(init_fn, update_fn)
