"""LeNet in flax.linen (NHWC, TPU-native).

Capability parity with the reference LeNet (/root/reference/src/model_ops/lenet.py:16-37):
conv(1->20, 5x5, valid) -> maxpool 2x2 -> relu -> conv(20->50, 5x5, valid)
-> maxpool 2x2 -> relu -> flatten(800) -> fc(500) -> fc(num_classes).

The reference's `LeNetSplit` variant (lenet.py:39-254) exists only to hand-
pipeline per-layer gradient Isends over MPI; on TPU that overlap is XLA's job
(latency hiding of the psum), so there is deliberately no "split" model here —
see ps_pytorch_tpu/parallel/ps.py for where the equivalent capability lives.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class LeNet(nn.Module):
    """Classic LeNet for 28x28x1 inputs (MNIST). Matches lenet.py:16-37."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train  # no BN/dropout in LeNet; kept for a uniform model interface
        x = x.astype(self.dtype)
        x = nn.Conv(20, (5, 5), strides=(1, 1), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(50, (5, 5), strides=(1, 1), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(500, dtype=self.dtype)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
