"""Model factory — name-keyed, mirroring the reference's `build_model`
(/root/reference/src/util.py:8-19) but covering the full family list the
reference ships (src/model_ops/: LeNet, ResNet-18/34/50/101/152,
VGG-11/13/16/19 +/- BN; the reference factory only wires a subset of these).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from .lenet import LeNet
from .resnet import ResNet18, ResNet34, ResNet50, ResNet101, ResNet152
from .vgg import (
    vgg11, vgg11_bn, vgg13, vgg13_bn, vgg16, vgg16_bn, vgg19, vgg19_bn,
)

# Name -> constructor. Names match the reference CLI values (`--network`,
# util.py:10-19) with the extra depths the reference defines but never wires.
MODEL_REGISTRY = {
    "LeNet": LeNet,
    "ResNet18": ResNet18,
    "ResNet34": ResNet34,
    "ResNet50": ResNet50,
    "ResNet101": ResNet101,
    "ResNet152": ResNet152,
    "VGG11": vgg11_bn,     # reference maps "VGG11" -> vgg11_bn (util.py:18-19)
    "VGG11NoBN": vgg11,
    "VGG13": vgg13_bn,
    "VGG13NoBN": vgg13,
    "VGG16": vgg16_bn,
    "VGG16NoBN": vgg16,
    "VGG19": vgg19_bn,
    "VGG19NoBN": vgg19,
}

# Input spec per dataset: (H, W, C). LeNet expects MNIST shapes; everything
# else expects 32x32x3 CIFAR/SVHN shapes.
INPUT_SHAPES = {
    "LeNet": (28, 28, 1),
}
DEFAULT_INPUT_SHAPE = (32, 32, 3)


def build_model(
    model_name: str,
    num_classes: int = 10,
    dtype: Any = jnp.float32,
    bn_axis_name: Optional[str] = None,
    remat: bool = False,
):
    """Construct a model by CLI name (parity: util.py:8-19). `remat`
    enables per-block activation rematerialization (ResNet family only —
    LeNet/VGG are too shallow for it to matter)."""
    if model_name not in MODEL_REGISTRY:
        raise ValueError(
            f"unknown model {model_name!r}; choose from {sorted(MODEL_REGISTRY)}"
        )
    ctor = MODEL_REGISTRY[model_name]
    kwargs = dict(num_classes=num_classes, dtype=dtype)
    if model_name != "LeNet":
        kwargs["bn_axis_name"] = bn_axis_name
    if model_name.startswith("ResNet"):
        kwargs["remat"] = remat
    elif remat:
        raise ValueError(f"remat is only supported for the ResNet family, not {model_name!r}")
    return ctor(**kwargs)


def input_shape_for(model_name: str) -> Tuple[int, int, int]:
    return INPUT_SHAPES.get(model_name, DEFAULT_INPUT_SHAPE)


def init_model(model, rng: jax.Array, input_shape=None, batch_size: int = 2):
    """Initialize params (+ batch_stats if the model has BN).

    Returns ``(params, batch_stats)`` where ``batch_stats`` is an empty dict
    for BN-free models, so callers can treat every model uniformly.
    """
    if input_shape is None:
        input_shape = input_shape_for(type(model).__name__)
    x = jnp.zeros((batch_size,) + tuple(input_shape), jnp.float32)
    variables = model.init({"params": rng, "dropout": rng}, x, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return params, batch_stats


def apply_model(model, params, batch_stats, x, train: bool = False,
                dropout_rng: Optional[jax.Array] = None):
    """Uniform apply: returns (logits, new_batch_stats)."""
    variables = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
    rngs = {"dropout": dropout_rng} if dropout_rng is not None else None
    if train and batch_stats:
        logits, mutated = model.apply(
            variables, x, train=True, mutable=["batch_stats"], rngs=rngs
        )
        return logits, mutated["batch_stats"]
    logits = model.apply(variables, x, train=train, rngs=rngs)
    return logits, batch_stats


def param_count(params) -> int:
    return sum(int(jnp.size(p)) for p in jax.tree_util.tree_leaves(params))
