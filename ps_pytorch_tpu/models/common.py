"""Shared building blocks for the model zoo.

Centralizes the conv initializer and BatchNorm configuration so ResNet and VGG
cannot silently diverge. BN semantics follow the reference's PyTorch defaults
(torch BatchNorm2d momentum=0.1 -> flax momentum=0.9, eps=1e-5); `axis_name`
enables cross-replica (synced) BN, while the parity default (None) keeps stats
local per worker like the reference (distributed_worker.py:239-252).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn

he_normal = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")


def batch_norm(
    train: bool,
    dtype: Any,
    bn_axis_name: Optional[str] = None,
    **kwargs,
) -> nn.BatchNorm:
    return nn.BatchNorm(
        use_running_average=not train,
        momentum=0.9,
        epsilon=1e-5,
        dtype=dtype,
        axis_name=bn_axis_name,
        **kwargs,
    )
