"""Sequence-parallel transformer LM — the long-context model family.

The reference has no attention workloads (SURVEY.md section 5), so this
family has no counterpart to cite; it exists because long-context is a
first-class capability of this framework. The design splits the sequence
axis across the mesh (parallel/ring_attention.py): every non-attention op
(embed, norms, MLP) is pointwise over sequence and runs on local shards
with zero communication; attention is the ring. Params stay replicated, so
the PS data-parallel engine and the sequence axis compose on a 2-D mesh
(dp x sp) without re-sharding weights.

Pure init/apply (no flax.linen) so the module works identically inside and
outside shard_map.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.ring_attention import SEQ_AXIS, full_attention, ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    dim: int = 128
    depth: int = 2
    heads: int = 4
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    causal: bool = True
    dtype: Any = jnp.float32
    # rematerialize each block's activations in backward (jax.checkpoint):
    # trades ~1/3 more FLOPs for O(depth) -> O(1) activation memory, the
    # standard lever for long-context training
    remat: bool = False
    # rotate K/V both ways on the sequence ring (half the sequential hops,
    # both ICI directions of a physical ring) — see parallel/ring_attention
    bidirectional_ring: bool = False
    # sequence-parallel attention scheme: "ring" (K/V rotation, any head
    # count) or "ulysses" (two all_to_alls, heads % axis_size == 0) — see
    # parallel/ulysses.py for the trade-off
    sp_attention: str = "ring"
    # within-chip attention: "naive" (materializes [T, T]) or "flash"
    # (Pallas blockwise kernel, ops/flash_attention.py). Applies to ALL
    # paths: single-device/tp/pp/moe use it directly; sp "ring" switches
    # to ring_flash_attention (partial-triple kernel per hop, never
    # [T_loc, T_loc]; one-way or bidirectional) and sp "ulysses" runs it
    # on the gathered full-seq/local-heads layout
    attention_impl: str = "naive"
    # mixed precision: params/optimizer state stay `dtype` (keep f32 —
    # bf16 Adam moments are broken: bf16(0.999) == 1.0), while block
    # matmuls/attention run in `compute_dtype` (None = same as dtype).
    # Same convention as the CNN trainer's --dtype bfloat16.
    compute_dtype: Any = None

    @property
    def effective_compute_dtype(self):
        return self.compute_dtype if self.compute_dtype is not None else self.dtype

    @property
    def head_dim(self) -> int:
        assert self.dim % self.heads == 0
        return self.dim // self.heads


def _dense_init(key, shape, dtype, scale=None):
    fan_in = shape[0]
    scale = scale if scale is not None else 1.0 / (fan_in ** 0.5)
    return (jax.random.normal(key, shape) * scale).astype(dtype)


def init_transformer(cfg: TransformerConfig, key: jax.Array) -> Dict:
    keys = jax.random.split(key, 2 + cfg.depth)
    params = {
        "embed": (
            jax.random.normal(keys[0], (cfg.vocab_size, cfg.dim)) * 0.02
        ).astype(cfg.dtype),
        "pos_embed": (
            jax.random.normal(keys[1], (cfg.max_seq_len, cfg.dim)) * 0.02
        ).astype(cfg.dtype),
        "blocks": [],
        "out_norm": jnp.ones((cfg.dim,), cfg.dtype),
    }
    for i in range(cfg.depth):
        bk = jax.random.split(keys[2 + i], 6)
        mlp_dim = cfg.dim * cfg.mlp_ratio
        params["blocks"].append(
            {
                "ln1": jnp.ones((cfg.dim,), cfg.dtype),
                "wqkv": _dense_init(bk[0], (cfg.dim, 3 * cfg.dim), cfg.dtype),
                "wo": _dense_init(bk[1], (cfg.dim, cfg.dim), cfg.dtype),
                "ln2": jnp.ones((cfg.dim,), cfg.dtype),
                "w_up": _dense_init(bk[2], (cfg.dim, mlp_dim), cfg.dtype),
                "w_down": _dense_init(bk[3], (mlp_dim, cfg.dim), cfg.dtype),
            }
        )
    return params


def _rms_norm(x, gamma, eps=1e-6):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + eps) * gamma


def local_attention(cfg: TransformerConfig):
    """The within-chip attention callable for this config: the Pallas
    flash kernel or the naive jnp reference. Shared by the single-device,
    tensor-, pipeline-, and expert-parallel paths."""
    if cfg.attention_impl == "flash":
        from ..ops.flash_attention import flash_attention

        return partial(flash_attention, causal=cfg.causal)
    if cfg.attention_impl == "naive":
        return partial(full_attention, causal=cfg.causal)
    raise ValueError(f"unknown attention_impl {cfg.attention_impl!r}")


def select_attention(cfg: TransformerConfig, seq_axis_name: Optional[str] = None):
    """The attention callable for this config — the ONE selection point.

    seq_axis_name=None: within-chip (naive jnp or Pallas flash).
    Otherwise: the sequence-parallel scheme (cfg.sp_attention) over that
    mesh axis — ring (jnp, or flash-per-hop under attention_impl="flash")
    or Ulysses (a2a re-shard, local attention in cfg.attention_impl).
    Shared by the dense transformer (apply_transformer) and the MoE
    transformer (parallel/moe.apply_moe_transformer) so the dense and MoE
    paths can never diverge in attention math."""
    if seq_axis_name is None:
        return local_attention(cfg)
    if cfg.sp_attention == "ulysses":
        from ..parallel.ulysses import ulysses_attention

        return partial(
            ulysses_attention, axis_name=seq_axis_name, causal=cfg.causal,
            impl=cfg.attention_impl,
        )
    if cfg.sp_attention == "ring":
        if cfg.attention_impl == "flash":
            # flash INSIDE each ring hop: no [T_loc, T_loc] block ever
            # materializes (ops/flash_attention partial-triple kernels);
            # bidirectional_ring rotates K/V both ways, two triples/hop
            from ..parallel.ring_attention import ring_flash_attention

            return partial(
                ring_flash_attention,
                axis_name=seq_axis_name,
                causal=cfg.causal,
                bidirectional=cfg.bidirectional_ring,
            )
        return partial(
            ring_attention,
            axis_name=seq_axis_name,
            causal=cfg.causal,
            bidirectional=cfg.bidirectional_ring,
        )
    raise ValueError(f"unknown sp_attention {cfg.sp_attention!r}")


def transformer_block(cfg: TransformerConfig, x, blk, attend, mlp=None):
    """One pre-norm block: attention + GELU MLP, both residual.

    The single source of the block math — apply_transformer (below), the
    pipeline-parallel schedule (parallel/pp.py), and the MoE transformer
    (parallel/moe.py, via `mlp`) all run exactly this, so no parallel path
    can desynchronize from the oracle it is tested against.
    `attend` maps ([B,T,H,hd],)*3 -> [B,T,H,hd]; `mlp` (optional) replaces
    the dense GELU MLP, mapping the normed hidden [B,T,D] -> [B,T,D].
    """
    cd = cfg.effective_compute_dtype
    x = x.astype(cd)
    # cast weights at use, not at init: params (and grads/moments) keep
    # their storage dtype; only the block math runs in compute_dtype
    blk = {k: v.astype(cd) for k, v in blk.items()}
    b, t = x.shape[0], x.shape[1]
    h = _rms_norm(x, blk["ln1"])
    qkv = h @ blk["wqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split_heads = lambda a: a.reshape(b, t, cfg.heads, cfg.head_dim)
    o = attend(split_heads(q), split_heads(k), split_heads(v))
    x = x + o.reshape(b, t, cfg.dim) @ blk["wo"]
    h = _rms_norm(x, blk["ln2"])
    if mlp is not None:
        return x + mlp(h)
    return x + jax.nn.gelu(h @ blk["w_up"]) @ blk["w_down"]


def apply_transformer(
    cfg: TransformerConfig,
    params: Dict,
    tokens: jax.Array,  # int32 [B, T_local]
    seq_axis_name: Optional[str] = None,
    pos_offset: Optional[jax.Array] = None,
) -> jax.Array:
    """Forward -> logits [B, T_local, vocab].

    Under shard_map pass seq_axis_name: attention runs on the ring and
    positional embeddings index by GLOBAL position (shard offset). Outside
    shard_map (seq_axis_name=None) this is the plain single-device model.
    """
    b, t_loc = tokens.shape
    if seq_axis_name is not None:
        shard = jax.lax.axis_index(seq_axis_name) * t_loc
    else:
        shard = 0
    attend = select_attention(cfg, seq_axis_name)
    if pos_offset is not None:
        shard = shard + pos_offset
    pos = shard + jnp.arange(t_loc)
    x = params["embed"][tokens] + params["pos_embed"][pos][None]

    def block(x, blk):
        return transformer_block(cfg, x, blk, attend)

    if cfg.remat:
        block = jax.checkpoint(block)
    for blk in params["blocks"]:
        x = block(x, blk)

    cd = cfg.effective_compute_dtype
    xf = _rms_norm(x.astype(cd), params["out_norm"].astype(cd))
    return xf @ params["embed"].T.astype(cd)


def make_sp_forward(
    cfg: TransformerConfig,
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
    jit: bool = True,
):
    """Sequence-parallel forward: params replicated, tokens/logits sharded
    [B, T] / [B, T, V] along the sequence axis. This is the ONE place the
    sp sharding contract lives — pass jit=False to compose the mapped fn
    inside a larger jitted computation (e.g. a loss)."""
    mapped = jax.shard_map(
        lambda p, tok: apply_transformer(cfg, p, tok, seq_axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )
    return jax.jit(mapped) if jit else mapped
