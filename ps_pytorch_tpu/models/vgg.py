"""VGG family (CIFAR variant) in flax.linen (NHWC, TPU-native).

Capability parity with /root/reference/src/model_ops/vgg.py:15-108:
configurations A/B/D/E (VGG-11/13/16/19) with or without BatchNorm, and the
CIFAR-sized classifier head Dropout -> 512 -> ReLU -> Dropout -> 512 -> ReLU
-> num_classes. Conv weights use the reference's He/fan-out normal init
(vgg.py:33-36).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from .common import batch_norm, he_normal

# Configuration tables (vgg.py:62-68). 'M' = 2x2 max-pool.
CFGS = {
    "A": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "B": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "D": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"),
    "E": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512,
          "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    """VGG trunk + CIFAR classifier head (vgg.py:15-43)."""

    cfg: Sequence[Union[int, str]]
    batch_norm: bool = False
    num_classes: int = 10
    dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(
                    int(v), (3, 3), padding=1, dtype=self.dtype,
                    kernel_init=he_normal, bias_init=nn.initializers.zeros,
                )(x)
                if self.batch_norm:
                    x = batch_norm(
                        train=train, dtype=self.dtype, bn_axis_name=self.bn_axis_name
                    )(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def _vgg(cfg_key: str, batch_norm: bool, num_classes: int, **kw) -> VGG:
    return VGG(cfg=CFGS[cfg_key], batch_norm=batch_norm, num_classes=num_classes, **kw)


def vgg11(num_classes: int = 10, **kw):
    return _vgg("A", False, num_classes, **kw)


def vgg11_bn(num_classes: int = 10, **kw):
    return _vgg("A", True, num_classes, **kw)


def vgg13(num_classes: int = 10, **kw):
    return _vgg("B", False, num_classes, **kw)


def vgg13_bn(num_classes: int = 10, **kw):
    return _vgg("B", True, num_classes, **kw)


def vgg16(num_classes: int = 10, **kw):
    return _vgg("D", False, num_classes, **kw)


def vgg16_bn(num_classes: int = 10, **kw):
    return _vgg("D", True, num_classes, **kw)


def vgg19(num_classes: int = 10, **kw):
    return _vgg("E", False, num_classes, **kw)


def vgg19_bn(num_classes: int = 10, **kw):
    return _vgg("E", True, num_classes, **kw)
