"""CIFAR-style ResNet family in flax.linen (NHWC, TPU-native).

Capability parity with /root/reference/src/model_ops/resnet.py:14-113:
BasicBlock/Bottleneck CIFAR ResNets — 3x3 stem (no 7x7, no stem pool),
4 stages at 64/128/256/512 planes, 4x4 average-pool head, Linear classifier.
Depths: 18/34 (BasicBlock), 50/101/152 (Bottleneck).

TPU-first re-design decisions (not in the reference):
- NHWC layout, bf16 compute with f32 params (`dtype` attr) to target the MXU.
- BatchNorm via flax with optional `bn_axis_name` for cross-replica (synced)
  statistics. The reference never syncs BN stats across workers — each worker
  keeps local running stats and the master skips them during weight exchange
  (distributed_worker.py:239-252) — so `bn_axis_name=None` (local stats) is the
  parity default, and synced BN is an opt-in improvement.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from .common import batch_norm, he_normal


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (resnet.py:14-36). expansion = 1."""

    planes: int
    stride: int = 1
    dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    expansion: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, kernel_init=he_normal)
        norm = partial(
            batch_norm, train=train, dtype=self.dtype, bn_axis_name=self.bn_axis_name
        )
        out = conv(self.planes, (3, 3), strides=(self.stride, self.stride), padding=1)(x)
        out = nn.relu(norm()(out))
        out = conv(self.planes, (3, 3), padding=1)(out)
        out = norm()(out)
        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.expansion * self.planes:
            shortcut = conv(
                self.expansion * self.planes, (1, 1), strides=(self.stride, self.stride)
            )(x)
            shortcut = norm()(shortcut)
        return nn.relu(out + shortcut)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 residual block (resnet.py:39-64). expansion = 4."""

    planes: int
    stride: int = 1
    dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, kernel_init=he_normal)
        norm = partial(
            batch_norm, train=train, dtype=self.dtype, bn_axis_name=self.bn_axis_name
        )
        out = nn.relu(norm()(conv(self.planes, (1, 1))(x)))
        out = conv(self.planes, (3, 3), strides=(self.stride, self.stride), padding=1)(out)
        out = nn.relu(norm()(out))
        out = norm()(conv(self.expansion * self.planes, (1, 1))(out))
        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.expansion * self.planes:
            shortcut = conv(
                self.expansion * self.planes, (1, 1), strides=(self.stride, self.stride)
            )(x)
            shortcut = norm()(shortcut)
        return nn.relu(out + shortcut)


class ResNet(nn.Module):
    """CIFAR ResNet trunk (resnet.py:67-97).

    `remat=True` rematerializes each residual block's activations in the
    backward pass (flax nn.remat) — the deep Bottleneck variants at large
    batch trade ~1/3 extra FLOPs for activation memory that otherwise
    scales with depth."""

    block: Any
    num_blocks: Sequence[int]
    num_classes: int = 10
    dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(
            64, (3, 3), padding=1, use_bias=False, dtype=self.dtype, kernel_init=he_normal
        )(x)
        x = batch_norm(train=train, dtype=self.dtype, bn_axis_name=self.bn_axis_name)(x)
        x = nn.relu(x)
        block_cls = (
            nn.remat(self.block, static_argnums=(2,)) if self.remat else self.block
        )
        # explicit names: nn.remat renames the class (BasicBlock ->
        # CheckpointBasicBlock), which would silently re-key the param tree
        # and break checkpoint exchange between remat and non-remat runs
        block_idx = 0
        for stage, (planes, stride) in enumerate(
            zip((64, 128, 256, 512), (1, 2, 2, 2))
        ):
            for i in range(self.num_blocks[stage]):
                x = block_cls(
                    planes=planes,
                    stride=stride if i == 0 else 1,
                    dtype=self.dtype,
                    bn_axis_name=self.bn_axis_name,
                    name=f"{self.block.__name__}_{block_idx}",
                )(x, train)
                block_idx += 1
        x = nn.avg_pool(x, (4, 4), strides=(4, 4))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def ResNet18(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(block=BasicBlock, num_blocks=(2, 2, 2, 2), num_classes=num_classes, **kw)


def ResNet34(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(block=BasicBlock, num_blocks=(3, 4, 6, 3), num_classes=num_classes, **kw)


def ResNet50(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(block=Bottleneck, num_blocks=(3, 4, 6, 3), num_classes=num_classes, **kw)


def ResNet101(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(block=Bottleneck, num_blocks=(3, 4, 23, 3), num_classes=num_classes, **kw)


def ResNet152(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(block=Bottleneck, num_blocks=(3, 8, 36, 3), num_classes=num_classes, **kw)
