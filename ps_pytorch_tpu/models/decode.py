"""Autoregressive decoding with a KV cache for the transformer LM.

No reference counterpart (the reference is CNN-only); this completes the
LM family as a usable product: train (cli/train_lm) -> evaluate
(cli/evaluate_lm) -> generate (here).

Design is XLA-native: the cache is a pair of [B, max_len, H, hd] buffers
per block, written with `lax.dynamic_update_slice` at the current
position; the whole decode loop is ONE `lax.scan` over step indices
(static shapes, no Python control flow), so it compiles once for a given
(batch, max_len). Attention over the cache masks positions >= the current
length — exact equality with re-running the full forward is tested.

Sampling: greedy (temperature=0) or temperature sampling driven by a PRNG
key, both inside the scan.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import TransformerConfig, _rms_norm

NEG_INF = -1e30


def init_kv_cache(
    cfg: TransformerConfig, batch: int, max_len: Optional[int] = None
) -> Dict:
    """Zeroed [B, L, H, hd] K/V buffers per block (compute dtype)."""
    L = max_len or cfg.max_seq_len
    cd = cfg.effective_compute_dtype
    shape = (batch, L, cfg.heads, cfg.head_dim)
    return {
        "k": jnp.zeros((cfg.depth,) + shape, cd),
        "v": jnp.zeros((cfg.depth,) + shape, cd),
    }


def _attend_cached(q, k_cache, v_cache, length, scale):
    """q [B, 1, H, hd] against cache[:, :L]; positions >= length masked.

    length is a traced scalar (the number of valid cache slots, including
    the position q is at) or an int [B] vector of per-row lengths — the
    serving engine's continuous-batching pool (serve/engine.py) holds one
    independent sequence per row, each at its own position, while the
    single-request decode below passes the shared scalar pos + 1."""
    # f32 scores/softmax regardless of compute dtype — the same softmax-
    # statistics convention as full/ring/flash attention in training, so
    # bf16 decode cannot numerically diverge from the training forward.
    scores = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
        )
        * scale
    )  # [B,H,1,L] f32
    pos = jnp.arange(k_cache.shape[1])
    # scalar length broadcasts to [1,1,1,1]; a [B] vector to [B,1,1,1]
    length = jnp.reshape(jnp.asarray(length), (-1, 1, 1, 1))
    scores = jnp.where(pos[None, None, None, :] < length, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_cache.dtype), v_cache,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _decode_one(cfg: TransformerConfig, params: Dict, cache: Dict,
                token: jax.Array, pos: jax.Array,
                moe=None) -> Tuple[jax.Array, Dict]:
    """One token [B] at position pos -> (logits [B, V], updated cache).

    Block math comes from transformer_block (the single source — training
    and decoding cannot diverge); only `attend` differs: it writes this
    step's K/V into the stacked cache IN PLACE (one [depth,B,L,H,hd]
    dynamic_update_slice per block, no full-cache re-stack) and attends
    over the valid prefix. With `moe` (a MoEConfig), the block's MLP is
    the all-experts-local MoE mixture (single-device decode; capacity is
    made roomy so no decode token is ever dropped).
    """
    from .transformer import transformer_block

    cd = cfg.effective_compute_dtype
    x = (params["embed"][token] + params["pos_embed"][pos][None]).astype(cd)
    x = x[:, None]  # [B, 1, D]
    scale = 1.0 / (cfg.head_dim ** 0.5)
    k_buf, v_buf = cache["k"], cache["v"]

    roomy = None
    if moe is not None:
        import dataclasses as _dc

        # roomy capacity: B tokens/step must never drop in decode
        roomy = _dc.replace(moe, capacity_factor=float(moe.num_experts))

    for i, blk in enumerate(params["blocks"]):

        def attend(q, k, v, _i=i):
            nonlocal k_buf, v_buf
            k_buf = lax.dynamic_update_slice(
                k_buf, k.astype(k_buf.dtype)[None], (_i, 0, pos, 0, 0)
            )
            v_buf = lax.dynamic_update_slice(
                v_buf, v.astype(v_buf.dtype)[None], (_i, 0, pos, 0, 0)
            )
            return _attend_cached(q, k_buf[_i], v_buf[_i], pos + 1, scale)

        mlp = None
        if roomy is not None:
            from ..parallel.moe import moe_mlp_local

            def mlp(h, _blk=blk):
                out, _aux = moe_mlp_local(h, _blk, roomy, None)
                return out

        x = transformer_block(cfg, x, blk, attend, mlp=mlp)

    cache = {"k": k_buf, "v": v_buf}
    xf = _rms_norm(x[:, 0].astype(cd), params["out_norm"].astype(cd))
    logits = xf @ params["embed"].T.astype(cd)  # [B, V]
    return logits.astype(jnp.float32), cache


def prefill(cfg: TransformerConfig, params: Dict, prompt: jax.Array,
            cache: Dict, moe=None) -> Dict:
    """Populate the KV cache for ALL prompt positions in ONE batched
    forward (vs. the scan's one-token-at-a-time decode): the same
    transformer_block math, with `attend` wrapped to capture each block's
    full-prompt K/V before attending. Attention follows
    cfg.attention_impl, so a long prompt prefills through the Pallas
    flash kernel with O(T) memory.

    Returns the updated cache (positions [0, T_prompt) filled). Cache
    values are numerically equivalent (exact up to float reassociation)
    to what T_prompt single-token decode steps would have written — K/V
    depend only on each block's input activations, which the batched
    causal forward reproduces, though XLA may fuse/reorder the batched
    matmuls' reductions differently than the per-token path's.
    """
    from .transformer import select_attention, transformer_block

    b, t = prompt.shape
    cd = cfg.effective_compute_dtype
    pos = jnp.arange(t)
    x = (params["embed"][prompt] + params["pos_embed"][pos][None]).astype(cd)
    base_attend = select_attention(cfg, None)
    k_buf, v_buf = cache["k"], cache["v"]

    roomy = None
    if moe is not None:
        import dataclasses as _dc

        roomy = _dc.replace(moe, capacity_factor=float(moe.num_experts))

    for i, blk in enumerate(params["blocks"]):

        def attend(q, k, v, _i=i):
            nonlocal k_buf, v_buf
            k_buf = lax.dynamic_update_slice(
                k_buf, k.astype(k_buf.dtype)[None], (_i, 0, 0, 0, 0)
            )
            v_buf = lax.dynamic_update_slice(
                v_buf, v.astype(v_buf.dtype)[None], (_i, 0, 0, 0, 0)
            )
            return base_attend(q, k, v)

        mlp = None
        if roomy is not None:
            from ..parallel.moe import moe_mlp_local

            def mlp(h, _blk=blk):
                out, _aux = moe_mlp_local(h, _blk, roomy, None)
                return out

        x = transformer_block(cfg, x, blk, attend, mlp=mlp)

    return {"k": k_buf, "v": v_buf}


def generate(
    cfg: TransformerConfig,
    params: Dict,
    prompt: jax.Array,  # int32 [B, T_prompt]
    max_new_tokens: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    moe=None,
) -> jax.Array:
    """Generate greedily (temperature=0) or by temperature sampling.
    Pass `moe` (a MoEConfig) to decode a MoE checkpoint (all experts
    local, no-drop capacity).

    Returns int32 [B, T_prompt + max_new_tokens]. The prompt is PREFILLED
    in one batched forward (see `prefill` — flash-kernel-capable, exact
    vs single-token decode); the scan then covers only the last prompt
    token plus the generated region.
    """
    if not cfg.causal:
        # the KV-cache decode attends causally by construction
        # (_attend_cached masks pos >= length regardless of cfg.causal),
        # and the batched prefill follows cfg.causal — a non-causal config
        # would silently diverge between the two, so refuse it loudly
        raise ValueError("generate() is autoregressive: cfg.causal must be True")
    b, t_prompt = prompt.shape
    L = max_len or cfg.max_seq_len
    total = t_prompt + max_new_tokens
    if total > L:
        raise ValueError(f"prompt {t_prompt} + new {max_new_tokens} > {L}")
    if temperature > 0 and key is None:
        raise ValueError("temperature sampling needs a PRNG key")
    key = key if key is not None else jax.random.key(0)

    cache0 = init_kv_cache(cfg, b, L)
    if t_prompt > 1:
        # batched prefill of positions [0, t_prompt-1); the final prompt
        # token goes through the ordinary decode step below, which both
        # writes its K/V and produces the first generated token
        cache0 = prefill(cfg, params, prompt[:, : t_prompt - 1], cache0,
                         moe=moe)
    # tokens buffer holds the prompt then generated ids
    buf0 = jnp.zeros((b, total), jnp.int32).at[:, :t_prompt].set(prompt)

    def step(carry, pos):
        buf, cache, k = carry
        token = buf[:, pos]  # current input token
        logits, cache = _decode_one(cfg, params, cache, token, pos, moe=moe)
        k, ks = jax.random.split(k)
        if temperature > 0:
            nxt = jax.random.categorical(ks, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        # write the prediction at pos+1 ONLY in the generation region
        # (prompt positions keep their given tokens — teacher forcing)
        write = pos + 1 >= t_prompt
        nxt = jnp.where(write, nxt, buf[:, jnp.minimum(pos + 1, total - 1)])
        buf = lax.dynamic_update_slice(
            buf, nxt[:, None].astype(jnp.int32), (0, pos + 1)
        )
        return (buf, cache, k), None

    (buf, _, _), _ = lax.scan(
        step, (buf0, cache0, key), jnp.arange(t_prompt - 1, total - 1)
    )
    return buf


def make_generate(cfg: TransformerConfig, max_new_tokens: int,
                  temperature: float = 0.0, max_len: Optional[int] = None,
                  moe=None):
    """Jitted generate: (params, prompt [B, T], key) -> [B, T + new].
    Pass `moe` for MoE checkpoints (same contract as generate)."""
    def fn(params, prompt, key):
        return generate(
            cfg, params, prompt, max_new_tokens,
            temperature=temperature, key=key, max_len=max_len, moe=moe,
        )

    return jax.jit(fn)
