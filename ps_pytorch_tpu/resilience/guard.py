"""Device-side non-finite gradient guard state + helpers.

The guard lives INSIDE the jitted PS train step (parallel/ps.py): each
worker reduces its gradient leaves to one all-finite flag, a single
int32 ``lax.pmin`` agrees on it mesh-wide (4 bytes on the wire, no host
transfer), and the whole state update is selected against the flag —
a bad step applies the identity instead of the optimizer. Under
``PSConfig.state_layout="flat"`` that rollback is a ``jnp.where`` over a
handful of whole flat vectors (params + each optimizer moment ride as
single padded buffers) instead of one select per pytree leaf — the
select itself is the same tree_map either way. Counters are
carried in ``GuardState`` (part of PSTrainState, so they checkpoint and
resume) and surfaced through the metrics dict the host already fetches
once per log window, so a healthy run pays zero extra host syncs.

Dynamic loss scaling (``PSConfig.dynamic_loss_scale``) rides the same
state: the loss is multiplied by ``scale`` before backprop and the
gradients divided by it after, the scale backs off 2x on every skipped
(overflowed) step and grows 2x after ``loss_scale_growth_interval``
consecutive good steps — the standard AMP recipe, aimed here at the int8
compression schemes whose wire range is the tightest.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp

# dynamic loss scale bounds: backing off below 1.0 would silently shrink
# healthy gradients; growing past 2^24 adds nothing once f32 headroom is
# exhausted
MIN_LOSS_SCALE = 1.0
MAX_LOSS_SCALE = float(2 ** 24)


@flax.struct.dataclass
class GuardState:
    """Per-run guard counters, replicated on the mesh and checkpointed.

    ``skipped``: total steps skipped (non-finite gradients somewhere on
    the mesh); ``consec``: current skip streak (the host aborts when it
    crosses TrainConfig.max_consecutive_skips); ``good``: current streak
    of finite steps (drives loss-scale growth); ``scale``: the live loss
    scale (1.0 when dynamic scaling is off); ``dyn``: 1 iff dynamic loss
    scaling was ON when this state was produced — checkpoint restore
    needs it to tell a dynamic-off scale of 1.0 apart from a dynamic run
    that legitimately backed off to MIN_LOSS_SCALE (both store 1.0, but
    only the former should re-init to loss_scale_init on a
    --dynamic-loss-scale resume)."""

    skipped: jax.Array
    consec: jax.Array
    good: jax.Array
    scale: jax.Array
    dyn: jax.Array


def init_guard_state(
    loss_scale: float = 1.0, dynamic: bool = False
) -> GuardState:
    return GuardState(
        skipped=jnp.zeros([], jnp.int32),
        consec=jnp.zeros([], jnp.int32),
        good=jnp.zeros([], jnp.int32),
        scale=jnp.asarray(loss_scale, jnp.float32),
        dyn=jnp.asarray(int(dynamic), jnp.int32),
    )


def reconcile_guard_state(stored: dict, fresh: dict) -> dict:
    """Merge a checkpointed guard-state dict into the current config's
    fresh one (both flax state-dicts); checkpoint.py calls this for the
    resettable ``guard_state`` field so the persistence layer stays
    ignorant of GuardState's field names and migration rules.

    Stored counters win — but the live loss scale is MATH once dynamic
    scaling is on: a dynamic-OFF checkpoint (dyn flag 0) resumed with
    --dynamic-loss-scale must start from the target's init instead of
    regrowing from 1.0 over ~growth_interval*log2(init) steps. The dyn
    flag (not scale==1.0) decides, so a dynamic run that legitimately
    backed off to MIN_LOSS_SCALE keeps its 1.0. The flag itself always
    reflects the CURRENT config."""
    sd, td = stored.get("dyn"), fresh.get("dyn")
    if sd is not None and td is not None:
        if int(td) == 1 and int(sd) == 0:
            stored["scale"] = fresh.get("scale")
        stored["dyn"] = td
    return stored


def tree_all_finite(tree: Any) -> jax.Array:
    """Scalar bool: every element of every leaf is finite (no NaN/Inf).

    One fused reduction per leaf; the cross-leaf AND is a handful of
    scalar ops — noise next to the backward pass it guards."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    flags = [jnp.all(jnp.isfinite(l)) for l in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return out


def update_guard_state(
    g: GuardState,
    finite: jax.Array,
    dynamic_loss_scale: bool,
    growth_interval: int,
) -> GuardState:
    """Pure device-side counter/scale update for one step.

    grow-on-success / back-off-on-overflow: a skipped step halves the
    scale (floored at MIN_LOSS_SCALE); ``growth_interval`` consecutive
    good steps double it (capped at MAX_LOSS_SCALE) and restart the good
    streak."""
    bad = (~finite).astype(jnp.int32)
    good1 = jnp.where(finite, g.good + 1, 0)
    if dynamic_loss_scale:
        do_grow = jnp.logical_and(finite, good1 >= growth_interval)
        grown = jnp.where(
            do_grow, jnp.minimum(g.scale * 2.0, MAX_LOSS_SCALE), g.scale
        )
        scale = jnp.where(
            finite, grown, jnp.maximum(g.scale * 0.5, MIN_LOSS_SCALE)
        )
        good1 = jnp.where(do_grow, 0, good1)
    else:
        scale = g.scale
    return GuardState(
        skipped=g.skipped + bad,
        consec=jnp.where(finite, 0, g.consec + 1),
        good=good1,
        scale=scale,
        dyn=g.dyn,
    )
