"""Elastic membership: resume-reshape across mesh geometries + adaptive
partial aggregation.

The reference PS is married twice over to the cluster it started on: the
mpirun hostfile fixes the worker count for the life of the run, and the
``--num-aggregate`` backup-worker knob is a constant chosen before the
first straggler ever shows up. This module removes both bindings:

1. **Resume-reshape** (``MeshGeometry`` / ``reshape_raw_state``): a
   checkpoint written on an N-worker mesh restores onto an M-worker mesh
   — shrink or grow, replicated or ZeRO-1-sharded optimizer placement,
   any ``bucket_bytes``/``quant_block_size`` carving. The interchange
   format is the replicated TREE shape (exactly what checkpoints already
   store for params, PR 5's layout-portability rule); everything
   worker-count-dependent is canonicalized into it on load and
   re-specialized into the target geometry:

   - **params**: tree-shaped in the file already (``FlatVector``
     serialization handlers) — untouched, bit-exact by construction.
   - **optimizer moments**, ZeRO-1: the stacked ``[N, shard]`` rows are
     the workers' per-bucket regions of one padded flat vector
     (``ps._worker_region``); inverting that layout and re-carving under
     the target's ``BucketPlan`` is a pure rearrangement of the same f32
     bits, so moments are BIT-EXACT across N→M and across
     replicated↔sharded switches (the padding tails are zeros on both
     sides).
   - **error-feedback residuals**: per-worker state with no meaningful
     identity on a different mesh. Re-distributed SUM-PRESERVINGLY: the
     total residual mass (what EF will eventually add back to the
     update) is conserved — each of the M workers gets total/M — but
     the per-worker rows are NOT bit-preserved. Exact conservation when
     M is a power of two (f32 division by 2^k is lossless); otherwise
     conserved to f32 rounding. This is the documented exception.
   - **BatchNorm stats**, ``bn_mode="local"``: per-worker stacked stats
     are averaged and broadcast to the new mesh — the same "stats are
     statistics, not math" stance the reference takes by never syncing
     them. Documented exception: not bit-preserved under N≠M.
   - **guard counters / step**: mesh-size-free, pass through (the
     RESETTABLE merge in checkpoint.py still applies afterwards).

   The source geometry comes from a tiny ``elastic.json`` manifest the
   trainer drops next to its checkpoints (`save_geometry`, per-step
   entries — an elastically-resumed dir holds MIXED-geometry files); a
   dir without one (pre-elastic runs) resumes fine on the SAME geometry
   and fails with an actionable error on a changed one — except the one
   change shapes cannot catch, a ZeRO-1 bucket/quant re-carving (same
   stacked shapes, permuted worker→region mapping), for which the
   trainer warns that the carving is unverifiable.

2. **Adaptive partial aggregation** (``AdaptiveMaskController``): the
   static pre-psum mask generalized to ACE-Sync-style adaptive sync.
   With ``PSConfig.num_aggregate_min/max`` set, the compiled train step
   takes a traced int32 count (no retrace on change) and this host-side
   controller picks next window's count from the straggler watchdog's
   per-step walltimes: a window containing slow steps shrinks the count
   (one per slow step, floored at min — stop waiting for stragglers),
   a clean window grows it back by one (ceilinged at max). Every change
   emits a ``mask_adapt`` JSONL event. Full-count windows are bit-exact
   against the static ``num_aggregate=None`` path (mask of exactly 1.0,
   denominator exactly N); partial counts that are not powers of two
   may differ from the equivalent static config by 1 ULP (XLA
   strength-reduces division by a static constant; the traced
   denominator is a true divide).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Optional

import numpy as np

logger = logging.getLogger("ps_pytorch_tpu")

GEOMETRY_FILE = "elastic.json"
GEOMETRY_VERSION = 1

# the MeshGeometry fields that decide state SHAPES/LAYOUT (needs_reshape
# reads these; dcn_hosts is recorded for the record but collective
# routing never changes what a checkpoint stores)
_SHAPE_FIELDS = (
    "num_workers", "opt_placement", "bucket_bytes", "quant_block_size",
    "compress", "error_feedback", "bn_mode",
)


@dataclasses.dataclass(frozen=True)
class MeshGeometry:
    """Everything about a run's mesh/placement that decides the SHAPES
    of its checkpointed state (the trainer's ``elastic.json`` manifest).
    ``state_layout`` rides along for the record but never matters:
    checkpoints are tree-shaped at the boundary in both layouts."""

    num_workers: int
    opt_placement: str = "replicated"
    bucket_bytes: Optional[int] = None
    quant_block_size: int = 0
    compress: Optional[str] = None
    error_feedback: bool = False
    bn_mode: str = "pmean"
    state_layout: str = "flat"
    dcn_hosts: int = 1

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["version"] = GEOMETRY_VERSION
        return d

    @classmethod
    def from_json(cls, d: dict) -> "MeshGeometry":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def geometry_of(cfg) -> MeshGeometry:
    """The manifest entry for a live PSConfig."""
    return MeshGeometry(
        num_workers=cfg.num_workers,
        opt_placement=cfg.opt_placement,
        bucket_bytes=cfg.bucket_bytes,
        quant_block_size=cfg.quant_block_size,
        compress=None if cfg.compress in (None, "none") else cfg.compress,
        error_feedback=cfg.error_feedback,
        bn_mode=cfg.bn_mode,
        state_layout=cfg.state_layout,
        dcn_hosts=cfg.dcn_hosts,
    )


def save_geometry(model_dir: str, geom: MeshGeometry,
                  step: Optional[int] = None) -> str:
    """Atomically write/merge the manifest (call from the writer process
    only; the trainer gates on process_index() == 0 like checkpoint
    writes).

    The top-level fields describe the dir's LATEST writer; ``step``
    additionally records the geometry under ``steps[str(step)]``. The
    per-step map matters because an elastically-resumed dir holds
    checkpoints from MIXED geometries (step 3 written on 8 workers,
    step 6 on 4): a corrupt-newest fallback that restores the older
    file must reshape by the geometry that wrote THAT file — the
    latest-writer entry would mislabel it, loudly for shape-changing
    differences, silently for a ZeRO-1 bucket-carving-only change."""
    os.makedirs(model_dir, exist_ok=True)
    path = os.path.join(model_dir, GEOMETRY_FILE)
    data = geom.to_json()
    steps = {}
    if os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                steps = json.load(f).get("steps", {}) or {}
        except (OSError, ValueError):
            steps = {}  # a torn manifest must not fail the save
    if step is not None:
        steps[str(step)] = geom.to_json()
    if steps:
        data["steps"] = steps
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_geometry(model_dir: str,
                  step: Optional[int] = None) -> Optional[MeshGeometry]:
    """The geometry that wrote checkpoint ``step`` (``step=None``: the
    dir's latest writer), or None when it cannot be known.

    None in two honest cases: no manifest (a pre-elastic dir), and a
    ``step`` with no per-step record — such a step was written BEFORE
    per-step tracking, so the latest-writer entry would be a guess, and
    guessing wrong on a ZeRO-1 carving is silent moment-scrambling; the
    caller's manifest-less path (restore unreshaped + warn) is strictly
    safer. A torn/unreadable manifest also returns None: resume's
    contract is quarantine-and-fall-back, and the manifest must never be
    the file that bricks it (the checkpoint CRC still guards the state
    itself)."""
    path = os.path.join(model_dir, GEOMETRY_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if step is not None:
            entry = (data.get("steps") or {}).get(str(step))
            return None if entry is None else MeshGeometry.from_json(entry)
        return MeshGeometry.from_json(data)
    except (OSError, ValueError, TypeError) as e:
        logger.warning(
            "elastic manifest %s is unreadable (%s); treating the dir "
            "as manifest-less", path, e,
        )
        return None


def needs_reshape(src: MeshGeometry, dst: MeshGeometry) -> bool:
    """Would a checkpoint written under ``src`` mis-load (wrong shapes OR
    silently wrong region mapping) into a ``dst``-geometry state?

    The subtle case: the ZeRO-1 stacked ``[n, shard]`` moment SHAPE does
    not depend on ``bucket_bytes`` (carving never changes the padded
    total), but the worker→region MAPPING does — a bucket_bytes change
    under the sharded placement loads cleanly and trains on scrambled
    moments. Reshape routes on the mapping, not just the shape."""
    if src.opt_placement != dst.opt_placement:
        return True
    n_changed = src.num_workers != dst.num_workers
    if src.opt_placement == "sharded":
        if n_changed:
            return True
        if (src.bucket_bytes or 0) != (dst.bucket_bytes or 0):
            return True
        if _quant_block(src) != _quant_block(dst):
            return True
    if n_changed and (src.error_feedback or dst.error_feedback):
        return True
    src_local = src.bn_mode == "local"
    dst_local = dst.bn_mode == "local"
    if src_local != dst_local or (n_changed and src_local):
        return True
    return False


# ------------------------------------------------------------ geometry math

def _quant_block(geom: MeshGeometry) -> int:
    if geom.compress in ("int8", "int8_2round") and geom.quant_block_size:
        return geom.quant_block_size
    return 1


def _ps_config(geom: MeshGeometry):
    """A PSConfig carrying this geometry, so the bucket plans come from
    THE engine's own ``_sharded_plan``/``wire_align`` — the reshape can
    never desync from the carving the live run used. Lazy import:
    parallel.ps imports resilience.guard, so a module-level import here
    would cycle through the package __init__."""
    from ..parallel.ps import PSConfig

    return PSConfig(
        num_workers=geom.num_workers,
        opt_placement=geom.opt_placement,
        bucket_bytes=geom.bucket_bytes,
        quant_block_size=geom.quant_block_size,
        compress=geom.compress,
        error_feedback=geom.error_feedback,
        bn_mode=geom.bn_mode,
        state_layout=geom.state_layout,
    )


def _sharded_plan(geom: MeshGeometry, total: int):
    from ..parallel.ps import _sharded_plan as plan

    return plan(_ps_config(geom), total)


def _regions_to_flat(stacked: np.ndarray, plan, n: int) -> np.ndarray:
    """Invert ``ps._worker_region``: stacked per-worker rows (each row =
    that worker's 1/n slice of every bucket, concatenated in bucket
    order) back into the one padded flat vector. Pure bit rearrangement."""
    flat = np.zeros((plan.padded_total,), np.asarray(stacked).dtype)
    off = 0
    for start, size in zip(plan.starts, plan.sizes):
        s = size // n
        for w in range(n):
            flat[start + w * s:start + (w + 1) * s] = stacked[w, off:off + s]
        off += s
    return flat


def _flat_to_regions(flat: np.ndarray, plan, n: int) -> np.ndarray:
    """``ps._worker_region`` for all workers at once, host-side."""
    out = np.empty((n, plan.padded_total // n), np.asarray(flat).dtype)
    off = 0
    for start, size in zip(plan.starts, plan.sizes):
        s = size // n
        for w in range(n):
            out[w, off:off + s] = flat[start + w * s:start + (w + 1) * s]
        off += s
    return out


def _tree_template(layout, length: int):
    from ..parallel.buckets import _np_flat_to_tree

    return _np_flat_to_tree(layout, np.zeros((length,), np.float32))


def _dict_to_flat(state_dict, layout, plan) -> np.ndarray:
    """Tree-shaped nested dict (the canonical interchange form) -> one
    padded flat vector in ``plan``'s geometry."""
    from flax import serialization

    from ..parallel.buckets import _np_tree_to_flat

    tree = serialization.from_state_dict(
        _tree_template(layout, plan.padded_total), state_dict
    )
    return _np_tree_to_flat(layout, plan, tree)


def _flat_to_dict(flat: np.ndarray, layout):
    """Padded (or exactly-total) flat vector -> tree-shaped nested dict."""
    from flax import serialization

    from ..parallel.buckets import _np_flat_to_tree

    return serialization.to_state_dict(_np_flat_to_tree(layout, flat))


# ------------------------------------------------------- opt_state reshape

def _opt_to_canonical(node, src_plan, n: int, layout):
    """Walk a stored ZeRO-1 opt_state dict: every stacked ``[n, shard]``
    moment becomes a tree-shaped dict (bit-exact region inversion), every
    stacked ``[n]`` scalar (optax step counts — identical on every
    worker by construction) collapses to row 0."""
    if node is None:
        return None
    if isinstance(node, dict):
        return {
            k: _opt_to_canonical(v, src_plan, n, layout)
            for k, v in node.items()
        }
    arr = np.asarray(node)
    shard = src_plan.padded_total // n
    if arr.ndim == 2 and arr.shape == (n, shard):
        return _flat_to_dict(_regions_to_flat(arr, src_plan, n), layout)
    if arr.ndim == 1 and arr.shape[0] == n:
        return arr[0]
    return node


def _opt_from_canonical(canon, tgt_node, dst_plan, m: int, layout):
    """Walk the TARGET's (fresh ZeRO-1) opt_state dict in parallel with
    the canonical form: tree-shaped moments are flattened and carved
    into the target's stacked regions, scalars broadcast to ``[m]``."""
    if tgt_node is None:
        return None
    if isinstance(tgt_node, dict):
        if not isinstance(canon, dict) or set(tgt_node) - set(canon):
            # same loud error for a non-dict AND for missing keys (e.g.
            # an sgd checkpoint resumed onto an adam target lacks
            # mu/nu): letting None fall through would surface as an
            # obscure flax structure crash or an object-dtype array
            raise ValueError(
                "elastic reshape: checkpointed optimizer state does not "
                "match the target optimizer's structure — resume with the "
                "same --optimizer the checkpoint was written with"
            )
        return {
            k: _opt_from_canonical(canon[k], tgt_node[k], dst_plan, m,
                                   layout)
            for k in tgt_node
        }
    tarr = np.asarray(tgt_node)
    shard = dst_plan.padded_total // m
    if tarr.ndim == 2 and tarr.shape == (m, shard):
        return _flat_to_regions(_dict_to_flat(canon, layout, dst_plan),
                                dst_plan, m)
    if tarr.ndim == 1 and tarr.shape[0] == m:
        return np.broadcast_to(np.asarray(canon), (m,)).copy()
    return canon


# ------------------------------------------------------ EF residual reshape

def _ef_to_canonical(raw_comm, src: MeshGeometry, layout):
    """Per-worker residual state -> ONE tree-shaped total-residual dict
    (sum over workers: the mass EF owes the next update)."""
    if src.opt_placement == "sharded":
        arr = np.asarray(raw_comm, np.float32)  # [n, padded_total_src]
        return _flat_to_dict(arr.sum(axis=0), layout)

    def leaf_sum(node):
        if isinstance(node, dict):
            return {k: leaf_sum(v) for k, v in node.items()}
        return np.asarray(node, np.float32).sum(axis=0)

    return leaf_sum(raw_comm)


def _ef_from_canonical(canon, dst: MeshGeometry, layout):
    """Total residual -> per-worker rows of total/M (sum-preserving; the
    per-worker split is NOT bit-preserved — documented exception)."""
    m = dst.num_workers
    if dst.opt_placement == "sharded":
        total = layout.total
        plan = _sharded_plan(dst, total)
        flat = _dict_to_flat(canon, layout, plan) / np.float32(m)
        return np.tile(flat[None, :], (m, 1))

    def leaf_rows(node):
        if isinstance(node, dict):
            return {k: leaf_rows(v) for k, v in node.items()}
        leaf = np.asarray(node, np.float32) / np.float32(m)
        return np.broadcast_to(leaf, (m,) + leaf.shape).copy()

    return leaf_rows(canon)


# ---------------------------------------------------------- bn-stats reshape

def _bn_to_canonical(raw_bs, local: bool):
    if not local:
        return raw_bs

    def leaf_mean(node):
        if isinstance(node, dict):
            return {k: leaf_mean(v) for k, v in node.items()}
        return np.asarray(node).mean(axis=0)

    return leaf_mean(raw_bs)


def _bn_from_canonical(canon, local: bool, m: int):
    if not local:
        return canon

    def leaf_stack(node):
        if isinstance(node, dict):
            return {k: leaf_stack(v) for k, v in node.items()}
        arr = np.asarray(node)
        return np.broadcast_to(arr, (m,) + arr.shape).copy()

    return leaf_stack(canon)


# --------------------------------------------------------------- entry point

def reshape_raw_state(raw: dict, src: MeshGeometry, dst_cfg, target) -> dict:
    """Transform a raw checkpoint state dict written under ``src`` into
    one loadable by ``checkpoint.restore_from_raw(target, ...)`` for a
    run configured as ``dst_cfg`` (a PSConfig), where ``target`` is the
    freshly-initialized host-side PSTrainState for the NEW geometry.

    params/step/guard_state pass through untouched (tree-shaped and
    mesh-size-free respectively); opt_state moments are bit-exact
    rearrangements; EF residuals and local BN stats are re-distributed
    (see module docstring for exactly what is and is not bit-preserved).
    """
    from flax import serialization

    from ..parallel.buckets import FlatVector, tree_layout

    dst = geometry_of(dst_cfg)
    if isinstance(target.params, FlatVector):
        layout = target.params.layout
    else:
        layout = tree_layout(target.params)
    out = dict(raw)

    # ---- optimizer moments (bit-exact across every geometry change)
    opt_raw = raw.get("opt_state")
    if opt_raw is not None:
        canon = opt_raw
        if src.opt_placement == "sharded":
            src_plan = _sharded_plan(src, layout.total)
            canon = _opt_to_canonical(
                opt_raw, src_plan, src.num_workers, layout
            )
        if dst.opt_placement == "sharded":
            dst_plan = _sharded_plan(dst, layout.total)
            tgt_opt = serialization.to_state_dict(target.opt_state)
            canon = _opt_from_canonical(
                canon, tgt_opt, dst_plan, dst.num_workers, layout
            )
        out["opt_state"] = canon

    # ---- error-feedback residuals (sum-preserving re-distribution);
    # present-vs-disabled mismatches are left for restore_from_raw's
    # existing loud config errors. Redistribute ONLY when worker
    # identity is actually lost (N or placement changed): the residual
    # rows are indexed by worker × flat position — replicated rows are
    # per-leaf and the sharded rows are FULL padded vectors, never
    # region-carved — so a bucket-carving-only (or bn-only) reshape
    # keeps every worker's accumulated residual bit-exact for free.
    comm = raw.get("comm_state")
    if comm is not None and target.comm_state is not None:
        identity_kept = (
            src.num_workers == dst.num_workers
            and src.opt_placement == dst.opt_placement
            and (
                src.opt_placement != "sharded"
                or _sharded_plan(src, layout.total).padded_total
                == _sharded_plan(dst, layout.total).padded_total
            )
        )
        if not identity_kept:
            out["comm_state"] = _ef_from_canonical(
                _ef_to_canonical(comm, src, layout), dst, layout
            )

    # ---- BatchNorm stats (mean/broadcast for the local mode) — same
    # identity rule as EF: per-worker stacked stats survive any reshape
    # that keeps N and locality (e.g. a ZeRO-1 carving-only change);
    # averaging them there would discard accumulated running stats for
    # nothing
    bs = raw.get("batch_stats")
    if bs is not None:
        src_local = src.bn_mode == "local"
        dst_local = dst.bn_mode == "local"
        bn_identity_kept = src_local == dst_local and (
            not src_local or src.num_workers == dst.num_workers
        )
        if not bn_identity_kept:
            out["batch_stats"] = _bn_from_canonical(
                _bn_to_canonical(bs, src_local), dst_local, dst.num_workers
            )

    return out


# ----------------------------------------------------- adaptive aggregation

class AdaptiveMaskController:
    """Host half of adaptive partial aggregation: windowed step-time
    statistics (the straggler watchdog's walltimes — the trainer arms
    its per-step barrier whenever this controller exists) pick the next
    window's aggregation count inside [num_aggregate_min, max].

    Policy — deliberately simple and deterministic (the chaos suite
    drives it through FaultPlan.slow_steps):

    - a window containing slow steps (walltime > ``threshold_s``, the
      watchdog's own threshold) shrinks the count by the number of slow
      steps, floored at min: stop waiting for that many stragglers
      within one window of first seeing them;
    - a clean window grows the count by one, ceilinged at max: recover
      gradually so a transient storm does not leave the run degraded.

    Every change emits one ``mask_adapt`` JSONL event through
    ``event_sink``; the traced count itself is clipped again on device,
    so the PSC108 envelope holds even against a buggy controller.

    Multi-host: hosts observe DIFFERENT local walltimes (the straggling
    host sees the stall; a fast host may not), but every host must pass
    the SAME traced count into the global psum — divergent counts make
    the masked aggregate mathematically wrong and silently diverge
    replicated params. ``consensus`` (trainer-provided on multi-host:
    min over hosts of the proposed count, one int32 DCN allgather) is
    applied at each window close — window boundaries are step-counted
    and therefore already identical across hosts. Min semantics: a
    straggler seen by ANY host shrinks everyone; recovery happens only
    when every host's window was clean. The ``slow_steps`` field of the
    mask_adapt event stays the LOCAL observation (hosts' events may
    differ there; step/from/to are identical by construction).

    This consensus hookup is CONTRACT, not convention: the registry's
    adaptive specs declare it as ``AdaptivePolicy.consensus =
    "trainer.Trainer._count_consensus"`` and PSC110 statically verifies
    the named function exists and is consensus-shaped (its return passes
    through a consensus collective — lint/diverge.py's inventory), while
    PSL007 flags any new path that feeds a process-divergent count to
    the traced step without laundering it first."""

    def __init__(self, cfg, threshold_s: float, window: int,
                 event_sink=None, consensus=None):
        if not cfg.adaptive_aggregate:
            raise ValueError(
                "AdaptiveMaskController needs num_aggregate_min/max set"
            )
        if window < 1:
            raise ValueError(f"adapt window must be >= 1, got {window}")
        if threshold_s is None or threshold_s <= 0:
            raise ValueError(
                "adaptive aggregation needs the straggler watchdog's "
                "threshold (arm it with --mode/--kill-threshold): the "
                "controller consumes its per-step walltimes"
            )
        self.lo = cfg.num_aggregate_min
        self.hi = cfg.num_aggregate_max
        self.count = int(cfg.initial_aggregate)
        self.threshold_s = float(threshold_s)
        self.window = int(window)
        self.adaptations = 0
        self._sink = event_sink
        self._consensus = consensus
        self._steps = 0
        self._slow = 0
        self._win_start: Optional[int] = None

    def record(self, step_no: int, seconds: float) -> int:
        """Feed one step's walltime; returns the count the NEXT step
        should use (changes only at window boundaries)."""
        if self._win_start is None:
            self._win_start = step_no
        self._steps += 1
        if seconds > self.threshold_s:
            self._slow += 1
        if self._steps >= self.window:
            self._close_window(step_no)
        return self.count

    def _close_window(self, step_no: int) -> None:
        old = self.count
        if self._slow:
            new = max(self.lo, old - self._slow)
        else:
            new = min(self.hi, old + 1)
        if self._consensus is not None:
            # every host calls this at the same (step-counted) boundary;
            # the adopted count is identical everywhere by construction
            new = min(max(int(self._consensus(new)), self.lo), self.hi)
        if new != old:
            self.adaptations += 1
            logger.info(
                "mask_adapt: aggregation count %d -> %d after window "
                "%d-%d (%d/%d slow steps)",
                old, new, self._win_start, step_no, self._slow, self._steps,
            )
            if self._sink is not None:
                self._sink({
                    "kind": "mask_adapt",
                    "step": step_no,
                    "window_start": self._win_start,
                    "from": old,
                    "to": new,
                    "slow_steps": self._slow,
                    "window_steps": self._steps,
                })
        self.count = new
        self._steps = 0
        self._slow = 0
        self._win_start = None
