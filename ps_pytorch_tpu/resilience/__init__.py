"""Resilience layer: failure is normal, so defend and *prove* the defense.

The reference PS design already treats failure as a first-class input —
a straggler kill-threshold on workers and an evaluator that survives on
checkpoints alone. This package gives the TPU-native reproduction the
matching machinery, in three parts:

- ``guard``:  the device-side non-finite gradient guard fused into the PS
  train step (parallel/ps.py) — a skipped step is the identity update,
  counted on device, with optional dynamic loss scaling for the int8
  compression schemes.
- ``retry``:  bounded exponential-backoff retry for checkpoint I/O (the
  reference's shared-NFS evaluator is exactly where transient EIO lives).
- ``faults``: a deterministic, env/flag-driven fault-injection plan so
  every defense is chaos-tested end-to-end (inject -> skip/fallback/
  resume -> converge) instead of trusted.
- ``elastic``: membership is an input too — resume-reshape lets a
  checkpoint written on an N-worker mesh continue on an M-worker mesh
  (shrink/grow, replicated<->ZeRO-1), and the adaptive aggregation
  controller turns the static backup-worker mask into a per-window
  response to observed stragglers.
- ``precision``: the adaptive per-bucket precision controller — windowed
  gradient-norm telemetry picks each wire bucket's lattice (skip / 4-bit
  / int8 / hi) under an optional byte budget, in the mask controller's
  exact mold (debounce, multihost consensus, schema-validated events).
"""

from .elastic import (
    AdaptiveMaskController,
    MeshGeometry,
    geometry_of,
    load_geometry,
    needs_reshape,
    reshape_raw_state,
    save_geometry,
)
from .faults import FaultPlan, resolve_fault_plan
from .guard import GuardState, init_guard_state, tree_all_finite
from .precision import PrecisionController, effective_wire_bytes
from .retry import retry_io

__all__ = [
    "AdaptiveMaskController",
    "FaultPlan",
    "GuardState",
    "MeshGeometry",
    "PrecisionController",
    "effective_wire_bytes",
    "geometry_of",
    "init_guard_state",
    "load_geometry",
    "needs_reshape",
    "reshape_raw_state",
    "resolve_fault_plan",
    "retry_io",
    "save_geometry",
    "tree_all_finite",
]
