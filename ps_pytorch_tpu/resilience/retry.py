"""Bounded exponential-backoff retry for checkpoint I/O.

Checkpoints cross a shared filesystem (the reference's NFS train_dir;
gcsfuse on a pod — checkpoint.py docstrings), which is exactly where
transient EIO/ESTALE lives. Delays are bounded exponential backoff with
BOUNDED multiplicative jitter: after a shared-storage hiccup, every host
of a pod (and every evaluator polling the same dir) retries on the same
schedule, and jitter-free backoff re-synchronizes their I/O into the
exact thundering herd that caused the hiccup. The jittered delay for
attempt k is uniform in [base*2^k, base*2^k * (1+jitter)] — never
shorter than the deterministic schedule, never more than ``jitter``
longer, so tests reasoning about minimum backoff still hold. The noise
source is injectable (``rng``): the chaos suite passes a seeded
``random.Random`` for reproducible schedules; the module default is
OS-entropy seeded so every process decorrelates unconditionally. The
last failure propagates unchanged so callers keep the real errno."""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")

logger = logging.getLogger("ps_pytorch_tpu")

# per-process default jitter source, urandom-seeded: pod hosts are
# separate machines/containers where the training process routinely has
# the IDENTICAL pid (pid 1 in a container, same mpirun launch order), so
# a pid seed would re-synchronize exactly the schedules jitter exists to
# spread; OS entropy decorrelates unconditionally
_DEFAULT_RNG = random.Random()

# default jitter fraction: up to +25% per delay — enough to spread a
# pod's retry herd across the backoff window, small enough to keep the
# total retry budget within ~1.25x of the deterministic schedule
DEFAULT_JITTER = 0.25


def retry_io(
    fn: Callable[[], T],
    desc: str,
    attempts: int = 3,
    base_delay_s: float = 0.05,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    jitter: float = DEFAULT_JITTER,
    rng: Optional[random.Random] = None,
) -> T:
    """Call ``fn()`` up to ``attempts`` times, sleeping
    ``base*2^k * (1 + jitter*u)`` with ``u ~ U[0,1)`` between tries
    (``jitter=0`` restores the fully deterministic schedule). Only
    ``retry_on`` exceptions are retried (default: OSError — corruption
    is NOT transient and must not be retried into)."""
    if jitter < 0:
        raise ValueError(f"jitter must be >= 0, got {jitter}")
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            delay = base_delay_s * (2 ** attempt)
            if jitter:
                delay *= 1.0 + jitter * (rng or _DEFAULT_RNG).random()
            logger.warning(
                "transient I/O failure (%s), attempt %d/%d, retrying in "
                "%.2fs: %s",
                desc, attempt + 1, attempts, delay, e,
            )
            time.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
