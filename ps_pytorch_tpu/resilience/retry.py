"""Bounded exponential-backoff retry for checkpoint I/O.

Checkpoints cross a shared filesystem (the reference's NFS train_dir;
gcsfuse on a pod — checkpoint.py docstrings), which is exactly where
transient EIO/ESTALE lives. Retries are deterministic (fixed delays, no
jitter: the chaos suite needs reproducible schedules) and bounded; the
last failure propagates unchanged so callers keep the real errno."""

from __future__ import annotations

import logging
import time
from typing import Callable, Tuple, Type, TypeVar

T = TypeVar("T")

logger = logging.getLogger("ps_pytorch_tpu")


def retry_io(
    fn: Callable[[], T],
    desc: str,
    attempts: int = 3,
    base_delay_s: float = 0.05,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
) -> T:
    """Call ``fn()`` up to ``attempts`` times, sleeping base*2^k between
    tries. Only ``retry_on`` exceptions are retried (default: OSError —
    corruption is NOT transient and must not be retried into)."""
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            delay = base_delay_s * (2 ** attempt)
            logger.warning(
                "transient I/O failure (%s), attempt %d/%d, retrying in "
                "%.2fs: %s",
                desc, attempt + 1, attempts, delay, e,
            )
            time.sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
