"""Deterministic fault injection: the chaos half of the resilience layer.

A ``FaultPlan`` names global step numbers (1-based, the numbers the log
lines and checkpoints carry) at which to inject a failure, so every run
of a chaos test replays the identical schedule — no timers, no
randomness. Injectable faults and the defense each one proves:

  nan_grads / inf_grads  device-side non-finite gradients -> the guard
                         skips the step (params identical, counter up)
  slow_steps (slow_s)    a host stall inside the step phase -> trips the
                         straggler watchdog / storm escalation
  ckpt_write_fail        checkpoint write raises EIO (every attempt at
                         that step) -> AsyncCheckpointer's structured
                         ckpt_write_failed event + contextual error
  ckpt_corrupt           the written checkpoint file is truncated on
                         disk -> CRC verify fails, --resume quarantines
                         it and falls back to the previous valid step
  sigterm                the process SIGTERMs itself at a step boundary
                         -> graceful-stop consensus, final checkpoint,
                         clean --resume

Serve-side faults (keyed by serve-loop TICK or checkpoint step — the
serving process has no training step counter; tick numbering starts
after the engine's compile warmup so plans target served traffic):

  slow_decode            host stall inside the serve tick (tick list +
  (slow_decode_s)        stall seconds) -> queue depth grows, driving
                         the admission controller into shedding
  rollover_corrupt       the checkpoint file is truncated on disk the
                         moment the engine STAGES it for rollover ->
                         the swap-time re-read must discover the damage
                         and abort onto the old weights
  spike                  [rate_mult, start_s, dur_s]: traffic burst
                         multiplier over a time range, consumed by the
                         traffic generator (serve/traffic.py square-
                         wave rate modulation) -> reproducible overload

The plan comes from ``--fault-plan`` (a JSON object or ``@path`` to one)
or the ``PS_TPU_FAULTS`` env var, so subprocess tests and tools/smoke.sh
drive it without touching code. Gradient faults are baked into the
jitted step as constants (parallel/ps.py); host faults hook the trainer
loop, the checkpoint writer, and the serving engine's tick/rollover
paths.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import signal
import time
from typing import Optional, Tuple

FAULTS_ENV = "PS_TPU_FAULTS"

_KNOWN_KEYS = {
    "nan_grads", "inf_grads", "slow_steps", "slow_s",
    "ckpt_write_fail", "ckpt_corrupt", "sigterm",
    "slow_decode", "slow_decode_s", "rollover_corrupt", "spike",
}


def _truncate_half(path: str) -> None:
    """Shear a file to half its size in place — the shared corruption
    primitive behind both checkpoint-corruption hooks (train-side
    ckpt_corrupt and serve-side rollover_corrupt must damage files the
    same way, or the two chaos suites drift apart)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 2, 1))


def _steps(raw, key) -> Tuple[int, ...]:
    if raw is None:
        return ()
    if not isinstance(raw, (list, tuple)):
        raise ValueError(f"fault plan {key!r} must be a list of steps")
    for s in raw:
        # bool is an int subclass: [true] would silently poison step 1
        if isinstance(s, bool) or not isinstance(s, int):
            raise ValueError(
                f"fault plan {key!r} steps must be integers, got {s!r}"
            )
    return tuple(sorted(raw))


@dataclasses.dataclass
class FaultPlan:
    """A deterministic schedule of injected failures, keyed by step."""

    nan_grads: Tuple[int, ...] = ()
    inf_grads: Tuple[int, ...] = ()
    slow_steps: Tuple[int, ...] = ()
    slow_s: float = 1.5
    ckpt_write_fail: Tuple[int, ...] = ()
    ckpt_corrupt: Tuple[int, ...] = ()
    sigterm: Optional[int] = None
    # serve side: ticks / checkpoint steps / traffic modulation
    slow_decode: Tuple[int, ...] = ()
    slow_decode_s: float = 0.05
    rollover_corrupt: Tuple[int, ...] = ()
    spike: Optional[Tuple[float, float, float]] = None

    def __post_init__(self):
        self._sigterm_fired = False

    # ------------------------------------------------------------- parsing
    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse a JSON object (or ``@path`` to a JSON file)."""
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                spec = f.read()
        raw = json.loads(spec)
        if not isinstance(raw, dict):
            raise ValueError("fault plan must be a JSON object")
        unknown = sorted(set(raw) - _KNOWN_KEYS)
        if unknown:
            raise ValueError(
                f"unknown fault plan key(s) {unknown}; known: "
                f"{sorted(_KNOWN_KEYS)}"
            )
        sig = raw.get("sigterm")
        if sig is not None and (
            isinstance(sig, bool) or not isinstance(sig, int)
        ):
            # every other fault key is a step LIST; catch the natural
            # '{"sigterm": [5]}' analogy with a real error, not a
            # TypeError traceback from int()
            raise ValueError(
                f"fault plan 'sigterm' must be a single step number "
                f"(the process can only die once), got {sig!r}"
            )
        slow_s = float(raw.get("slow_s", cls.slow_s))
        if slow_s < 0:
            # fail at parse time like every other malformed field, not as
            # a time.sleep ValueError mid-run at the injection step
            raise ValueError(
                f"fault plan 'slow_s' must be >= 0, got {slow_s}"
            )
        slow_decode_s = float(raw.get("slow_decode_s", cls.slow_decode_s))
        if slow_decode_s < 0:
            raise ValueError(
                f"fault plan 'slow_decode_s' must be >= 0, got "
                f"{slow_decode_s}"
            )
        spike = raw.get("spike")
        if spike is not None:
            if (
                not isinstance(spike, (list, tuple))
                or len(spike) != 3
                or any(
                    isinstance(x, bool) or not isinstance(x, (int, float))
                    for x in spike
                )
            ):
                raise ValueError(
                    f"fault plan 'spike' must be [rate_mult, start_s, "
                    f"dur_s] (three numbers), got {spike!r}"
                )
            mult, start_s, dur_s = (float(x) for x in spike)
            if mult <= 0 or start_s < 0 or dur_s <= 0:
                raise ValueError(
                    f"fault plan 'spike' needs rate_mult > 0, start_s >= "
                    f"0, dur_s > 0, got {spike!r}"
                )
            spike = (mult, start_s, dur_s)
        return cls(
            nan_grads=_steps(raw.get("nan_grads"), "nan_grads"),
            inf_grads=_steps(raw.get("inf_grads"), "inf_grads"),
            slow_steps=_steps(raw.get("slow_steps"), "slow_steps"),
            slow_s=slow_s,
            ckpt_write_fail=_steps(raw.get("ckpt_write_fail"),
                                   "ckpt_write_fail"),
            ckpt_corrupt=_steps(raw.get("ckpt_corrupt"), "ckpt_corrupt"),
            sigterm=(None if raw.get("sigterm") is None
                     else int(raw["sigterm"])),
            slow_decode=_steps(raw.get("slow_decode"), "slow_decode"),
            slow_decode_s=slow_decode_s,
            rollover_corrupt=_steps(raw.get("rollover_corrupt"),
                                    "rollover_corrupt"),
            spike=spike,
        )

    # --------------------------------------------------------- host hooks
    def maybe_sleep(self, step: int) -> None:
        """Stall the host inside the step phase (straggler injection)."""
        if step in self.slow_steps:
            time.sleep(self.slow_s)

    def maybe_sigterm(self, step: int) -> None:
        """Deliver SIGTERM to this process once, at the planned step."""
        if self.sigterm == step and not self._sigterm_fired:
            self._sigterm_fired = True
            os.kill(os.getpid(), signal.SIGTERM)

    def maybe_fail_ckpt_write(self, step: int) -> None:
        """Raise EIO from inside the checkpoint writer. Persistent for
        the step (every retry attempt fails), so the failure surfaces
        instead of being absorbed by the I/O retry."""
        if step in self.ckpt_write_fail:
            raise OSError(
                errno.EIO, f"injected checkpoint write failure (step {step})"
            )

    def maybe_corrupt_ckpt(self, path: str, step: int) -> None:
        """Truncate the just-written checkpoint to half its size —
        simulated on-disk corruption the CRC trailer must catch."""
        if step in self.ckpt_corrupt:
            _truncate_half(path)

    # -------------------------------------------------------- serve hooks
    def maybe_slow_decode(self, tick: int, sleep=time.sleep) -> None:
        """Stall the host inside a serve tick (per-tick injected latency:
        queue growth drives the admission controller). ``sleep`` is
        injectable so virtual-clock tests advance their clock instead of
        real-sleeping."""
        if tick in self.slow_decode:
            sleep(self.slow_decode_s)

    def maybe_corrupt_staged(self, path: str, step: int) -> None:
        """Truncate a checkpoint the serving engine just STAGED for
        rollover — corruption landing between stage and swap, which the
        swap-time re-read must discover (rollover_abort, not a crash)."""
        if step in self.rollover_corrupt:
            _truncate_half(path)


def resolve_fault_plan(spec: Optional[str]) -> Optional[FaultPlan]:
    """Explicit spec first (CLI flag), else the env var, else None."""
    spec = spec or os.environ.get(FAULTS_ENV) or None
    return FaultPlan.parse(spec) if spec else None
