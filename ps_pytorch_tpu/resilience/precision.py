"""Adaptive per-bucket precision: the host half.

The traced half (PSConfig.precision_adapt) makes the train step take an
int32 tag per wire bucket — skip / 4-bit / int8 / hi — and quantize each
bucket onto the lattice its tag names, with NO retrace on tag change
(ops/quantize.quantize_lattice: the tag only selects a traced clipping
peak). This module is the controller that PICKS the tags, in the exact
mold of elastic.AdaptiveMaskController: windowed telemetry in, one tiny
deterministic policy, multihost consensus at window close, a
schema-validated JSONL event on every change.

Telemetry: the step's ``bucket_sqnorm`` metrics row — the mesh-mean
squared gradient norm per bucket, [n_buckets] f32, one device fetch per
step the trainer already pays for its metrics window. Per-bucket signal
DENSITY (window-mean sqnorm / bucket size) is the ranking currency:
Variance-based Gradient Compression (PAPERS.md) assigns rate by
per-coordinate signal variance, and density is its cheap bucketed proxy.

Policy (deliberately simple, fully deterministic):

- RELATIVE thresholds against the window's densest bucket: a bucket at
  <= 1e-8 of the max density carries noise — SKIP it (EF keeps its whole
  gradient as residual, nothing is lost, just deferred); <= 1e-3 earns
  the 4-bit lattice; >= 0.25 earns the HI lattice (finest the wire's
  narrowest integer hop carries, ps.precision_hi_peak); else int8.
- BUDGET: ``--wire-budget-bytes`` caps the per-step EFFECTIVE wire bytes
  (sum of size_b * bytes-per-element of tag_b — what a byte-honest
  transport would ship; the physical trace bytes are static, PSC108's
  "adaptation reshapes values, never bytes" stance). Over budget, the
  LOWEST-density bucket downgrades one notch, repeatedly — but the
  budget never forces a SKIP (dropping signal entirely is the density
  ladder's call, not the accountant's).
- HYSTERESIS by debounce: a proposal is adopted only when two
  consecutive windows propose the SAME tag vector — one noisy window
  can never flap the wire.
- CONSENSUS: hosts observe the same pmean'd telemetry in exact
  arithmetic but a paranoid elementwise MIN over hosts' adopted tags is
  applied at window close (finer lattice = larger tag, so min = the
  coarsest any host wants = the cheapest — consensus can only reduce
  effective bytes, never break the budget). Same contract discipline as
  the mask controller: the registry declares the trainer's hookup
  (``trainer.Trainer._tags_consensus``) and PSC110 verifies it is
  consensus-shaped.

A window whose telemetry contains any non-finite value adapts nothing
(the guard is already skipping those steps; adapting on poisoned stats
would launder a NaN into a policy change).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

from ..ops.quantize import (
    PREC_4BIT,
    PREC_HI,
    PREC_INT8,
    PREC_SKIP,
    PRECISION_TAG_NAMES,
    precision_bytes_per_element,
)

logger = logging.getLogger("ps_pytorch_tpu")

# relative-density ladder (fractions of the window's max density)
SKIP_FRACTION = 1e-8
FOURBIT_FRACTION = 1e-3
HI_FRACTION = 0.25


def effective_wire_bytes(
    tags: Sequence[int], sizes: Sequence[int], hi_peak: int
) -> int:
    """Effective gradient-wire bytes one step ships under ``tags``: the
    controller's budget currency and the bench A/B's evidence metric.
    Skip = 0, 4-bit = size/2 (pack_int4's exact output size, rounded up
    per bucket), int8 = size, hi = the minimal integer width holding
    ``hi_peak``. Scale rows are tag-invariant and excluded — identical
    on both sides of any comparison this number feeds."""
    per_el = precision_bytes_per_element(hi_peak)
    total = 0.0
    for t, s in zip(tags, sizes):
        total += per_el[int(t)] * int(s)
    return int(np.ceil(total))


class PrecisionController:
    """Host half of adaptive per-bucket precision (module docstring has
    the policy). Feed one ``record(step_no, bucket_sqnorm)`` per step;
    it returns the int32 tag vector the NEXT step should trace (changes
    only at window boundaries). ``consensus``, when given (multihost),
    maps a proposed int32 tag vector to the elementwise min across
    hosts — the trainer provides its PSC110-declared hookup."""

    def __init__(self, cfg, sizes: Sequence[int], window: int,
                 budget_bytes: Optional[int] = None, event_sink=None,
                 consensus=None):
        from ..parallel.ps import precision_hi_peak

        if not cfg.precision_adapt:
            raise ValueError(
                "PrecisionController needs cfg.precision_adapt=True"
            )
        if window < 1:
            raise ValueError(f"adapt window must be >= 1, got {window}")
        self.sizes = np.asarray(sizes, np.int64)
        if self.sizes.ndim != 1 or self.sizes.size < 1 or (
            self.sizes <= 0
        ).any():
            raise ValueError(
                f"bad bucket sizes {sizes!r}: need >= 1 positive entries "
                f"(state_plan(cfg, total).sizes)"
            )
        self.hi_peak = precision_hi_peak(cfg)
        self._bytes_per_el = precision_bytes_per_element(self.hi_peak)
        static_int8 = effective_wire_bytes(
            [PREC_INT8] * self.sizes.size, self.sizes, self.hi_peak
        )
        if budget_bytes is not None and budget_bytes < 1:
            raise ValueError(f"bad wire budget {budget_bytes} (need >= 1)")
        self.budget_bytes = (
            int(budget_bytes) if budget_bytes is not None else None
        )
        self.static_int8_bytes = static_int8
        self.window = int(window)
        # start on the committed-contract lattice everywhere: the first
        # window observes static-int8 behavior, adaptation is evidence-in
        self.tags = np.full(self.sizes.size, PREC_INT8, np.int32)
        self.adaptations = 0
        self._sink = event_sink
        self._consensus = consensus
        self._steps = 0
        self._sq_sum = np.zeros(self.sizes.size, np.float64)
        self._finite = True
        self._win_start: Optional[int] = None
        self._pending: Optional[np.ndarray] = None

    # ------------------------------------------------------------- policy

    def _ladder(self, density: np.ndarray) -> np.ndarray:
        """Relative-threshold tag proposal from per-element densities."""
        dmax = float(density.max())
        if dmax <= 0.0:
            # an all-zero gradient window: keep the current tags (there
            # is no signal to rank; skipping everything on silence would
            # stall warmup)
            return self.tags.copy()
        rel = density / dmax
        tags = np.full(density.size, PREC_INT8, np.int32)
        tags[rel >= HI_FRACTION] = PREC_HI
        tags[rel <= FOURBIT_FRACTION] = PREC_4BIT
        tags[rel <= SKIP_FRACTION] = PREC_SKIP
        return tags

    def _enforce_budget(self, tags: np.ndarray,
                        density: np.ndarray) -> np.ndarray:
        """Downgrade lowest-density non-minimum buckets one notch at a
        time until the effective bytes fit the budget (or nothing above
        4-bit remains — the budget never forces a SKIP)."""
        if self.budget_bytes is None:
            return tags
        tags = tags.copy()
        order = np.argsort(density, kind="stable")  # cheapest signal first
        while self.effective_bytes(tags) > self.budget_bytes:
            downgraded = False
            for b in order:
                if tags[b] > PREC_4BIT:
                    tags[b] -= 1
                    downgraded = True
                    break
            if not downgraded:
                logger.warning(
                    "precision_adapt: wire budget %d B unreachable — "
                    "floor is %d B with every bucket at 4-bit",
                    self.budget_bytes, self.effective_bytes(tags),
                )
                break
        return tags

    # ----------------------------------------------------------- interface

    def effective_bytes(self, tags: Optional[np.ndarray] = None) -> int:
        return effective_wire_bytes(
            self.tags if tags is None else tags, self.sizes, self.hi_peak
        )

    def record(self, step_no: int, bucket_sqnorm) -> np.ndarray:
        """Feed one step's [n_buckets] mesh-mean squared-norm row;
        returns the tag vector the NEXT step should use."""
        sq = np.asarray(bucket_sqnorm, np.float64).reshape(-1)
        if sq.size != self.sizes.size:
            raise ValueError(
                f"bucket_sqnorm has {sq.size} entries, plan has "
                f"{self.sizes.size} buckets"
            )
        if self._win_start is None:
            self._win_start = step_no
        self._steps += 1
        if not np.isfinite(sq).all():
            self._finite = False
        else:
            self._sq_sum += sq
        if self._steps >= self.window:
            self._close_window(step_no)
        return self.tags

    def _close_window(self, step_no: int) -> None:
        win_start, steps = self._win_start, self._steps
        finite, sq_sum = self._finite, self._sq_sum
        self._steps = 0
        self._sq_sum = np.zeros(self.sizes.size, np.float64)
        self._finite = True
        self._win_start = None
        if not finite:
            self._pending = None  # poisoned window: adapt nothing
            return
        density = (sq_sum / steps) / self.sizes
        proposal = self._enforce_budget(self._ladder(density), density)
        # debounce: adopt only what two consecutive windows agree on
        if self._pending is None or not np.array_equal(
            self._pending, proposal
        ):
            self._pending = proposal
            return
        adopted = proposal
        if self._consensus is not None:
            # elementwise min across hosts: coarsest wins, so consensus
            # can only shrink effective bytes — the budget still holds
            adopted = np.minimum(
                np.asarray(self._consensus(adopted), np.int32),
                adopted,
            ).astype(np.int32)
        changed = int((adopted != self.tags).sum())
        if changed:
            self.tags = adopted.astype(np.int32)
            self.adaptations += 1
            counts = {
                f"n_{name}": int((self.tags == t).sum())
                for t, name in enumerate(PRECISION_TAG_NAMES)
            }
            eff = self.effective_bytes()
            logger.info(
                "precision_adapt: %d/%d buckets retagged after window "
                "%d-%d (skip=%d 4bit=%d int8=%d hi=%d, effective %d B "
                "vs static int8 %d B)",
                changed, self.tags.size, win_start, step_no,
                counts["n_skip"], counts["n_4bit"], counts["n_int8"],
                counts["n_hi"], eff, self.static_int8_bytes,
            )
            if self._sink is not None:
                self._sink({
                    "kind": "precision_adapt",
                    "step": step_no,
                    "window_start": win_start,
                    "changed": changed,
                    "effective_bytes": eff,
                    "budget_bytes": (
                        self.budget_bytes
                        if self.budget_bytes is not None
                        else 0
                    ),
                    **counts,
                })
