"""Flash attention as Pallas TPU kernels (forward + backward).

The single-chip attention hot path. parallel/ring_attention.py and
parallel/ulysses.py already avoid materializing the [T, T] score matrix
ACROSS chips; this kernel does the same WITHIN a chip: blockwise online
softmax in VMEM, O(T) memory instead of O(T^2) HBM traffic, MXU-shaped
[block_q, d] x [d, block_k] matmuls.

Layout: inputs [B, T, H, D] are folded to [B*H, T, D]; the grid walks
(batch*head, q_block, k_block) with the k axis innermost, accumulating
(acc, row-max m, row-sum l) in VMEM scratch and writing the normalized
output plus the logsumexp L = m + log(l) at the last k step. The backward
pass recomputes p = exp(q k^T * scale - L) per block (flash-attention-2
style) in two kernels: one accumulating dq over k blocks, one accumulating
(dk, dv) over q blocks, seeded with delta = rowsum(do * o) computed in
plain XLA.

Causality is enforced by masking with global positions (uniform grid —
fully-masked blocks still run; the win is memory, not skipped FLOPs).

Selection follows ops/quantize.py's convention: Pallas on TPU backends,
interpret mode under PS_TPU_PALLAS_INTERPRET=1 (how CPU CI exercises the
kernels), pure-jnp reference otherwise (PS_TPU_DISABLE_PALLAS=1 forces
it). The jnp reference is ring_attention.full_attention — also the test
oracle.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _mask_scores(scores, qi, ki, block_q, block_k, causal, k_len,
                 q_off=0, k_off=0):
    """Apply the causal and/or key-padding mask to one [block_q, block_k]
    score tile, with positions taken from the grid indices plus GLOBAL
    offsets (q_off/k_off are 0 single-chip; on a sequence-parallel ring
    they are the traced shard offsets of the local q block and the
    visiting k block). `k_len` (static) masks key positions >= k_len —
    how flash_attention supports sequence lengths that are not block
    multiples: inputs are zero-padded to the block grid and the padded
    keys are masked here. The ONE masking implementation shared by the
    forward, dq, and dkv kernels — they must never diverge or gradients
    silently stop matching the forward."""
    k_local = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    keep = None
    if causal:
        q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        keep = (k_off + k_local) <= q_pos
    if k_len is not None:
        # k_len is the LOCAL (unpadded) length of this k/v operand — the
        # pad mask is in local coordinates, unlike the causal mask's
        # global ones (a visiting ring shard pads at its local tail)
        pad_keep = k_local < k_len
        keep = pad_keep if keep is None else (keep & pad_keep)
    return jnp.where(keep, scores, NEG_INF)


def _pallas_mode() -> Optional[dict]:
    if os.environ.get("PS_TPU_DISABLE_PALLAS"):
        return None
    if os.environ.get("PS_TPU_PALLAS_INTERPRET"):
        return {"interpret": True}
    if jax.default_backend() == "tpu":
        return {}
    return None


# --------------------------------------------------------------- forward


def _make_fwd_kernel(scale, causal, block_q, block_k, n_k, normalize,
                     k_len=None):
    from jax.experimental import pallas as pl

    masked = causal or k_len is not None

    def kernel(off_ref, q_ref, k_ref, v_ref, *out_and_scratch):
        if normalize:
            o_ref, lse_ref, acc_ref, m_ref, l_ref = out_and_scratch
        else:
            pv_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref = out_and_scratch
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)

        q = q_ref[0]  # [Bq, D]
        k = k_ref[0]  # [Bk, D]
        v = v_ref[0]  # [Bk, D]
        scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

        if masked:
            scores = _mask_scores(
                scores, qi, ki, block_q, block_k, causal, k_len,
                off_ref[0, 0], off_ref[0, 1],
            )

        m_prev = m_ref[:]  # [Bq, 1]
        m_blk = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(scores - m_new)  # [Bq, Bk]
        if masked:
            # rows with every key masked: m_new == NEG_INF, exp(0)=1 junk
            p = jnp.where(m_new > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)  # [Bq, 1]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

        @pl.when(ki == n_k - 1)
        def _finalize():
            if normalize:
                l = l_ref[:]
                l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0
                o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
                lse_ref[0] = (m_ref[:] + jnp.log(l_safe))[:, 0]
            else:
                # partial triple for ring hops: UNNORMALIZED numerator plus
                # the (m, l) stats, merged across hops by the caller
                pv_ref[0] = acc_ref[:]
                mo_ref[0] = m_ref[:][:, 0]
                lo_ref[0] = l_ref[:][:, 0]

    return kernel


def _offsets_arr(offsets):
    """(q_off, k_off) traced/static scalars -> (1, 2) i32 SMEM operand."""
    if offsets is None:
        return jnp.zeros((1, 2), jnp.int32)
    q_off, k_off = offsets
    return jnp.stack(
        [jnp.asarray(q_off, jnp.int32), jnp.asarray(k_off, jnp.int32)]
    )[None]


def _smem_spec():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(
        (1, 2), lambda *_: (0, 0), memory_space=pltpu.SMEM
    )


def _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, mode,
               offsets=None, normalize=True, k_len=None):
    """q3/k3/v3: [BH, T, D] -> (o [BH, T, D], lse [BH, T]) when normalize,
    else the partial triple (pv f32 [BH, T, D], m f32 [BH, T], l f32
    [BH, T]) for ring-hop merging. `offsets` shifts the causal mask's
    global positions; static `k_len` masks zero-padded key positions
    (see _mask_scores)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    tk = k3.shape[1]
    n_q, n_k = t // block_q, tk // block_k
    kernel = _make_fwd_kernel(scale, causal, block_q, block_k, n_k, normalize,
                              k_len=k_len)
    if normalize:
        out_specs = [
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q), lambda b, qi, ki: (b, qi)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ]
    else:
        out_specs = [
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q), lambda b, qi, ki: (b, qi)),
            pl.BlockSpec((1, block_q), lambda b, qi, ki: (b, qi)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ]
    return pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        **mode,
    )(_offsets_arr(offsets), q3, k3, v3)


# --------------------------------------------------------------- backward


def _make_dq_kernel(scale, causal, block_q, block_k, n_k, k_len=None):
    from jax.experimental import pallas as pl

    masked = causal or k_len is not None

    def kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, acc_ref):
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0][:, None]  # [Bq, 1]
        delta = delta_ref[0][:, None]  # [Bq, 1]
        scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if masked:
            scores = _mask_scores(
                scores, qi, ki, block_q, block_k, causal, k_len,
                off_ref[0, 0], off_ref[0, 1],
            )
        p = jnp.exp(scores - lse)  # exact softmax probs, [Bq, Bk]
        # fully-masked rows: lse == NEG_INF and scores == NEG_INF give
        # exp(0) = 1; such rows contributed nothing forward, so zero them
        p = jnp.where(lse > NEG_INF / 2, p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        acc_ref[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

        @pl.when(ki == n_k - 1)
        def _finalize():
            dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(scale, causal, block_q, block_k, n_q, k_len=None):
    from jax.experimental import pallas as pl

    masked = causal or k_len is not None

    def kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dk_ref, dv_ref, dk_acc, dv_acc):
        ki = pl.program_id(1)
        qi = pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0][:, None]
        delta = delta_ref[0][:, None]
        scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if masked:
            scores = _mask_scores(
                scores, qi, ki, block_q, block_k, causal, k_len,
                off_ref[0, 0], off_ref[0, 1],
            )
        p = jnp.exp(scores - lse)  # [Bq, Bk]
        p = jnp.where(lse > NEG_INF / 2, p, 0.0)  # fully-masked rows (see dq)
        dv_acc[:] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale  # [Bq, Bk]
        dk_acc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

        @pl.when(qi == n_q - 1)
        def _finalize():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


def _flash_bwd(q3, k3, v3, lse, delta, do3, scale, causal, block_q, block_k,
               mode, offsets=None, out_dtype=None, k_len=None):
    """Blockwise gradients. `lse`/`delta` are the FINAL (post-merge)
    softmax stats — single-chip they come straight from the forward; on a
    ring every hop reuses the globally-merged values, which is what makes
    per-hop contributions sum to the exact gradient. k3/v3 may have a
    different sequence length than q3 (a visiting ring shard).
    `out_dtype` overrides the gradients' dtype (the ring passes f32 so
    per-hop pieces accumulate without a per-hop rounding)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    tk = k3.shape[1]
    n_q, n_k = t // block_q, tk // block_k
    off = _offsets_arr(offsets)
    dq_dt = out_dtype or q3.dtype
    dk_dt = out_dtype or k3.dtype
    dv_dt = out_dtype or v3.dtype

    dq = pl.pallas_call(
        _make_dq_kernel(scale, causal, block_q, block_k, n_k, k_len=k_len),
        grid=(bh, n_q, n_k),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q), lambda b, qi, ki: (b, qi)),
            pl.BlockSpec((1, block_q), lambda b, qi, ki: (b, qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), dq_dt),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        **mode,
    )(off, q3, k3, v3, do3, lse, delta)

    dk, dv = pl.pallas_call(
        _make_dkv_kernel(scale, causal, block_q, block_k, n_q, k_len=k_len),
        grid=(bh, n_k, n_q),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q), lambda b, ki, qi: (b, qi)),
            pl.BlockSpec((1, block_q), lambda b, ki, qi: (b, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), dk_dt),
            jax.ShapeDtypeStruct((bh, tk, d), dv_dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        **mode,
    )(off, q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------- public API


def _ceil_pow2(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


def _floor_pow2(x: int) -> int:
    p = 1
    while p * 2 <= x:
        p *= 2
    return p


def _plan_blocks(t: int, want_q: int, want_k: int):
    """(block_q, block_k, padded_t) for a sequence of length t. When t is
    not a multiple of the block grid, pad UP to it and mask the tail
    (k_len) instead of shrinking blocks — a T=1000 call keeps MXU-shaped
    128-wide tiles over T=1024 rather than degrading to a 1-wide grid
    (VERDICT r02 weak #3). Requested block sizes are floored to powers of
    two so the padded length is divisible by both (lcm = max) — a non-pow2
    request must never leave grid-uncovered tail rows."""
    bq, _ = _plan_one(t, want_q)
    bk, _ = _plan_one(t, want_k)
    lcm = max(bq, bk)  # both are powers of two: lcm = max
    tp = -(-t // lcm) * lcm
    return bq, bk, tp


def _plan_one(t: int, want: int):
    """(block, padded_t) for ONE sequence axis (the ring-hop API plans q
    and k independently — a visiting k/v shard can have a different
    length than the local q shard)."""
    b = min(_floor_pow2(want), max(8, _ceil_pow2(t)))
    return b, -(-t // b) * b


def _pad_t(x, tp, value=0.0):
    """Pad axis 1 (sequence) of [BH, T, ...] up to tp with `value`."""
    t = x.shape[1]
    if tp == t:
        return x
    widths = [(0, 0), (0, tp - t)] + [(0, 0)] * (x.ndim - 2)
    return jnp.pad(x, widths, constant_values=value)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q3, k3, v3, scale, causal, block_q, block_k, k_len):
    o, _ = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                      _pallas_mode() or {"interpret": True}, k_len=k_len)
    return o


def _flash_vjp_fwd(q3, k3, v3, scale, causal, block_q, block_k, k_len):
    mode = _pallas_mode() or {"interpret": True}
    o, lse = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, mode,
                        k_len=k_len)
    return o, (q3, k3, v3, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, k_len, res, do3):
    q3, k3, v3, o3, lse = res
    mode = _pallas_mode() or {"interpret": True}
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)
    return _flash_bwd(q3, k3, v3, lse, delta, do3, scale, causal,
                      block_q, block_k, mode, k_len=k_len)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Drop-in replacement for ring_attention.full_attention ([B, T, H, D]
    in and out), differentiable, Pallas-backed on TPU.

    Falls back to the jnp reference when Pallas is unavailable/disabled.
    Any T works: lengths that are not block multiples are zero-padded up
    to the block grid and the padded keys masked inside the kernels, so
    tiles stay MXU-shaped (no silent degradation to tiny blocks).
    """
    if _pallas_mode() is None:
        from ..parallel.ring_attention import full_attention

        return full_attention(q, k, v, causal=causal, scale=scale)

    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    bq, bk, tp = _plan_blocks(t, block_q, block_k)
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    q3, k3, v3 = fold(q), fold(k), fold(v)
    k_len = None
    if tp != t:
        pad = ((0, 0), (0, tp - t), (0, 0))
        q3, k3, v3 = (jnp.pad(x, pad) for x in (q3, k3, v3))
        k_len = t
    o3 = _flash(q3, k3, v3, float(scale), bool(causal), bq, bk, k_len)
    o3 = o3[:, :t]
    return o3.reshape(b, h, t, d).transpose(0, 2, 1, 3)


# ------------------------------------------- ring-hop partial-triple API
# (consumed by parallel/ring_attention.ring_flash_attention: flash WITHIN
# each ring hop, so a sequence shard never materializes [T_loc, T_loc])


def flash_partial(q3, k3, v3, scale, causal, q_off, k_off,
                  block_q=128, block_k=128, mode=None):
    """One hop's UNNORMALIZED contribution: [BH, Tq, D] queries against a
    visiting [BH, Tk, D] K/V shard -> (pv f32 [BH, Tq, D], m f32 [BH, Tq],
    l f32 [BH, Tq]). q_off/k_off are the shards' global sequence offsets
    (traced scalars are fine — they ride in SMEM, one compiled kernel
    serves every hop). The caller merges triples across hops with the
    usual online-softmax rescale and normalizes once at the end.

    Shard lengths need not be block multiples: like flash_attention, odd
    lengths are padded up to the block grid (padded keys masked via
    k_len, padded query rows sliced off) so tiles stay MXU-shaped."""
    tq, tk = q3.shape[1], k3.shape[1]
    bq, tpq = _plan_one(tq, block_q)
    bk, tpk = _plan_one(tk, block_k)
    q3 = _pad_t(q3, tpq)
    k3, v3 = _pad_t(k3, tpk), _pad_t(v3, tpk)
    pv, m, l = _flash_fwd(
        q3, k3, v3, scale, causal, bq, bk,
        mode if mode is not None else (_pallas_mode() or {"interpret": True}),
        offsets=(q_off, k_off), normalize=False,
        k_len=(tk if tpk != tk else None),
    )
    return pv[:, :tq], m[:, :tq], l[:, :tq]


def flash_grads_partial(q3, k3, v3, do3, lse, delta, scale, causal,
                        q_off, k_off, block_q=128, block_k=128, mode=None):
    """One hop's gradient contributions (dq [BH, Tq, D], dk [BH, Tk, D],
    dv [BH, Tk, D], all f32) given the FINAL merged lse/delta — per-hop
    pieces sum to the exact flash backward (f32 out so cross-hop
    accumulation never rounds per hop, even under bf16 inputs). Odd shard
    lengths pad-and-mask exactly like flash_partial (padded q rows carry
    zero do/delta, so they contribute nothing to dk/dv)."""
    tq, tk = q3.shape[1], k3.shape[1]
    bq, tpq = _plan_one(tq, block_q)
    bk, tpk = _plan_one(tk, block_k)
    q3, do3 = _pad_t(q3, tpq), _pad_t(do3, tpq)
    # lse pads with +inf-ish so padded rows' p = exp(scores - lse)
    # underflows to 0 (their do/delta are zero-padded, so they'd
    # contribute nothing anyway — this just keeps exp() finite)
    lse, delta = _pad_t(lse, tpq, value=-NEG_INF), _pad_t(delta, tpq)
    k3, v3 = _pad_t(k3, tpk), _pad_t(v3, tpk)
    dq, dk, dv = _flash_bwd(
        q3, k3, v3, lse, delta, do3, scale, causal, bq, bk,
        mode if mode is not None else (_pallas_mode() or {"interpret": True}),
        offsets=(q_off, k_off), out_dtype=jnp.float32,
        k_len=(tk if tpk != tk else None),
    )
    return dq[:, :tq], dk[:, :tk], dv[:, :tk]
