"""Host byte codec — ctypes binding of the native C++ codec (native/codec.cc).

API parity with the reference's codec module (/root/reference/src/
compression.py:18-46: g_compress/g_decompress/w_compress/w_decompress wrap
blosc.pack_array/unpack_array): same four names, same role (gradients and
weights on the host wire), different engine — our own shuffle+LZ C++ library
instead of an external c-blosc dependency. Array framing (dtype/shape) is a
small JSON header ahead of the byte stream.

The shared library is built on demand with g++ (native/Makefile has the same
recipe); if no compiler is available the module falls back to zlib so the
checkpoint/codec feature degrades gracefully rather than failing.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
import zlib
from typing import Optional

import numpy as np

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_PKG_DIR, "_native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpsnative.so")
_SRC_DIR = os.path.join(os.path.dirname(_PKG_DIR), "native")
_SOURCES = ("codec.cc", "loader.cc")

_lock = threading.Lock()
_lib = None
_lib_tried = False

MAGIC = b"PSAR"  # array framing magic (codec stream has its own 'PSC1')


def _build_library() -> Optional[ctypes.CDLL]:
    """Compile the native sources and return a handle to the FRESH build.

    The handle is dlopen'd from a unique temp path before the os.replace
    into _LIB_PATH: dlopen caches by pathname, so re-opening _LIB_PATH
    after replacing a stale .so would silently return the old mapping."""
    sources = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    if not all(os.path.exists(s) for s in sources):
        return None
    os.makedirs(_NATIVE_DIR, exist_ok=True)
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-std=c++17", "-fPIC", "-Wall",
        "-shared", "-pthread",
        "-o", tmp, *sources,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        lib = ctypes.CDLL(tmp)
        # publish for other processes; our mapping survives the rename
        os.replace(tmp, _LIB_PATH)
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return lib


def _load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None -> zlib fallback."""
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        lib = None
        if os.path.exists(_LIB_PATH):
            try:
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError:
                lib = None
        if lib is None or getattr(lib, "psl_gather", None) is None:
            # missing or stale (pre-loader.cc) build — compile fresh; keep
            # a stale-but-working codec lib if no compiler is available
            rebuilt = _build_library()
            if rebuilt is not None:
                lib = rebuilt
        if lib is None:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.psc_max_compressed.restype = ctypes.c_size_t
        lib.psc_max_compressed.argtypes = [ctypes.c_size_t]
        lib.psc_compress.restype = ctypes.c_size_t
        lib.psc_compress.argtypes = [
            u8p, ctypes.c_size_t, u8p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
        ]
        lib.psc_raw_size.restype = ctypes.c_size_t
        lib.psc_raw_size.argtypes = [u8p, ctypes.c_size_t]
        lib.psc_decompress.restype = ctypes.c_size_t
        lib.psc_decompress.argtypes = [
            u8p, ctypes.c_size_t, u8p, ctypes.c_size_t, ctypes.c_int,
        ]
        if getattr(lib, "psl_gather", None) is not None:
            lib.psl_gather.restype = ctypes.c_int
            lib.psl_gather.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, u8p,
                ctypes.c_int,
            ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _as_u8p(buf: bytearray):
    return ctypes.cast(
        (ctypes.c_char * len(buf)).from_buffer(buf), ctypes.POINTER(ctypes.c_uint8)
    )


def _as_const_u8p(data: bytes):
    """Zero-copy read-only view of a bytes object for the C side (which
    only reads src) — avoids duplicating checkpoint-sized buffers."""
    return ctypes.cast(ctypes.c_char_p(data or b"\0"), ctypes.POINTER(ctypes.c_uint8))


def compress_bytes(data: bytes, itemsize: int = 1, n_threads: int = 0) -> bytes:
    """Compress raw bytes (native codec, zlib fallback prefixed 'Z')."""
    lib = _load()
    if lib is None:
        return b"Z" + zlib.compress(data, 6)
    n = len(data)
    cap = lib.psc_max_compressed(n)
    dst = ctypes.create_string_buffer(cap)
    got = lib.psc_compress(
        _as_const_u8p(data),
        n,
        ctypes.cast(dst, ctypes.POINTER(ctypes.c_uint8)),
        cap,
        itemsize,
        n_threads,
    )
    if got == 0 and n > 0:
        raise RuntimeError("psc_compress failed")
    # the input (checkpoint-sized) is passed zero-copy above; copying the
    # compressed OUTPUT once here is the cheap side of the trade
    return b"N" + ctypes.string_at(dst, got)


def decompress_bytes(blob: bytes, n_threads: int = 0) -> bytes:
    tag, payload = blob[:1], blob[1:]
    if tag == b"Z":
        return zlib.decompress(payload)
    if tag != b"N":
        raise ValueError("not a psnative codec blob")
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "blob was written by the native codec but the library is unavailable"
        )
    src = _as_const_u8p(payload)
    raw = lib.psc_raw_size(src, len(payload))
    if raw == 0:
        # raw==0 is either a genuinely empty stream or a bad header —
        # disambiguate by validating the header here
        if (
            len(payload) >= 16
            and payload[:4] == b"PSC1"
            and payload[4] == 1
            and int.from_bytes(payload[8:16], "little") == 0
        ):
            return b""
        raise ValueError("malformed psnative stream")
    dst = bytearray(raw)
    got = lib.psc_decompress(src, len(payload), _as_u8p(dst), raw, n_threads)
    if got != raw:
        raise ValueError("corrupt psnative stream")
    return bytes(dst)


def compress_array(arr: np.ndarray, n_threads: int = 0) -> bytes:
    """Array -> framed compressed blob (parity role: blosc.pack_array)."""
    arr = np.asarray(arr)
    shape = list(arr.shape)  # before ascontiguousarray, which promotes 0-d to 1-d
    arr = np.ascontiguousarray(arr)
    header = json.dumps({"dtype": arr.dtype.str, "shape": shape}).encode()
    body = compress_bytes(arr.tobytes(), itemsize=arr.dtype.itemsize, n_threads=n_threads)
    return MAGIC + len(header).to_bytes(4, "little") + header + body


def decompress_array(blob: bytes, n_threads: int = 0) -> np.ndarray:
    if blob[:4] != MAGIC:
        raise ValueError("not a psnative array blob")
    hlen = int.from_bytes(blob[4:8], "little")
    meta = json.loads(blob[8 : 8 + hlen].decode())
    raw = decompress_bytes(blob[8 + hlen :], n_threads=n_threads)
    return np.frombuffer(raw, dtype=np.dtype(meta["dtype"])).reshape(meta["shape"]).copy()


# ----- reference-name aliases (compression.py:18-46) -----------------------
def g_compress(grad: np.ndarray) -> bytes:
    return compress_array(grad)


def g_decompress(msg: bytes) -> np.ndarray:
    return decompress_array(msg)


def w_compress(weight: np.ndarray) -> bytes:
    return compress_array(weight)


def w_decompress(msg: bytes) -> np.ndarray:
    return decompress_array(msg)
