"""Loss and metric ops (pure jnp, jit-friendly).

Parity targets: the reference computes CrossEntropyLoss
(distributed_worker.py:96, nn_ops.py) and Prec@1/Prec@5 — implemented three
separate times in the reference (nn_ops.py:14-27, sync_replicas_master_nn.py:33-46,
distributed_worker.py:26-38); here, once.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Softmax cross-entropy with integer labels, mean reduction
    (= torch.nn.CrossEntropyLoss)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def next_token_nll(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token negative log-likelihood for an LM batch.

    logits [B, T, V] (position t predicts token t+1), tokens int32 [B, T].
    The single source of the LM loss used by the tensor-, pipeline-, and
    expert-parallel train steps (leading batch-like dims beyond [B] are
    folded in, so [M, B_mb, T] microbatched logits work unchanged).
    """
    logp = jax.nn.log_softmax(logits[..., :-1, :].astype(jnp.float32), axis=-1)
    tgt = tokens[..., 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
    return jnp.mean(nll)


def accuracy(
    logits: jax.Array, labels: jax.Array, topk: Sequence[int] = (1,)
) -> Tuple[jax.Array, ...]:
    """Prec@k for each k, in percent (parity: nn_ops.py:14-27)."""
    maxk = max(topk)
    _, pred = jax.lax.top_k(logits, maxk)  # [B, maxk]
    correct = pred == labels[:, None]
    out = []
    for k in topk:
        out.append(100.0 * jnp.mean(jnp.any(correct[:, :k], axis=-1)))
    return tuple(out)
