"""Ops: losses/metrics, and (see quantize.py) the int8 gradient-compression
kernels that replace the reference's Blosc codec (src/compression.py)."""

from .metrics import accuracy, cross_entropy_loss
