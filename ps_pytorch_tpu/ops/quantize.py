"""int8 symmetric quantize/dequantize — the TPU-native replacement for the
reference's Blosc byte-compression of gradients (/root/reference/src/
compression.py:18-31, snappy codec at :20).

A lossless byte codec is pointless inside XLA programs; the *capability* being
matched is bandwidth reduction on the gradient path (4x for int8), wired into
the collective in parallel/collectives.py. Implementations:

- a pure-jnp reference (runs anywhere; used on the virtual CPU test mesh),
- Pallas TPU kernels (per-tensor and per-block) fusing scale-multiply +
  round + clip + int8 cast on the VPU (8x128 lanes), selected automatically
  on TPU backends and exercised on CPU via PS_TPU_PALLAS_INTERPRET=1
  (pallas interpret mode).

Rounding: "nearest" (default) or "stochastic" — stochastic rounding makes
the quantizer unbiased (E[deq(q(x))] = x), which matters for gradient
aggregation: nearest-rounding bias accumulates over steps, stochastic noise
averages out across workers and time. Stochastic mode needs a PRNG key and
runs on the jnp path (XLA fuses it; the Pallas kernel covers the nearest
hot path).

Scales are symmetric absmax/127, per-tensor (block_size=0) or per-block of
the flattened tensor (block_size>0, tighter error). When `axis_name` is
given the absmax is pmax'd across that mesh axis so every worker quantizes
with the SAME scale — which is what makes the int32 psum of quantized
values an exact sum of the per-worker quantizations (determinism the
reference's per-worker Blosc streams cannot offer).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_LANE = 128
_SUBLANE = 8


def _pallas_mode(x: jax.Array) -> Optional[dict]:
    """None = use jnp; otherwise kwargs for pl.pallas_call."""
    if os.environ.get("PS_TPU_DISABLE_PALLAS"):
        return None
    if os.environ.get("PS_TPU_PALLAS_INTERPRET"):
        return {"interpret": True}
    if jax.default_backend() == "tpu" and x.size >= _LANE * _SUBLANE:
        return {}
    return None


# ------------------------------------------------------------ pallas kernels


def _quant_kernel(x_ref, inv_ref, out_ref):
    out_ref[:] = jnp.clip(
        jnp.round(x_ref[:] * inv_ref[0, 0]), -127.0, 127.0
    ).astype(jnp.int8)


def _quant_rows_kernel(x_ref, inv_ref, out_ref):
    # per-row (= per-quantization-block) scales: inv_ref is [block_rows, 1]
    out_ref[:] = jnp.clip(
        jnp.round(x_ref[:] * inv_ref[:]), -127.0, 127.0
    ).astype(jnp.int8)


def _pallas_quantize_2d(x2: jax.Array, inv_scale: jax.Array, mode: dict) -> jax.Array:
    """x2: f32 [M, 128], M % 8 == 0; inv_scale: f32 scalar -> int8 [M, 128]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = x2.shape[0]
    block_m = min(m, 1024)
    return pl.pallas_call(
        _quant_kernel,
        out_shape=jax.ShapeDtypeStruct((m, _LANE), jnp.int8),
        grid=(pl.cdiv(m, block_m),),
        in_specs=[
            pl.BlockSpec((block_m, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (block_m, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        **mode,
    )(x2, inv_scale.reshape(1, 1))


def _pallas_quantize_rows(xb: jax.Array, inv: jax.Array, mode: dict) -> jax.Array:
    """xb: f32 [NB, BS] (BS % 128 == 0), inv: f32 [NB, 1] -> int8 [NB, BS]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb, bs = xb.shape
    block_nb = min(nb, max(_SUBLANE, 4096 // (bs // _LANE)))
    block_nb = -(-block_nb // _SUBLANE) * _SUBLANE  # sublane-align the tile
    return pl.pallas_call(
        _quant_rows_kernel,
        out_shape=jax.ShapeDtypeStruct((nb, bs), jnp.int8),
        grid=(pl.cdiv(nb, block_nb),),
        in_specs=[
            pl.BlockSpec((block_nb, bs), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_nb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (block_nb, bs), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        **mode,
    )(xb, inv)


# ---------------------------------------------------------------- public API


def _round(x: jax.Array, rounding: str, key: Optional[jax.Array]) -> jax.Array:
    if rounding == "nearest":
        return jnp.round(x)
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding needs a PRNG key")
        # floor(x + U[0,1)): P(round up) == frac(x) -> unbiased
        return jnp.floor(x + jax.random.uniform(key, x.shape, jnp.float32))
    raise ValueError(f"unknown rounding {rounding!r}")


def quantize_int8(
    x: jax.Array,
    axis_name: Optional[str] = None,
    block_size: int = 0,
    rounding: str = "nearest",
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization.

    Returns ``(q, scale)``. Per-tensor mode: q has x's shape, scale is scalar.
    Per-block mode: q is [n_blocks, block_size] over the zero-padded flattened
    tensor, scale is [n_blocks, 1]. Pass the original shape to
    ``dequantize_int8`` to undo.
    """
    x = x.astype(jnp.float32)
    mode = _pallas_mode(x) if rounding == "nearest" else None
    if block_size:
        flat = x.reshape(-1)
        n = flat.shape[0]
        nb = -(-n // block_size)
        flat = jnp.pad(flat, (0, nb * block_size - n))
        xb = flat.reshape(nb, block_size)
        absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        if axis_name is not None:
            absmax = lax.pmax(absmax, axis_name)
        scale = absmax / 127.0
        inv = jnp.where(absmax > 0, 127.0 / jnp.maximum(absmax, 1e-30), 0.0)
        # VMEM budget: an 8-sublane f32 tile of a huge block_size would not
        # fit on chip (~16MB VMEM, double-buffered) — cap the tile at 2MB
        # and fall back to jnp beyond it
        fits_vmem = _SUBLANE * block_size * 4 <= 2 * 1024 * 1024
        if (
            mode is not None
            and block_size % _LANE == 0
            and nb % _SUBLANE == 0
            and fits_vmem
        ):
            q = _pallas_quantize_rows(xb, inv, mode)
        else:
            q = jnp.clip(_round(xb * inv, rounding, key), -127, 127).astype(jnp.int8)
        return q, scale

    absmax = jnp.max(jnp.abs(x))
    if axis_name is not None:
        absmax = lax.pmax(absmax, axis_name)
    scale = absmax / 127.0
    inv = jnp.where(absmax > 0, 127.0 / jnp.maximum(absmax, 1e-30), 0.0)
    if mode is not None:
        n = x.size
        rows = -(-n // _LANE)
        rows_pad = -(-rows // _SUBLANE) * _SUBLANE
        flat = jnp.pad(x.reshape(-1), (0, rows_pad * _LANE - n))
        q2 = _pallas_quantize_2d(flat.reshape(rows_pad, _LANE), inv, mode)
        q = q2.reshape(-1)[:n].reshape(x.shape)
    else:
        q = jnp.clip(_round(x * inv, rounding, key), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_int8(
    q: jax.Array,
    scale: jax.Array,
    block_size: int = 0,
    shape: Optional[Tuple[int, ...]] = None,
) -> jax.Array:
    """Invert `quantize_int8` (q may be an int32 psum of int8 payloads)."""
    out = q.astype(jnp.float32) * scale
    if block_size:
        if shape is None:
            raise ValueError("block mode dequantization needs the original shape")
        n = int(np.prod(shape))
        out = out.reshape(-1)[:n].reshape(shape)
    return out


# ------------------------------------------- homomorphic (compressed-domain)


_INT8_PEAK = 127  # symmetric int8 payloads live in [-127, 127]

# exact-sum capacity per accumulator dtype: the largest number of
# full-scale (|q| = 127) int8 payloads whose sum provably fits. int16
# holds 258 (258 * 127 = 32766 <= 32767), int32 holds 16_909_320
# (16_909_320 * 127 = 2_147_483_640 <= 2^31 - 1).
ACCUM_CAPACITY = {
    "int16": (2 ** 15 - 1) // _INT8_PEAK,
    "int32": (2 ** 31 - 1) // _INT8_PEAK,
}


def accum_dtype(num_summands: int):
    """Smallest integer dtype whose range provably holds a sum of
    ``num_summands`` full-scale int8 payloads — the wire dtype of a
    homomorphic psum (collectives.quantized_psum with
    wire_domain="homomorphic"). The sum of n values in [-127, 127] is
    bounded by n * 127, so the choice is a static function of the mesh
    size: int16 through 258 workers (2 bytes/element on the wire vs 4
    for the dequant path's int32), int32 through ~16.9M. Beyond that no
    supported accumulator is exact — raise rather than wrap."""
    if num_summands < 1:
        raise ValueError(f"accum_dtype needs >= 1 summand, got {num_summands}")
    if num_summands <= ACCUM_CAPACITY["int16"]:
        return jnp.int16
    if num_summands <= ACCUM_CAPACITY["int32"]:
        return jnp.int32
    raise ValueError(
        f"homomorphic accumulation over {num_summands} full-scale int8 "
        f"payloads can overflow int32 (capacity "
        f"{ACCUM_CAPACITY['int32']}) — use wire_domain='dequant'"
    )


def homomorphic_rescale(acc: jax.Array, divisor) -> jax.Array:
    """Integer lattice rescale: ``round(acc / divisor)`` back to int8.

    ``acc`` is an exact integer accumulation of at most ``divisor``
    int8 payloads on a SHARED quantization lattice (|acc| <= divisor *
    127), so the rounded quotient provably fits [-127, 127] — the
    compressed-domain replacement for the dequant wire's round-2
    widen -> requantize: no f32 on the wire, no new scale rows, one
    deterministic rounding at the shared scale's granularity.
    ``divisor`` may be a traced scalar (the adaptive aggregation
    count). The divide runs in f32 COMPUTE (never on the wire), which
    represents the accumulator exactly through 2^24 — every mesh the
    int16/int32 capacity table admits below ~132k workers."""
    q = jnp.round(acc.astype(jnp.float32) / divisor)
    return jnp.clip(q, -_INT8_PEAK, _INT8_PEAK).astype(jnp.int8)


def _accum_rescale_kernel(recv_ref, div_ref, out_ref):
    acc = jnp.sum(recv_ref[:].astype(jnp.int32), axis=0, keepdims=True)
    q = jnp.round(acc.astype(jnp.float32) / div_ref[0, 0])
    out_ref[:] = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)


def _pallas_accum_rescale(recv: jax.Array, divisor, mode: dict) -> jax.Array:
    """recv: int8 [n, s] with s % 128 == 0 -> int8 [s]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, s = recv.shape
    # VMEM budget: the n x block_s int8 tile (plus int32 widening) must
    # fit on chip; 16Ki lanes x n<=~258 rows stays well under it
    block_s = min(s, 16384 // _LANE * _LANE)
    out = pl.pallas_call(
        _accum_rescale_kernel,
        out_shape=jax.ShapeDtypeStruct((1, s), jnp.int8),
        grid=(pl.cdiv(s, block_s),),
        in_specs=[
            pl.BlockSpec((n, block_s), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, block_s), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        **mode,
    )(recv, jnp.asarray(divisor, jnp.float32).reshape(1, 1))
    return out.reshape(-1)


def accumulate_rescale_int8(recv: jax.Array, divisor) -> jax.Array:
    """The homomorphic gather hop's fused hot path: exact integer
    accumulation over the worker rows of an all_to_all'd int8 payload
    ``[n, s]`` + lattice rescale back to int8 — the compressed-domain
    replacement for the dequant wire's widen -> requantize, fused into
    ONE Pallas VPU pass on TPU (int8 load, int32 accumulate, f32
    divide/round, int8 store: no widened intermediate ever reaches HBM).
    Exercised on CPU via PS_TPU_PALLAS_INTERPRET=1 like the flash
    kernels; the pure-jnp path is bit-identical (same sum, same f32
    divide, same round-half-even). ``divisor`` may be traced (the
    adaptive aggregation count rides the SMEM scalar operand)."""
    mode = _pallas_mode(recv)
    if mode is not None and recv.shape[1] % _LANE == 0:
        return _pallas_accum_rescale(recv, divisor, mode)
    return homomorphic_rescale(
        jnp.sum(recv.astype(jnp.int32), axis=0), divisor
    )


def quantization_error(x: jax.Array, block_size: int = 0) -> jax.Array:
    """Max abs round-trip error — used by tests and for Msg(MB)-style
    introspection (the reference logs compressed message sizes,
    tiny_tuning_parser.py:18; for int8 the 'compression ratio' is a constant
    4x plus scale overhead, and the interesting number is this error)."""
    q, s = quantize_int8(x, block_size=block_size)
    return jnp.max(jnp.abs(dequantize_int8(q, s, block_size, x.shape) - x))
