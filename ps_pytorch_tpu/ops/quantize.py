"""int8 symmetric quantize/dequantize — the TPU-native replacement for the
reference's Blosc byte-compression of gradients (/root/reference/src/
compression.py:18-31, snappy codec at :20).

A lossless byte codec is pointless inside XLA programs; the *capability* being
matched is bandwidth reduction on the gradient path (4x for int8), wired into
the collective in parallel/collectives.py. Implementations:

- a pure-jnp reference (runs anywhere; used on the virtual CPU test mesh),
- Pallas TPU kernels (per-tensor and per-block) fusing scale-multiply +
  round + clip + int8 cast on the VPU (8x128 lanes), selected automatically
  on TPU backends and exercised on CPU via PS_TPU_PALLAS_INTERPRET=1
  (pallas interpret mode).

Rounding: "nearest" (default) or "stochastic" — stochastic rounding makes
the quantizer unbiased (E[deq(q(x))] = x), which matters for gradient
aggregation: nearest-rounding bias accumulates over steps, stochastic noise
averages out across workers and time. Stochastic mode needs a PRNG key and
runs on the jnp path (XLA fuses it; the Pallas kernel covers the nearest
hot path).

Scales are symmetric absmax/127, per-tensor (block_size=0) or per-block of
the flattened tensor (block_size>0, tighter error). When `axis_name` is
given the absmax is pmax'd across that mesh axis so every worker quantizes
with the SAME scale — which is what makes the int32 psum of quantized
values an exact sum of the per-worker quantizations (determinism the
reference's per-worker Blosc streams cannot offer).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_LANE = 128
_SUBLANE = 8


def _pallas_mode(x: jax.Array) -> Optional[dict]:
    """None = use jnp; otherwise kwargs for pl.pallas_call."""
    if os.environ.get("PS_TPU_DISABLE_PALLAS"):
        return None
    if os.environ.get("PS_TPU_PALLAS_INTERPRET"):
        return {"interpret": True}
    if jax.default_backend() == "tpu" and x.size >= _LANE * _SUBLANE:
        return {}
    return None


# ------------------------------------------------------------ pallas kernels


def _quant_kernel(x_ref, inv_ref, out_ref):
    out_ref[:] = jnp.clip(
        jnp.round(x_ref[:] * inv_ref[0, 0]), -127.0, 127.0
    ).astype(jnp.int8)


def _quant_rows_kernel(x_ref, inv_ref, out_ref):
    # per-row (= per-quantization-block) scales: inv_ref is [block_rows, 1]
    out_ref[:] = jnp.clip(
        jnp.round(x_ref[:] * inv_ref[:]), -127.0, 127.0
    ).astype(jnp.int8)


def _pallas_quantize_2d(x2: jax.Array, inv_scale: jax.Array, mode: dict) -> jax.Array:
    """x2: f32 [M, 128], M % 8 == 0; inv_scale: f32 scalar -> int8 [M, 128]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = x2.shape[0]
    block_m = min(m, 1024)
    return pl.pallas_call(
        _quant_kernel,
        out_shape=jax.ShapeDtypeStruct((m, _LANE), jnp.int8),
        grid=(pl.cdiv(m, block_m),),
        in_specs=[
            pl.BlockSpec((block_m, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (block_m, _LANE), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        **mode,
    )(x2, inv_scale.reshape(1, 1))


def _pallas_quantize_rows(xb: jax.Array, inv: jax.Array, mode: dict) -> jax.Array:
    """xb: f32 [NB, BS] (BS % 128 == 0), inv: f32 [NB, 1] -> int8 [NB, BS]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb, bs = xb.shape
    block_nb = min(nb, max(_SUBLANE, 4096 // (bs // _LANE)))
    block_nb = -(-block_nb // _SUBLANE) * _SUBLANE  # sublane-align the tile
    return pl.pallas_call(
        _quant_rows_kernel,
        out_shape=jax.ShapeDtypeStruct((nb, bs), jnp.int8),
        grid=(pl.cdiv(nb, block_nb),),
        in_specs=[
            pl.BlockSpec((block_nb, bs), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_nb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (block_nb, bs), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        **mode,
    )(xb, inv)


# ---------------------------------------------------------------- public API


def _round(x: jax.Array, rounding: str, key: Optional[jax.Array]) -> jax.Array:
    if rounding == "nearest":
        return jnp.round(x)
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding needs a PRNG key")
        # floor(x + U[0,1)): P(round up) == frac(x) -> unbiased
        return jnp.floor(x + jax.random.uniform(key, x.shape, jnp.float32))
    raise ValueError(f"unknown rounding {rounding!r}")


def quantize_int8(
    x: jax.Array,
    axis_name: Optional[str] = None,
    block_size: int = 0,
    rounding: str = "nearest",
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization.

    Returns ``(q, scale)``. Per-tensor mode: q has x's shape, scale is scalar.
    Per-block mode: q is [n_blocks, block_size] over the zero-padded flattened
    tensor, scale is [n_blocks, 1]. Pass the original shape to
    ``dequantize_int8`` to undo.
    """
    x = x.astype(jnp.float32)
    mode = _pallas_mode(x) if rounding == "nearest" else None
    if block_size:
        flat = x.reshape(-1)
        n = flat.shape[0]
        nb = -(-n // block_size)
        flat = jnp.pad(flat, (0, nb * block_size - n))
        xb = flat.reshape(nb, block_size)
        absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        if axis_name is not None:
            absmax = lax.pmax(absmax, axis_name)
        scale = absmax / 127.0
        inv = jnp.where(absmax > 0, 127.0 / jnp.maximum(absmax, 1e-30), 0.0)
        # VMEM budget: an 8-sublane f32 tile of a huge block_size would not
        # fit on chip (~16MB VMEM, double-buffered) — cap the tile at 2MB
        # and fall back to jnp beyond it
        fits_vmem = _SUBLANE * block_size * 4 <= 2 * 1024 * 1024
        if (
            mode is not None
            and block_size % _LANE == 0
            and nb % _SUBLANE == 0
            and fits_vmem
        ):
            q = _pallas_quantize_rows(xb, inv, mode)
        else:
            q = jnp.clip(_round(xb * inv, rounding, key), -127, 127).astype(jnp.int8)
        return q, scale

    absmax = jnp.max(jnp.abs(x))
    if axis_name is not None:
        absmax = lax.pmax(absmax, axis_name)
    scale = absmax / 127.0
    inv = jnp.where(absmax > 0, 127.0 / jnp.maximum(absmax, 1e-30), 0.0)
    if mode is not None:
        n = x.size
        rows = -(-n // _LANE)
        rows_pad = -(-rows // _SUBLANE) * _SUBLANE
        flat = jnp.pad(x.reshape(-1), (0, rows_pad * _LANE - n))
        q2 = _pallas_quantize_2d(flat.reshape(rows_pad, _LANE), inv, mode)
        q = q2.reshape(-1)[:n].reshape(x.shape)
    else:
        q = jnp.clip(_round(x * inv, rounding, key), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_int8(
    q: jax.Array,
    scale: jax.Array,
    block_size: int = 0,
    shape: Optional[Tuple[int, ...]] = None,
) -> jax.Array:
    """Invert `quantize_int8` (q may be an int32 psum of int8 payloads)."""
    out = q.astype(jnp.float32) * scale
    if block_size:
        if shape is None:
            raise ValueError("block mode dequantization needs the original shape")
        n = int(np.prod(shape))
        out = out.reshape(-1)[:n].reshape(shape)
    return out


# ------------------------------------------ int4 lattice codec + traced peak

_INT8_PEAK = 127  # symmetric int8 payloads live in [-127, 127]
_INT4_PEAK = 7    # symmetric int4 payloads live in [-7, 7] (two per byte)
_INT4_BIAS = 8    # nibble storage bias: value + 8 in [1, 15]

# per-bucket precision tags (the adaptive-precision wire,
# PSConfig.precision_adapt): a traced int32 per bucket selects the
# lattice peak that bucket quantizes onto THIS window. The payload's
# static dtype (and therefore the traced program and its physical wire
# bytes) never changes — adaptation reshapes VALUES, never bytes
# (PSC108's stance); the per-tag EFFECTIVE bytes (what a byte-honest
# transport ships: 0, half, one, or payload-width bytes per element)
# are the controller's budget currency and telemetry evidence.
PREC_SKIP = 0   # peak 0: q == 0, scale == 0 — EF keeps the whole gradient
PREC_4BIT = 1   # peak 7: the int4 lattice (pack_int4 ships 2/byte)
PREC_INT8 = 2   # peak 127: the committed-contract int8 lattice
PREC_HI = 3     # peak precision_hi_peak(cfg): finest the payload carries
PRECISION_TAGS = (PREC_SKIP, PREC_4BIT, PREC_INT8, PREC_HI)
PRECISION_TAG_NAMES = ("skip", "4bit", "int8", "hi")


def precision_peaks(hi_peak: int) -> np.ndarray:
    """The tag -> lattice-peak table (f32, indexable by a traced tag)."""
    return np.asarray(
        [0.0, float(_INT4_PEAK), float(_INT8_PEAK), float(hi_peak)],
        np.float32,
    )


def precision_bytes_per_element(hi_peak: int) -> Tuple[float, ...]:
    """Effective wire bytes per f32 gradient element by tag: skip ships
    nothing, int4 packs two values per byte, int8 one, and the HI tag
    costs the minimal integer width that holds its peak."""
    hi_bytes = 1.0 if hi_peak <= _INT8_PEAK else (
        2.0 if hi_peak <= 2 ** 15 - 1 else 4.0
    )
    return (0.0, 0.5, 1.0, hi_bytes)


def pack_int4(q: jax.Array) -> jax.Array:
    """Pack int4 lattice values (int8 storage, each in [-7, 7]) two per
    byte: value + 8 in the low/high nibble of a uint8. Odd-length
    (flattened) inputs pad the final high nibble with the bias (value
    0), so ``unpack_int4(pack_int4(q), q.size)`` round-trips any bucket
    length — the carved buckets the adaptive wire prices at size/2
    effective bytes are exactly this codec's output size."""
    flat = q.reshape(-1).astype(jnp.int8)
    n = flat.shape[0]
    flat = jnp.pad(flat, (0, n % 2))
    lo = (flat[0::2] + _INT4_BIAS).astype(jnp.uint8)
    hi = (flat[1::2] + _INT4_BIAS).astype(jnp.uint8)
    return lo | (hi << 4)


def unpack_int4(packed: jax.Array, n: int) -> jax.Array:
    """Invert ``pack_int4``: uint8 [ceil(n/2)] -> int8 [n] in [-7, 7]."""
    lo = (packed & 0xF).astype(jnp.int8) - _INT4_BIAS
    hi = ((packed >> 4) & 0xF).astype(jnp.int8) - _INT4_BIAS
    return jnp.stack([lo, hi], axis=1).reshape(-1)[:n]


def quantize_lattice(
    x: jax.Array,
    peak,
    axis_name=None,
    block_size: int = 0,
    hi_peak: int = _INT8_PEAK,
    out_dtype=jnp.int8,
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric quantize onto a lattice whose peak may be TRACED — the
    adaptive-precision generalization of ``quantize_int8`` (identical
    arithmetic and scale geometry at ``peak == 127``: same ``peak /
    absmax`` association, same pmax-shared scales, so an all-int8 tag
    vector is bit-exact against the static path).

    ``peak`` is a scalar from {0, 7, 127, hi_peak} selected by a traced
    per-bucket tag (ops-level it is just any non-negative scalar): peak
    0 gives ``q == 0`` and ``scale == 0`` — the SKIP tag's semantics,
    the bucket contributes nothing and error feedback keeps the whole
    gradient as residual. The traced clamp at ±peak is what bounds the
    runtime values; the OUTER STATIC clamp at ±``hi_peak`` is redundant
    at runtime (peak <= hi_peak by construction) but is what lets the
    psnumerics analyzer (check/numerics.py carries scalar bounds only
    through static clamps) prove PSC113's accumulation-capacity bound
    for the adaptive wire. Runs on the jnp path — the Pallas kernels
    stay the static int8 hot path.

    Returns ``(q, scale)``: q in ``out_dtype`` (the wire payload dtype:
    int8 when hi_peak <= 127, else the minimal wider int), scale =
    absmax / peak (0 where peak == 0). Per-tensor or per-block geometry
    exactly as ``quantize_int8``."""
    x = x.astype(jnp.float32)
    peak_f = jnp.asarray(peak, jnp.float32)

    def finish(xb, absmax):
        inv = jnp.where(absmax > 0, peak_f / jnp.maximum(absmax, 1e-30), 0.0)
        q = jnp.round(xb * inv)
        q = jnp.clip(q, -peak_f, peak_f)  # traced bound: exact at runtime
        q = jnp.clip(q, -float(hi_peak), float(hi_peak)).astype(out_dtype)
        scale = jnp.where(
            peak_f > 0, absmax / jnp.maximum(peak_f, 1.0), 0.0
        )
        return q, scale

    if block_size:
        flat = x.reshape(-1)
        n = flat.shape[0]
        nb = -(-n // block_size)
        flat = jnp.pad(flat, (0, nb * block_size - n))
        xb = flat.reshape(nb, block_size)
        absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
        if axis_name is not None:
            absmax = lax.pmax(absmax, axis_name)
        return finish(xb, absmax)
    absmax = jnp.max(jnp.abs(x))
    if axis_name is not None:
        absmax = lax.pmax(absmax, axis_name)
    return finish(x, absmax)


def quantize_int4(
    x: jax.Array,
    axis_name=None,
    block_size: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric 4-bit quantization: the int8 scheme's exact geometry
    (same block carving, same pmax-shared scales) at peak 7. Returns
    ``(q, scale)`` with q as int8 STORAGE in [-7, 7] — ``pack_int4``
    ships two values per byte. Nearest rounding only (the int4 lattice
    exists for the shared-scale homomorphic wire, where per-worker
    stochastic draws are incoherent)."""
    return quantize_lattice(
        x, float(_INT4_PEAK), axis_name=axis_name, block_size=block_size,
        hi_peak=_INT4_PEAK, out_dtype=jnp.int8,
    )


# ------------------------------------------- homomorphic (compressed-domain)


def accum_capacity(dtype_name: str, peak: int = _INT8_PEAK) -> int:
    """Largest number of full-scale (|q| = ``peak``) lattice payloads
    whose sum provably fits ``dtype_name``: floor(dtype_max / peak).
    The int8 lattice (peak 127) gives int16 a capacity of 258 workers;
    the int4 lattice (peak 7) more than doubles the doubling — 4681
    workers before the homomorphic int16 wire must widen."""
    bits = {"int16": 15, "int32": 31}[dtype_name]
    return (2 ** bits - 1) // int(peak)


# the int8-lattice capacity table (the committed-contract wire): int16
# holds 258 (258 * 127 = 32766 <= 32767), int32 holds 16_909_320
# (16_909_320 * 127 = 2_147_483_640 <= 2^31 - 1). Peak-generalized
# lookups go through accum_capacity(dtype, peak).
ACCUM_CAPACITY = {
    "int16": accum_capacity("int16"),
    "int32": accum_capacity("int32"),
}


def accum_dtype(num_summands: int, peak: int = _INT8_PEAK):
    """Smallest integer dtype whose range provably holds a sum of
    ``num_summands`` full-scale lattice payloads of ``|q| <= peak`` —
    the wire dtype of a homomorphic psum (collectives.quantized_psum
    with wire_domain="homomorphic"). The sum of n values in [-peak,
    peak] is bounded by n * peak, so the choice is a static function of
    the mesh size and the lattice: on the int8 lattice (peak 127) int16
    carries 258 workers (2 bytes/element on the wire vs 4 for the
    dequant path's int32); on the int4 lattice (peak 7) int16 carries
    4681. Beyond int32's capacity no supported accumulator is exact —
    raise rather than wrap."""
    if num_summands < 1:
        raise ValueError(f"accum_dtype needs >= 1 summand, got {num_summands}")
    if peak < 1:
        raise ValueError(f"accum_dtype needs peak >= 1, got {peak}")
    if num_summands <= accum_capacity("int16", peak):
        return jnp.int16
    if num_summands <= accum_capacity("int32", peak):
        return jnp.int32
    raise ValueError(
        f"homomorphic accumulation over {num_summands} full-scale "
        f"peak-{peak} payloads can overflow int32 (capacity "
        f"{accum_capacity('int32', peak)}) — use wire_domain='dequant'"
    )


def homomorphic_rescale(acc: jax.Array, divisor) -> jax.Array:
    """Integer lattice rescale: ``round(acc / divisor)`` back to int8.

    ``acc`` is an exact integer accumulation of at most ``divisor``
    int8 payloads on a SHARED quantization lattice (|acc| <= divisor *
    127), so the rounded quotient provably fits [-127, 127] — the
    compressed-domain replacement for the dequant wire's round-2
    widen -> requantize: no f32 on the wire, no new scale rows, one
    deterministic rounding at the shared scale's granularity.
    ``divisor`` may be a traced scalar (the adaptive aggregation
    count). The divide runs in f32 COMPUTE (never on the wire), which
    represents the accumulator exactly through 2^24 — every mesh the
    int16/int32 capacity table admits below ~132k workers."""
    q = jnp.round(acc.astype(jnp.float32) / divisor)
    return jnp.clip(q, -_INT8_PEAK, _INT8_PEAK).astype(jnp.int8)


def _accum_rescale_kernel(recv_ref, div_ref, out_ref):
    acc = jnp.sum(recv_ref[:].astype(jnp.int32), axis=0, keepdims=True)
    q = jnp.round(acc.astype(jnp.float32) / div_ref[0, 0])
    out_ref[:] = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)


def _pallas_accum_rescale(recv: jax.Array, divisor, mode: dict) -> jax.Array:
    """recv: int8 [n, s] with s % 128 == 0 -> int8 [s]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, s = recv.shape
    # VMEM budget: the n x block_s int8 tile (plus int32 widening) must
    # fit on chip; 16Ki lanes x n<=~258 rows stays well under it
    block_s = min(s, 16384 // _LANE * _LANE)
    out = pl.pallas_call(
        _accum_rescale_kernel,
        out_shape=jax.ShapeDtypeStruct((1, s), jnp.int8),
        grid=(pl.cdiv(s, block_s),),
        in_specs=[
            pl.BlockSpec((n, block_s), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, block_s), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        **mode,
    )(recv, jnp.asarray(divisor, jnp.float32).reshape(1, 1))
    return out.reshape(-1)


def accumulate_rescale_int8(recv: jax.Array, divisor) -> jax.Array:
    """The homomorphic gather hop's fused hot path: exact integer
    accumulation over the worker rows of an all_to_all'd int8 payload
    ``[n, s]`` + lattice rescale back to int8 — the compressed-domain
    replacement for the dequant wire's widen -> requantize, fused into
    ONE Pallas VPU pass on TPU (int8 load, int32 accumulate, f32
    divide/round, int8 store: no widened intermediate ever reaches HBM).
    Exercised on CPU via PS_TPU_PALLAS_INTERPRET=1 like the flash
    kernels; the pure-jnp path is bit-identical (same sum, same f32
    divide, same round-half-even). ``divisor`` may be traced (the
    adaptive aggregation count rides the SMEM scalar operand)."""
    mode = _pallas_mode(recv)
    if mode is not None and recv.shape[1] % _LANE == 0:
        return _pallas_accum_rescale(recv, divisor, mode)
    return homomorphic_rescale(
        jnp.sum(recv.astype(jnp.int32), axis=0), divisor
    )


def quantization_error(x: jax.Array, block_size: int = 0) -> jax.Array:
    """Max abs round-trip error — used by tests and for Msg(MB)-style
    introspection (the reference logs compressed message sizes,
    tiny_tuning_parser.py:18; for int8 the 'compression ratio' is a constant
    4x plus scale overhead, and the interesting number is this error)."""
    q, s = quantize_int8(x, block_size=block_size)
    return jnp.max(jnp.abs(dequantize_int8(q, s, block_size, x.shape) - x))
