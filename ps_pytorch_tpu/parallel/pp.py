"""Pipeline (stage) parallelism for the transformer family.

Absent from the reference (SURVEY.md section 2: "TP / PP / SP / EP / CP ...
absent"); built here so model depth scales across the mesh. The schedule is
GPipe mapped onto SPMD collectives:

- the transformer's blocks are STACKED into [depth, ...] leaves and the
  depth axis is sharded over the `stage` mesh axis — each device owns
  depth/n_stages contiguous blocks and runs them with a local `lax.scan`;
- the global batch is cut into M microbatches; one jitted `lax.scan` over
  M + S - 1 ticks runs the pipeline: each tick every stage `ppermute`s its
  previous activation to the next stage, stage 0 injects the next
  microbatch's embedding, the last stage collects finished microbatches;
- embeddings / norms / unembedding are replicated (stage 0 embeds, the
  last stage projects to logits; psum completes the loss on all stages).

Bubble fraction is the usual (S-1)/(M+S-1) — choose M >= S. All ticks are
one compiled loop body (uniform control flow; `jnp.where` does the
schedule gating), so XLA overlaps each tick's ppermute with the next
tick's block compute where the hardware allows.

Gradient correctness uses the same rule as parallel/tp.py: under
shard_map(check_vma=False), AD computes exact gradients of the SUM over
shards of the per-shard outputs, so the train step differentiates loss/S
and psums the replicated leaves' gradients afterwards.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional, Sequence, TYPE_CHECKING

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.metrics import next_token_nll
from .tp import opt_state_specs

if TYPE_CHECKING:  # pragma: no cover
    from ..models.transformer import TransformerConfig

PP_AXIS = "stage"


def make_pp_mesh(
    num_stages: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D pipeline mesh (axis 'stage')."""
    from .mesh import make_mesh

    return make_mesh(num_workers=num_stages, devices=devices, axis_name=PP_AXIS)


def to_pp_layout(cfg: "TransformerConfig", params: Dict) -> Dict:
    """Stack the per-block param dicts into [depth, ...] leaves so the
    depth axis can be mesh-sharded and scanned."""
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *params["blocks"])
    return out


def from_pp_layout(cfg: "TransformerConfig", params_pp: Dict) -> Dict:
    """Inverse of `to_pp_layout` (checkpoint interchange)."""
    out = {k: v for k, v in params_pp.items() if k != "blocks"}
    out["blocks"] = [
        jax.tree.map(lambda x: x[i], params_pp["blocks"])
        for i in range(cfg.depth)
    ]
    return out


def pp_param_specs(cfg: "TransformerConfig", axis: str = PP_AXIS) -> Dict:
    """Stacked blocks shard their leading (depth) dim over the stage axis;
    everything else is replicated."""
    blk = {
        "ln1": P(axis),
        "wqkv": P(axis),
        "wo": P(axis),
        "ln2": P(axis),
        "w_up": P(axis),
        "w_down": P(axis),
    }
    return {"embed": P(), "pos_embed": P(), "out_norm": P(), "blocks": blk}


def shard_params_pp(
    cfg: "TransformerConfig", params_pp: Dict, mesh: Mesh, axis: str = PP_AXIS
) -> Dict:
    n = mesh.shape[axis]
    if cfg.depth % n:
        raise ValueError(f"depth {cfg.depth} not divisible by {n} stages")
    from .mesh import place_on_mesh

    return place_on_mesh(params_pp, mesh, pp_param_specs(cfg, axis))


def _block(cfg: "TransformerConfig", x, blk):
    """One transformer block — the same function the oracle runs."""
    from ..models.transformer import local_attention, transformer_block

    return transformer_block(cfg, x, blk, local_attention(cfg))


def gpipe_fold(
    axis_name: str,
    tokens: jax.Array,  # int32 [M, B_mb, T] microbatched (this column's)
    dim: int,
    cd,
    embed: Callable,  # mb_idx -> [B_mb, T, dim] activations (stage 0)
    run_local: Callable,  # x -> (y, aux_scalar) through this stage's blocks
    mb_loss: Callable,  # (y, tok_mb) -> scalar loss for one microbatch
):
    """THE GPipe tick schedule — the single implementation shared by the
    dense pipeline (here), MoE-in-PP (parallel/pp_moe.py), and the 3-D
    dp x pp x tp composition (parallel/dp_tp_pp.py); only the per-stage
    block body, embedding, and loss head differ.

    One `lax.scan` over M + S - 1 ticks: every tick each stage ppermutes
    its previous activation to the next stage, stage 0 injects the next
    microbatch's embedding, and the loss head is folded INTO the tick per
    finished microbatch — so the largest activation ever live is one
    microbatch's [B_mb, T, V] logits, never [M, B_mb, T, V] (a PP stage's
    memory must scale with the microbatch, not the global batch). The
    loss value is computed uniformly on every stage (SPMD control flow);
    only the last stage's survives the mask+psum. `run_local`'s aux
    output (e.g. MoE load-balance) is accumulated over VALID ticks only —
    warmup/drain ticks process garbage activations whose statistics must
    not leak.

    Returns (task_loss, aux_sum): task replicated within the column via
    the stage psum-mask and already divided by M (mean of equal-size
    per-microbatch means == global mean); aux_sum is the raw valid-tick
    sum (normalize at the caller).
    """
    n = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m, b_mb, t = tokens.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    y0 = jnp.zeros((b_mb, t, dim), cd)

    def tick(carry, tk):
        y, loss_sum, aux_sum = carry
        inbound = lax.ppermute(y, axis_name, perm)
        x_in = jnp.where(stage == 0, embed(tk), inbound)
        y_new, aux_tick = run_local(x_in)
        mine = tk - stage  # the microbatch THIS stage processed this tick
        aux_sum = aux_sum + jnp.where(
            (mine >= 0) & (mine < m), aux_tick, 0.0
        )
        done = tk - (n - 1)
        tok_mb = lax.dynamic_index_in_dim(
            tokens, jnp.clip(done, 0, m - 1), 0, keepdims=False
        )
        loss_sum = loss_sum + jnp.where(
            (done >= 0) & (done < m), mb_loss(y_new, tok_mb), 0.0
        )
        return (y_new, loss_sum, aux_sum), None

    zero = jnp.zeros((), jnp.float32)
    (_, loss_sum, aux_sum), _ = lax.scan(
        tick, (y0, zero, zero), jnp.arange(m + n - 1)
    )
    task = lax.psum(jnp.where(stage == n - 1, loss_sum / m, 0.0), axis_name)
    return task, aux_sum


def _pp_logits_and_loss(
    cfg: "TransformerConfig",
    params: Dict,  # PP layout, LOCAL shards (inside shard_map)
    tokens: jax.Array,  # int32 [M, B_mb, T] microbatched, replicated
    axis_name: str,
):
    """Run the pipeline schedule; returns the scalar mean next-token loss
    (identical on every stage, via psum of the last stage's value)."""
    from ..models.transformer import _rms_norm

    m = tokens.shape[0]
    pos = jnp.arange(tokens.shape[2])
    cd = cfg.effective_compute_dtype  # blocks emit compute_dtype activations

    def local_blocks(x):
        body = lambda x, blk: (_block(cfg, x, blk), None)
        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = lax.scan(body, x, params["blocks"])
        return x, jnp.zeros((), jnp.float32)

    def embed(mb_idx):
        tok = lax.dynamic_index_in_dim(
            tokens, jnp.clip(mb_idx, 0, m - 1), 0, keepdims=False
        )
        return (params["embed"][tok] + params["pos_embed"][pos][None]).astype(cd)

    def mb_loss(y, tok_mb):
        xf = _rms_norm(y, params["out_norm"].astype(cd))
        logits = xf @ params["embed"].T.astype(cd)  # [B_mb, T, V]
        return next_token_nll(logits, tok_mb)

    task, _ = gpipe_fold(
        axis_name, tokens, cfg.dim, cd, embed, local_blocks, mb_loss
    )
    return task


def make_pp_train_step(
    cfg: "TransformerConfig",
    tx: optax.GradientTransformation,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = PP_AXIS,
    donate: bool = True,
):
    """Jitted PP LM train step: (params_pp, opt_state, tokens [B, T]) ->
    (params_pp, opt_state, loss). Block params/opt state sharded over the
    stage axis; tokens replicated and cut into `num_microbatches` equal
    microbatches inside the step."""
    specs_tree = pp_param_specs(cfg, axis_name)

    def shard_fn(params, opt_state, tokens):
        n = lax.axis_size(axis_name)
        bsz, t = tokens.shape
        if bsz % num_microbatches:  # static shape: raises at trace time
            raise ValueError(
                f"batch {bsz} not divisible by {num_microbatches} microbatches"
            )
        mb = tokens.reshape(num_microbatches, bsz // num_microbatches, t)

        # same AD rule as tp.py: grads of sum-over-shards => scale by 1/n,
        # then psum the replicated leaves' partial grads
        loss, grads = jax.value_and_grad(
            lambda p: _pp_logits_and_loss(cfg, p, mb, axis_name) / n
        )(params)
        grads = jax.tree.map(
            lambda g, s: lax.psum(g, axis_name) if s == P() else g,
            grads,
            specs_tree,
            is_leaf=lambda x: isinstance(x, P),
        )
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss * n

    shapes = _pp_param_shapes(cfg)
    opt_specs = opt_state_specs(jax.eval_shape(tx.init, shapes), shapes, specs_tree)
    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(specs_tree, opt_specs, P()),
        out_specs=(specs_tree, opt_specs, P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())


def _pp_param_shapes(cfg: "TransformerConfig") -> Dict:
    from ..models.transformer import init_transformer

    shapes = jax.eval_shape(lambda: init_transformer(cfg, jax.random.key(0)))
    return jax.eval_shape(partial(to_pp_layout, cfg), shapes)


def init_pp_state(
    cfg: "TransformerConfig",
    tx: optax.GradientTransformation,
    key: jax.Array,
    mesh: Mesh,
    axis_name: str = PP_AXIS,
):
    """Init (params_pp, opt_state) placed with PP shardings."""
    from ..models.transformer import init_transformer

    params_pp = shard_params_pp(
        cfg, to_pp_layout(cfg, init_transformer(cfg, key)), mesh, axis_name
    )
    from .mesh import place_on_mesh

    opt_state = tx.init(params_pp)
    specs = opt_state_specs(opt_state, params_pp, pp_param_specs(cfg, axis_name))
    return params_pp, place_on_mesh(opt_state, mesh, specs)
