"""2-D parallelism: expert parallelism x ring-attention sequence parallelism.

The last composition gap from round 1 (NOTES gap #4 / VERDICT item 9): MoE
models with long contexts. One (expert x seq) mesh:

- batch sharded over the expert axis (it doubles as data parallelism, as
  in parallel/moe.py), sequence sharded over the seq axis;
- attention: ring (or ring-flash / Ulysses, via TransformerConfig) over
  `seq` — K/V blocks rotate within each expert row;
- MoE MLP: two all_to_alls over `expert` — token routing within each seq
  column. The two collectives touch ORTHOGONAL mesh dimensions, so the
  composition needs no new communication primitive at all: exactly the
  scaling-book recipe of assigning independent parallelism forms to
  independent mesh axes.

Gradient rule (the same sum-over-shards discipline as dp_sp.py + moe.py):
each (ep, sp) shard differentiates its LOCAL objective slice
  lm_local + aux_w * aux_local / n_sp      (lm_local sums its nll slice
                                            over count psum'd over sp)
Replicated leaves then need psum over sp and pmean over ep (PS-mean over
the batch axis); expert-sharded leaves already carry their ep-routed
contributions (all_to_all transposes to all_to_all) and need only
psum over sp and the 1/n_ep mean scale.

No reference counterpart (SURVEY.md section 2: every parallelism axis
beyond DP is absent there).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .moe import (
    EP_AXIS,
    MoEConfig,
    apply_moe_transformer,
    init_moe_params,
    moe_param_specs,
)
from .ring_attention import SEQ_AXIS
from .tp import opt_state_specs

from ..models.transformer import TransformerConfig


def make_mesh_ep_sp(
    num_ep: int,
    num_sp: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(num_ep x num_sp) mesh; expert outer, seq inner (the ring is the
    latency-critical dimension — keep it on neighboring devices)."""
    devs = list(devices if devices is not None else jax.devices())
    need = num_ep * num_sp
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(num_ep, num_sp)
    return Mesh(grid, (EP_AXIS, SEQ_AXIS))


def shard_tokens_ep_sp(tokens, mesh: Mesh):
    """[B_global, T_global] -> B over expert, T over seq."""
    return jax.device_put(tokens, NamedSharding(mesh, P(EP_AXIS, SEQ_AXIS)))


def moe_lm_loss_local(
    cfg: TransformerConfig,
    moe: MoEConfig,
    params,
    tokens: jax.Array,  # [b_local, t_local]
    ep_axis: str = EP_AXIS,
    sp_axis: str = SEQ_AXIS,
):
    """LOCAL slice of the global-mean next-token loss + aux, for one
    (ep, sp) shard. Mirrors dp_sp.lm_loss_local (boundary target fetched
    with one ppermute; final global position masked), plus the MoE aux
    scaled so the sp-sum + ep-mean of the slices is the global mean aux."""
    b_loc, t_loc = tokens.shape
    n_sp = lax.axis_size(sp_axis)
    s = lax.axis_index(sp_axis)
    logits, aux = apply_moe_transformer(
        cfg, moe, params, tokens, axis_name=ep_axis, seq_axis_name=sp_axis
    )
    nxt_first = lax.ppermute(
        tokens[:, :1], sp_axis, [(j, (j - 1) % n_sp) for j in range(n_sp)]
    )
    tgt = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    pos = s * t_loc + jnp.arange(t_loc)
    valid = (pos < n_sp * t_loc - 1).astype(jnp.float32)
    loss_sum = jnp.sum(nll * valid[None, :])
    count = jnp.float32(b_loc) * jnp.sum(valid)
    lm_local = loss_sum / lax.psum(count, sp_axis)
    return lm_local, aux


def make_ep_sp_train_step(
    cfg: TransformerConfig,
    moe: MoEConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    donate: bool = True,
):
    """Jitted 2-D MoE train step: (params, opt_state, tokens) ->
    (params, opt_state, task_loss, aux). Expert weights sharded over
    `expert` (replicated over `seq`); tokens [B over expert, T over seq];
    everything else replicated."""
    specs_tree = moe_param_specs(cfg, EP_AXIS)

    def shard_fn(params, opt_state, tokens):
        n_ep = lax.axis_size(EP_AXIS)
        n_sp = lax.axis_size(SEQ_AXIS)

        def local_obj(p):
            lm_local, aux = moe_lm_loss_local(cfg, moe, p, tokens)
            # aux_local/n_sp: sp-sum + ep-mean of slices == mean over shards
            return lm_local + moe.aux_loss_weight * aux / n_sp, (lm_local, aux)

        (_, (lm_local, aux)), grads = jax.value_and_grad(
            local_obj, has_aux=True
        )(params)
        grads = jax.tree.map(
            lambda g, s: (
                lax.pmean(lax.psum(g, SEQ_AXIS), EP_AXIS)
                if s == P()
                # expert-sharded: ep contributions already routed home by
                # the all_to_all transpose; sum the sp replicas, mean
                # over the ep (data) axis
                else lax.psum(g, SEQ_AXIS) / n_ep
            ),
            grads,
            specs_tree,
            is_leaf=lambda x: isinstance(x, P),
        )
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        task = lax.pmean(lax.psum(lm_local, SEQ_AXIS), EP_AXIS)
        return new_params, new_opt, task, lax.pmean(aux, (EP_AXIS, SEQ_AXIS))

    shapes = jax.eval_shape(lambda: init_moe_params(cfg, moe, jax.random.key(0)))
    opt_specs = opt_state_specs(jax.eval_shape(tx.init, shapes), shapes, specs_tree)
    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(specs_tree, opt_specs, P(EP_AXIS, SEQ_AXIS)),
        out_specs=(specs_tree, opt_specs, P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())


def init_ep_sp_state(
    cfg: TransformerConfig,
    moe: MoEConfig,
    tx: optax.GradientTransformation,
    key: jax.Array,
    mesh: Mesh,
):
    """Init (params, opt_state) placed for the 2-D mesh: P(expert) leaves
    shard over the expert axis and replicate over seq automatically."""
    from .mesh import place_on_mesh
    from .moe import shard_params_moe

    params = shard_params_moe(cfg, init_moe_params(cfg, moe, key), mesh)
    opt_state = tx.init(params)
    specs = opt_state_specs(opt_state, params, moe_param_specs(cfg, EP_AXIS))
    return params, place_on_mesh(opt_state, mesh, specs)
