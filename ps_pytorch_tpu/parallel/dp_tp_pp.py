"""3-D parallelism: data x pipeline-stage x tensor(model) on one mesh.

The "compose freely" claim of ARCHITECTURE.md made executable at full
rank: a (dp, stage, model) mesh where

- the batch shards over `dp` (each dp column runs an independent GPipe
  schedule over its batch slice; gradients meet in one pmean — the PS
  aggregation, as everywhere else);
- block params are PP-stacked [depth, ...] over `stage` AND Megatron-
  split over `model` (tp.to_tp_layout applied per block before stacking):
  each (stage, model) device owns depth/n_pp blocks' worth of its own
  heads / MLP columns;
- within a tick, every block runs the TP math (two psums over `model`,
  the innermost / highest-bandwidth axis), activations ppermute over
  `stage`, microbatches fill the pipeline — three orthogonal collective
  patterns, one mesh, no new primitive.

Gradient rule (sum-over-shards, as tp/pp/moe): the tick-folded loss is
replicated across stage x model within a dp column, so differentiate
local/(n_dp * n_pp * n_tp); then
  replicated leaves (embeddings, out_norm) -> psum over all three axes,
  stage-sharded norms -> psum over dp and model,
  (stage x model)-sharded matrices -> psum over dp only (TP transposes
  already localized them; PP stages own disjoint depth slices).

No reference counterpart (SURVEY.md section 2: only DP exists there).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerConfig
from ..ops.metrics import next_token_nll
from .mesh import WORKER_AXIS
from .pp import PP_AXIS
from .tp import TP_AXIS, opt_state_specs, to_tp_layout

DP_AXIS = WORKER_AXIS


def make_mesh_3d(
    num_dp: int,
    num_pp: int,
    num_tp: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(dp x stage x model); model innermost — the TP psums fire twice per
    block per tick and must ride the fastest links."""
    devs = list(devices if devices is not None else jax.devices())
    need = num_dp * num_pp * num_tp
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(num_dp, num_pp, num_tp)
    return Mesh(grid, (DP_AXIS, PP_AXIS, TP_AXIS))


def to_3d_layout(cfg: TransformerConfig, params: Dict) -> Dict:
    """Replicated params -> TP layout per block, then PP-stacked."""
    tp_params = to_tp_layout(cfg, params)
    out = {k: v for k, v in tp_params.items() if k != "blocks"}
    out["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *tp_params["blocks"])
    return out


def from_3d_layout(cfg: TransformerConfig, params_3d: Dict) -> Dict:
    """Inverse of to_3d_layout (checkpoint interchange)."""
    from .tp import from_tp_layout

    blocks = [
        jax.tree.map(lambda x: x[i], params_3d["blocks"])
        for i in range(cfg.depth)
    ]
    tp_params = {k: v for k, v in params_3d.items() if k != "blocks"}
    tp_params["blocks"] = blocks
    return from_tp_layout(cfg, tp_params)


def param_specs_3d(cfg: TransformerConfig) -> Dict:
    blk = {
        "ln1": P(PP_AXIS),
        "wqkv": P(PP_AXIS, None, None, TP_AXIS, None),  # [d, D, 3, H, hd]
        "wo": P(PP_AXIS, TP_AXIS, None, None),  # [d, H, hd, D]
        "ln2": P(PP_AXIS),
        "w_up": P(PP_AXIS, None, TP_AXIS),  # [d, D, M]
        "w_down": P(PP_AXIS, TP_AXIS, None),  # [d, M, D]
    }
    return {"embed": P(), "pos_embed": P(), "out_norm": P(), "blocks": blk}


def shard_tokens_3d(tokens, mesh: Mesh):
    """[B_global, T] -> B over dp (replicated over stage/model)."""
    return jax.device_put(tokens, NamedSharding(mesh, P(DP_AXIS)))


def _tp_block(cfg: TransformerConfig, x, blk, axis_name: str):
    """One Megatron block on local heads/columns (tp.apply_transformer_tp's
    block body, reused for stacked-scan consumption)."""
    from ..models.transformer import _rms_norm, local_attention

    cd = cfg.effective_compute_dtype
    x = x.astype(cd)
    blk = {k: v.astype(cd) for k, v in blk.items()}
    h = _rms_norm(x, blk["ln1"])
    qkv = jnp.einsum("btd,dchk->btchk", h, blk["wqkv"])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    o = local_attention(cfg)(q, k, v)
    proj = jnp.einsum("bthk,hkd->btd", o, blk["wo"])
    x = x + lax.psum(proj, axis_name)
    h = _rms_norm(x, blk["ln2"])
    down = jax.nn.gelu(h @ blk["w_up"]) @ blk["w_down"]
    return x + lax.psum(down, axis_name)


def _3d_loss(cfg: TransformerConfig, params: Dict, tokens: jax.Array):
    """Tick-folded pipeline loss (the shared pp.gpipe_fold schedule) with
    TP blocks; tokens [M, B_mb, T] are this dp column's microbatches.
    Value is replicated across stage and model within the column."""
    from ..models.transformer import _rms_norm
    from .pp import gpipe_fold

    m = tokens.shape[0]
    pos = jnp.arange(tokens.shape[2])
    cd = cfg.effective_compute_dtype

    def local_blocks(x):
        body = lambda x, blk: (_tp_block(cfg, x, blk, TP_AXIS), None)
        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = lax.scan(body, x, params["blocks"])
        return x, jnp.zeros((), jnp.float32)

    def embed(mb_idx):
        tok = lax.dynamic_index_in_dim(
            tokens, jnp.clip(mb_idx, 0, m - 1), 0, keepdims=False
        )
        return (params["embed"][tok] + params["pos_embed"][pos][None]).astype(cd)

    def mb_loss(y, tok_mb):
        xf = _rms_norm(y, params["out_norm"].astype(cd))
        logits = xf @ params["embed"].T.astype(cd)  # [B_mb, T, V]
        return next_token_nll(logits, tok_mb)

    task, _ = gpipe_fold(
        PP_AXIS, tokens, cfg.dim, cd, embed, local_blocks, mb_loss
    )
    return task


def make_3d_train_step(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    num_microbatches: int,
    donate: bool = True,
):
    """Jitted dp x pp x tp train step: (params_3d, opt_state, tokens
    [B_global, T]) -> (params_3d, opt_state, loss)."""
    specs_tree = param_specs_3d(cfg)

    def shard_fn(params, opt_state, tokens):
        n_dp = lax.axis_size(DP_AXIS)
        n_pp = lax.axis_size(PP_AXIS)
        n_tp = lax.axis_size(TP_AXIS)
        bsz, t = tokens.shape
        if bsz % num_microbatches:
            raise ValueError(
                f"per-dp batch {bsz} not divisible by "
                f"{num_microbatches} microbatches"
            )
        mb = tokens.reshape(num_microbatches, bsz // num_microbatches, t)

        loss_local, grads = jax.value_and_grad(
            lambda p: _3d_loss(cfg, p, mb) / (n_dp * n_pp * n_tp)
        )(params)

        def reduce_grad(g, s):
            axes = []
            if DP_AXIS not in s:
                axes.append(DP_AXIS)
            if PP_AXIS not in s:
                axes.append(PP_AXIS)
            if TP_AXIS not in s:
                axes.append(TP_AXIS)
            return lax.psum(g, tuple(axes)) if axes else g

        grads = jax.tree.map(
            reduce_grad, grads, specs_tree, is_leaf=lambda x: isinstance(x, P)
        )
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, lax.pmean(loss_local, DP_AXIS) * n_pp * n_tp * n_dp

    from ..models.transformer import init_transformer

    shapes = jax.eval_shape(
        lambda: to_3d_layout(cfg, init_transformer(cfg, jax.random.key(0)))
    )
    opt_specs = opt_state_specs(jax.eval_shape(tx.init, shapes), shapes, specs_tree)
    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(specs_tree, opt_specs, P(DP_AXIS)),
        out_specs=(specs_tree, opt_specs, P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())


def init_3d_state(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    key: jax.Array,
    mesh: Mesh,
):
    """Init (params_3d, opt_state) placed for the (dp, stage, model) mesh."""
    from ..models.transformer import init_transformer
    from .mesh import place_on_mesh

    if cfg.depth % mesh.shape[PP_AXIS]:
        raise ValueError(
            f"depth {cfg.depth} not divisible by {mesh.shape[PP_AXIS]} stages"
        )
    n_tp = mesh.shape[TP_AXIS]
    if cfg.heads % n_tp or (cfg.dim * cfg.mlp_ratio) % n_tp:
        raise ValueError(
            f"heads/mlp not divisible by {n_tp} model shards"
        )
    specs = param_specs_3d(cfg)
    params = place_on_mesh(
        to_3d_layout(cfg, init_transformer(cfg, key)), mesh, specs
    )
    opt_state = tx.init(params)
    return params, place_on_mesh(
        opt_state, mesh, opt_state_specs(opt_state, params, specs)
    )
