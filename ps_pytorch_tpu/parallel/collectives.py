"""Gradient-aggregation collectives: the TPU-native replacement for the
reference master's Irecv/waitany gather loop and Blosc codec.

Reference semantics being reproduced (see SURVEY.md section 3.2):
- plain aggregation: sum of per-worker gradients divided by num_aggregate
  (sync_replicas_master_nn.py:204-208) -> `psum_mean`
- partial ("backup-worker") aggregation: only the first K of N gradients per
  layer are added, but the step is still synchronous
  (sync_replicas_master_nn.py:179-186,207) -> `aggregation_mask`, applied
  before the psum. `random_k` models "first K to *arrive*" (arrival order is
  nondeterministic in the reference); `first_k` is the deterministic variant.
- compressed communication: Blosc/snappy byte compression of each gradient
  (compression.py:18-31) -> int8 uniform quantization on the reduce path
  (`quantized_psum`): quantize with a global per-tensor scale, sum in int32,
  dequantize. Same capability (bandwidth reduction), hardware-native form.
  The Pallas TPU kernels for the quantize/dequantize hot path live in
  ops/quantize.py; this module wires them into the collective.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.quantize import (
    _INT8_PEAK,
    accum_dtype,
    accumulate_rescale_int8,
    dequantize_int8,
    quantize_int8,
    quantize_lattice,
)
from .buckets import piece_stream


# ----------------------------------------- adaptive per-bucket precision
# (PSConfig.precision_adapt): ``bucket_peaks`` is a traced f32 [n_buckets]
# vector of lattice peaks (0 | 7 | 127 | hi_peak, one per bucket of the
# wire's BucketPlan in CANONICAL order) selecting each bucket's
# quantization lattice THIS step. Every scheme resolves its piece's peak
# through ``_bucket_ordinal`` and quantizes via ops.quantize_lattice —
# the same shared-scale geometry, so the EF contribution mirror and the
# homomorphic lattice algebra are unchanged. Requires a bucketed wire
# (the tags are per-bucket) and nearest rounding (one shared lattice).


def _bucket_ordinal(key_ids):
    """Canonical bucket ordinal for each piece's PRNG key id. Bucketed
    key_ids are START OFFSETS in the flat buffer, ascending in canonical
    order, so a piece's ordinal is its offset's rank — stable across the
    serial and pipelined (readiness-order) enumerations, which is what
    lets ``bucket_peaks[ordinal]`` index one tag vector from either."""
    order = sorted(key_ids)
    return {i: order.index(i) for i in key_ids}


def _lattice_payload_dtype(hi_peak: int):
    """Minimal integer payload dtype holding the HI tag's peak — the
    static wire dtype every tag of an adaptive bucket rides (values
    adapt, bytes do not)."""
    if hi_peak <= _INT8_PEAK:
        return jnp.int8
    if hi_peak <= 2 ** 15 - 1:
        return jnp.int16
    return jnp.int32


def _resolve_peak(bucket_peaks, ordinal, i):
    """This piece's traced lattice peak, or None on the static wire."""
    if bucket_peaks is None:
        return None
    return bucket_peaks[ordinal[i]]


def aggregation_mask(
    axis_name: str,
    num_workers: int,
    num_aggregate,
    key: Optional[jax.Array] = None,
    mode: str = "random_k",
) -> jax.Array:
    """Per-worker {0,1} scalar: does this worker's gradient enter the sum?

    Must be called inside shard_map/pmap over `axis_name`. With
    num_aggregate None or >= num_workers, every worker participates.

    ``num_aggregate`` may be a TRACED int32 scalar (the adaptive partial
    aggregation path, resilience/elastic.py): the selection is then
    computed with dynamic-k arithmetic — ``random_k`` via the rank of
    each worker in the shared permutation (worker w is selected iff
    argsort(perm)[w] < k, exactly the set perm[:k] the static spelling
    builds), ``first_k`` via the same ``w < k`` compare. A traced k equal
    to num_workers yields a mask of exactly 1.0 everywhere, so the
    full-mask adaptive step multiplies by 1.0 — bit-exact against the
    static no-mask path."""
    dynamic = isinstance(num_aggregate, jax.Array)
    if not dynamic and (
        num_aggregate is None or num_aggregate >= num_workers
    ):
        return jnp.float32(1.0)
    w = lax.axis_index(axis_name)
    if mode == "first_k":
        return (w < num_aggregate).astype(jnp.float32)
    if mode == "random_k":
        if key is None:
            raise ValueError("random_k masking needs a (replicated) PRNG key")
        perm = jax.random.permutation(key, num_workers)
        if dynamic:
            # rank[w] = position of worker w in perm; rank < k <=> w is in
            # perm[:k] — same selected set as the static scatter below,
            # but expressible with a traced k
            rank = jnp.argsort(perm)
            return (rank[w] < num_aggregate).astype(jnp.float32)
        selected = jnp.zeros((num_workers,), jnp.float32).at[perm[:num_aggregate]].set(1.0)
        return selected[w]
    raise ValueError(f"unknown aggregation mode {mode!r}")


def _bucket_scope(pipelined: bool, key_id):
    """Named scope for one bucket's reduce chain (pipelined mode only):
    the per-bucket span names (``bucket_reduce_o<start offset>``) that
    profiler timelines and tools/trace_report.py's overlap analysis key
    on. Serial mode stays scope-free so its lowering is untouched."""
    if not pipelined:
        return contextlib.nullcontext()
    return jax.named_scope(f"bucket_reduce_o{int(key_id)}")


def psum_mean(tree, axis_name: str, denominator: float,
              bucket_bytes: Optional[int] = None,
              flat_output: bool = False, pipelined: bool = False,
              bucket_output: bool = False):
    """Sum over workers / denominator (parity: _model_update divides the
    aggregate buffer by num_aggregate, sync_replicas_master_nn.py:204-207).

    ``bucket_bytes`` (buckets.piece_stream) ships the fused flat f32
    buckets instead of the raw leaves — bit-exact for f32 gradients
    (same values, same elementwise sum/divide), and the collective
    operands become a few contiguous buffers instead of one per leaf.
    ``flat_output`` (state_layout="flat") returns the aggregate as one
    padded flat vector instead of scattering it back into the tree; the
    collectives themselves are identical (jax batches a whole-tree psum
    into one eqn either way).

    ``pipelined`` (PSConfig.overlap) emits ONE psum eqn per bucket, in
    readiness order, over buckets assembled from their own leaves — same
    buckets, same bytes, bit-identical values, but each bucket's reduce
    is dataflow-independent of the rest of the backward so a
    latency-hiding scheduler can overlap them (serial's fused psum over
    the global concat cannot start until every gradient exists).
    ``bucket_output`` returns the canonical-order list of per-bucket
    aggregates for the per-bucket vector update."""
    if bucket_bytes is None and not flat_output:
        summed = lax.psum(tree, axis_name)
        return jax.tree_util.tree_map(lambda g: g / denominator, summed)
    pieces, key_ids, rebuild = piece_stream(
        tree, bucket_bytes, flat_output=flat_output, pipelined=pipelined,
        bucket_output=bucket_output,
    )
    if pipelined:
        outs = []
        for i, g in zip(key_ids, pieces):
            with _bucket_scope(True, i):
                outs.append(lax.psum(g, axis_name) / denominator)
        return rebuild(outs)
    summed = lax.psum(pieces, axis_name)  # one fused eqn over the buckets
    return rebuild([s / denominator for s in summed])


def quantized_psum(
    tree,
    axis_name: str,
    denominator: float,
    block_size: int = 0,
    rounding: str = "nearest",
    key: Optional[jax.Array] = None,
    bucket_bytes: Optional[int] = None,
    flat_output: bool = False,
    pipelined: bool = False,
    bucket_output: bool = False,
    wire_domain: str = "dequant",
    num_workers: Optional[int] = None,
    bucket_peaks=None,
    lattice_hi_peak: int = _INT8_PEAK,
):
    """int8-quantized gradient all-reduce.

    Per piece: global absmax (pmax) -> symmetric int8 quantize -> int32 psum
    -> dequantize / denominator. Deterministic (same scale on all workers) and
    exact-sum in int32 (no overflow below 2^23 workers). `block_size` > 0
    switches to per-block scales for tighter quantization error; `rounding=
    "stochastic"` makes each worker's quantization unbiased with independent
    noise (key folded by worker index and piece id), so rounding error
    averages out across the psum instead of accumulating (capabilities beyond
    the reference's lossless-but-slow Blosc path).

    ``wire_domain="homomorphic"`` (PSConfig.wire_domain) is the THC-style
    compressed-domain spelling of the same sum: the scales are already
    shared (the pmax), so the psum rides the MINIMAL exact accumulator
    dtype for ``num_workers`` summands (ops/quantize.accum_dtype — int16
    through 258 workers, halving the dequant path's int32 wire) and the
    division by ``denominator`` folds into the single deferred
    scale-multiply at the consumer. The accumulation itself is bit-exact
    either way (integer sums); only the wire bytes and the final
    multiply's association differ.

    A piece is one pytree leaf (``bucket_bytes=None``, the reference's
    message-per-layer shape) or one fused flat bucket (buckets.py) — the
    latter collapses O(n_leaves) pmax+psum pairs into O(n_buckets), with
    bucket boundaries aligned to ``block_size`` so no scale row straddles
    buckets and PRNG keys folded by bucket start offset (position-stable).

    ``bucket_peaks`` (adaptive per-bucket precision — see the module
    section above) switches each bucket's quantize to the traced-peak
    lattice. The psum operand's static dtype is unchanged on the
    dequant wire unless the HI tag's peak exceeds int8 (then the
    payload intermediate widens to the minimal int that holds it — the
    int32 psum on the wire is byte-identical either way); the
    homomorphic wire's payload already rides ``accum_dtype``.
    """
    if bucket_peaks is not None and rounding == "stochastic":
        raise ValueError(
            "adaptive precision needs rounding='nearest' (the traced-"
            "peak lattice is shared-scale by construction)"
        )
    if wire_domain == "homomorphic":
        if num_workers is None:
            raise ValueError(
                "homomorphic quantized_psum needs num_workers (it sizes "
                "the exact accumulator dtype)"
            )
        if rounding == "stochastic":
            raise ValueError(
                "homomorphic wire needs rounding='nearest' (per-worker "
                "stochastic noise is incoherent on a shared lattice)"
            )
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding needs a key")
        key = jax.random.fold_in(key, lax.axis_index(axis_name))

    def one(i, g):
        g32 = g.astype(jnp.float32)
        leaf_key = jax.random.fold_in(key, i) if key is not None else None
        peak = _resolve_peak(bucket_peaks, ordinal, i)
        if peak is not None:
            q, scale = quantize_lattice(
                g32,
                peak,
                axis_name=axis_name,
                block_size=block_size,
                hi_peak=lattice_hi_peak,
                out_dtype=(
                    accum_dtype(num_workers)
                    if wire_domain == "homomorphic"
                    else _lattice_payload_dtype(lattice_hi_peak)
                ),
            )
        else:
            q, scale = quantize_int8(
                g32,
                axis_name=axis_name,
                block_size=block_size,
                rounding=rounding,
                key=leaf_key,
            )
        if wire_domain == "homomorphic":
            # compressed-domain sum: narrow exact accumulator on the
            # wire, ONE deferred scale-multiply (the denominator folds
            # into the shared scale) at the consumer
            s = lax.psum(q.astype(accum_dtype(num_workers)), axis_name)
            return dequantize_int8(
                s, scale / denominator, block_size=block_size, shape=g.shape
            )
        s = lax.psum(q.astype(jnp.int32), axis_name)
        deq = dequantize_int8(s, scale, block_size=block_size, shape=g.shape)
        return deq / denominator

    pieces, key_ids, rebuild = piece_stream(
        tree, bucket_bytes, align=block_size or 1, flat_output=flat_output,
        pipelined=pipelined, bucket_output=bucket_output,
    )
    ordinal = None if bucket_peaks is None else _bucket_ordinal(key_ids)
    outs = []
    for i, g in zip(key_ids, pieces):
        with _bucket_scope(pipelined, i):
            outs.append(one(i, g))
    return rebuild(outs)


def _slice_len(total: int, n: int, block_size: int) -> int:
    """Per-worker region length: ceil(total/n) rounded up to whole
    quantization blocks."""
    bs = block_size or 1
    return (-(-total // n) + bs - 1) // bs * bs


def _q2r_scatter_stage(g32, axis_name, n, s, block_size, rounding, leaf_key,
                       peak=None):
    """Round 1 of the 2-round scheme for one flat padded [n*s] leaf:
    shared-scale int8 quantize -> all_to_all int8 -> local int32 sum ->
    dequantize MY region. Returns the f32 partial sum [s] — an int8-wire
    reduce_scatter. ``peak`` (adaptive precision) swaps the quantize for
    the traced-peak lattice; the a2a payload stays int8 (the 2-round
    wire's HI tag is capped at the int8 peak its payload carries)."""
    if peak is not None:
        q1, scale1 = quantize_lattice(
            g32, peak, axis_name=axis_name, block_size=block_size,
        )
    else:
        q1, scale1 = quantize_int8(
            g32,
            axis_name=axis_name,  # shared (pmax) scales: replicated rows
            block_size=block_size,
            rounding=rounding,
            key=leaf_key,
        )
    q1 = q1.reshape(n, s).astype(jnp.int8)
    # row j of the a2a result = device j's slice of MY region
    recv = lax.all_to_all(q1, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    partial = jnp.sum(recv.astype(jnp.int32), axis=0)  # [s]
    w = lax.axis_index(axis_name)
    if block_size:
        nb_loc = s // block_size
        my_scales = lax.dynamic_slice(scale1, (w * nb_loc, 0), (nb_loc, 1))
        partial = (
            partial.reshape(nb_loc, block_size).astype(jnp.float32)
            * my_scales
        ).reshape(-1)
    else:
        partial = partial.astype(jnp.float32) * scale1
    return partial


def _q2r_scatter_stage_hom(g32, wire_axis, scale_axes, n, s, block_size,
                           peak=None):
    """Homomorphic round 1 for one flat padded [n*s] piece: SHARED-scale
    (pmax over ``scale_axes`` — the whole reducing axis set, so one scale
    row set serves every worker) int8 quantize -> all_to_all int8 over
    ``wire_axis``. Returns ``(recv [n, s] int8, scale)`` — the received
    worker rows of MY region, un-accumulated so the caller can fuse the
    exact integer accumulation with its lattice rescale
    (ops/quantize.accumulate_rescale_int8, one Pallas VPU pass on TPU).
    The scale rows cover the WHOLE padded vector and are replicated on
    every worker by the pmax, so any consumer can dequantize any region
    with zero scale traffic. ``peak`` (adaptive precision) swaps in the
    traced-peak lattice — the rescale/deferred-multiply algebra
    downstream is peak-agnostic (|acc| <= n * peak <= n * 127 still
    rescales into int8 range; a SKIP bucket's all-zero payload
    dequantizes through its zero scale)."""
    if peak is not None:
        q1, scale1 = quantize_lattice(
            g32, peak, axis_name=scale_axes, block_size=block_size
        )
    else:
        q1, scale1 = quantize_int8(
            g32, axis_name=scale_axes, block_size=block_size
        )
    q1 = q1.reshape(n, s).astype(jnp.int8)
    recv = lax.all_to_all(q1, wire_axis, split_axis=0, concat_axis=0,
                          tiled=True)
    return recv, scale1


def _deq_shared(full, scale, gain, block_size):
    """THE single deferred scale-multiply of the homomorphic wire: int8
    payload x (shared scale x gain) -> f32, per block row or per tensor.
    ``gain`` folds the lattice-rescale factors and the aggregation
    denominator back in (it may be traced)."""
    if block_size:
        return (
            full.reshape(-1, block_size).astype(jnp.float32)
            * (scale * gain)
        ).reshape(-1)
    return full.astype(jnp.float32) * (scale * gain)


def _q2r_gather_stage(partial, axis_name, n, s, block_size, rounding, key2):
    """Round 2: requantize the [s] partial sum with LOCAL scales (regions
    are disjoint, so no cross-worker scale agreement is needed) and
    all_gather int8 (+ tiny f32 scale rows) -> dequantized full [n*s]."""
    q2, scale2 = quantize_int8(
        partial, block_size=block_size, rounding=rounding, key=key2
    )
    q2 = q2.reshape(-1).astype(jnp.int8)
    full = lax.all_gather(q2, axis_name, tiled=True)  # int8 on the wire
    if block_size:
        scales2 = lax.all_gather(scale2, axis_name, tiled=True)  # [nb,1]
        deq = (
            full.reshape(-1, block_size).astype(jnp.float32) * scales2
        ).reshape(-1)
    else:
        scales2 = lax.all_gather(scale2.reshape(1), axis_name, tiled=True)
        deq = (
            full.reshape(n, s).astype(jnp.float32) * scales2[:, None]
        ).reshape(-1)
    return deq


def quantized_allreduce_2round(
    tree,
    axis_name: str,
    denominator: float,
    num_workers: int,
    block_size: int = 0,
    rounding: str = "nearest",
    key: Optional[jax.Array] = None,
    bucket_bytes: Optional[int] = None,
    flat_output: bool = False,
    pipelined: bool = False,
    bucket_output: bool = False,
    wire_domain: str = "dequant",
    bucket_peaks=None,
):
    """Two-round int8 all-reduce whose WIRE traffic is actually int8.

    `quantized_psum` sums int8 payloads in an int32 psum — exact, but the
    bytes on the interconnect are int32, so it compresses compute, not
    bandwidth. This is the bandwidth-honest scheme (the compressed
    multi-hop all-reduce family — THC/DynamiQ, PAPERS.md): per leaf,

        flatten -> pad to [n, s] -> int8 quantize (round 1, shared
        per-block scales via pmax) -> all_to_all int8 (each worker
        receives everyone's slice of ITS region) -> local int32 sum ->
        requantize the partial sum (round 2, local scales) -> all_gather
        int8 (+ tiny f32 scale rows) -> dequantize / denominator.

    ~2 int8 bytes/element on the wire per device vs ~8 for an f32 ring
    psum — a true 4x reduction, at the cost of a second (per-block-scaled)
    quantization on the partial sums. That round-2 noise is NOT tracked by
    the EF residual (which mirrors round 1 only); measured on real LeNet
    gradients it is ~1.5e-2 of the aggregate's norm with per-tensor scales
    and ~8e-3 with block-128 scales
    (tests/test_compression.py::test_ef_untracked_round2_noise_measured).
    The result is identical on every worker by construction (it is
    all_gathered).

    ``wire_domain="homomorphic"``: round 2's widen -> requantize (and its
    f32 scale-row gather) disappears entirely — the exact int32
    accumulation of MY region is lattice-rescaled by the aggregation
    denominator (``ops/quantize.homomorphic_rescale``: round(acc / k)
    provably fits int8, since |acc| <= k * 127 on the shared lattice),
    all_gathered as int8, and dequantized by ONE deferred scale-multiply
    with the round-1 scale rows every worker already holds from the
    pmax. The only lossy step beyond round 1 is that single deterministic
    rounding at the shared scale's granularity (vs the dequant path's
    adaptively-rescaled round-2 requantization — comparable envelope,
    zero extra wire rows). Requires ``rounding="nearest"`` (PSConfig
    enforces it: per-worker stochastic noise has no coherent meaning on
    a shared lattice rescale).
    """
    n = num_workers
    if bucket_peaks is not None and rounding == "stochastic":
        raise ValueError(
            "adaptive precision needs rounding='nearest' (the traced-"
            "peak lattice is shared-scale by construction)"
        )
    if wire_domain == "homomorphic" and rounding == "stochastic":
        raise ValueError(
            "homomorphic wire needs rounding='nearest' (per-worker "
            "stochastic noise is incoherent on a shared lattice)"
        )
    # same key discipline as quantized_psum / local_quantized_contribution
    # (fold worker first, leaf second) so error-feedback residuals mirror
    # the transmitted values exactly
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding needs a key")
        key = jax.random.fold_in(key, lax.axis_index(axis_name))

    def one(i, g):
        g32 = g.astype(jnp.float32).reshape(-1)
        total = g32.shape[0]
        s = _slice_len(total, n, block_size)
        g32 = jnp.pad(g32, (0, n * s - total))
        peak = _resolve_peak(bucket_peaks, ordinal, i)
        if wire_domain == "homomorphic":
            recv, scale1 = _q2r_scatter_stage_hom(
                g32, axis_name, axis_name, n, s, block_size, peak=peak
            )
            q2 = accumulate_rescale_int8(recv, denominator)
            full = lax.all_gather(q2, axis_name, tiled=True)  # int8, no
            # scale rows: every worker holds the shared rows already
            deq = _deq_shared(full, scale1, 1.0, block_size)
            return deq[:total].reshape(g.shape)  # denominator folded in
        leaf_key = jax.random.fold_in(key, i) if key is not None else None
        partial = _q2r_scatter_stage(
            g32, axis_name, n, s, block_size, rounding, leaf_key, peak=peak
        )
        k2 = jax.random.fold_in(leaf_key, 1) if leaf_key is not None else None
        deq = _q2r_gather_stage(
            partial, axis_name, n, s, block_size, rounding, k2
        )
        return (deq[:total] / denominator).reshape(g.shape)

    pieces, key_ids, rebuild = piece_stream(
        tree, bucket_bytes, align=block_size or 1, flat_output=flat_output,
        pipelined=pipelined, bucket_output=bucket_output,
    )
    ordinal = None if bucket_peaks is None else _bucket_ordinal(key_ids)
    outs = []
    for i, g in zip(key_ids, pieces):
        with _bucket_scope(pipelined, i):
            outs.append(one(i, g))
    return rebuild(outs)


def quantized_allreduce_2round_hier(
    tree,
    axis_names: tuple,
    denominator: float,
    axis_sizes: tuple,
    block_size: int = 0,
    rounding: str = "nearest",
    key: Optional[jax.Array] = None,
    bucket_bytes: Optional[int] = None,
    flat_output: bool = False,
    pipelined: bool = False,
    bucket_output: bool = False,
    wire_domain: str = "dequant",
    bucket_peaks=None,
):
    """Hierarchical (DCN x ICI) bandwidth-honest int8 all-reduce that
    crosses DCN exactly ONCE per gradient element.

    Naively composing two flat 2-round all-reduces would end the inner
    (ICI) round with an all_gather, leaving every ICI column holding the
    identical full host-sum — and then per_host redundant int8 copies of
    the whole gradient would cross the DCN bottleneck. Instead, per leaf:

      1. inner int8-wire reduce_scatter over ICI (round-1 stage only):
         each chip ends with the f32 partial sum of ITS 1/per_host
         region of the host total;
      2. a full 2-round int8 all-reduce over the DCN axis on that region
         alone — the ICI columns carry DISJOINT regions, so total DCN
         traffic is ~1 int8 byte/element regardless of per_host;
      3. one f32 all_gather over ICI reassembles the globally-summed
         vector (ICI bandwidth is an order of magnitude above DCN; the
         scheme spends bytes on the link that has them).

    axis_names = (dcn_axis, ici_axis); axis_sizes = (hosts, per_host).
    Round-1 quantization (the EF contribution transform) is shared-scale
    over the ICI axis with the key pre-folded by DCN index — mirror it
    with local_quantized_contribution(axis_names[1], key=dcn_folded_key).

    ``wire_domain="homomorphic"``: round-1 scales are shared GLOBALLY
    (one pmax over BOTH axes — one scale row set serves every chip on
    the mesh), so the accumulated payload stays on one lattice across
    every hop and NOTHING ever widens to f32 on the wire: the ICI
    partial sums lattice-rescale (/per_host) to int8 and cross DCN as
    int8, the DCN sums rescale (/hosts) and gather back as int8, and —
    the headline row — the ICI reassembly all_gather carries int8
    instead of the dequant path's f32 (4x smaller; the PSC103 hier
    reassembly allowance disappears). The consumer applies ONE deferred
    scale-multiply with gain (per_host * hosts) / denominator folding
    the exact aggregation count back in. Mirror the EF contribution
    with local_quantized_contribution over the FULL axis tuple."""
    dcn_axis, ici_axis = axis_names
    hosts, per_host = axis_sizes
    if wire_domain == "homomorphic" and rounding == "stochastic":
        raise ValueError(
            "homomorphic wire needs rounding='nearest' (per-worker "
            "stochastic noise is incoherent on a shared lattice)"
        )
    if bucket_peaks is not None and rounding == "stochastic":
        raise ValueError(
            "adaptive precision (bucket_peaks) needs rounding='nearest'"
        )
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding needs a key")
        # decorrelate across hosts FIRST (same-ICI-index chips on
        # different hosts must not draw identical noise), then per chip
        key = jax.random.fold_in(key, lax.axis_index(dcn_axis))
        key = jax.random.fold_in(key, lax.axis_index(ici_axis))

    def one_hom(i, g):
        g32 = g.astype(jnp.float32).reshape(-1)
        total = g32.shape[0]
        s1 = _slice_len(total, per_host, block_size)
        g32 = jnp.pad(g32, (0, per_host * s1 - total))
        peak = _resolve_peak(bucket_peaks, ordinal, i)
        # 1. ICI: shared-GLOBAL-scale quantize, int8 a2a, exact int sum
        recv1, scale1 = _q2r_scatter_stage_hom(
            g32, ici_axis, axis_names, per_host, s1, block_size, peak=peak
        )
        # 2. DCN hop forwards the accumulated payload on the SAME
        # lattice: fused accumulate+rescale /per_host back into int8
        # range (|acc| <= per_host * 127), a2a int8, fused
        # accumulate+rescale /hosts
        q_mid = accumulate_rescale_int8(recv1, float(per_host))
        s2 = _slice_len(s1, hosts, block_size)
        q_mid = jnp.pad(q_mid, (0, hosts * s2 - s1))
        recv2 = lax.all_to_all(
            q_mid.reshape(hosts, s2), dcn_axis, split_axis=0,
            concat_axis=0, tiled=True,
        )
        q2 = accumulate_rescale_int8(recv2, float(hosts))
        region = lax.all_gather(q2, dcn_axis, tiled=True)[:s1]
        # 3. reassemble over ICI — int8, the hop the dequant path pays
        # f32 for; then the single deferred scale-multiply, with the
        # rescale factors and the true denominator folded into the gain
        full = lax.all_gather(region, ici_axis, tiled=True)
        gain = (per_host * hosts) / denominator
        deq = _deq_shared(full, scale1, gain, block_size)
        return deq[:total].reshape(g.shape)

    def one(i, g):
        if wire_domain == "homomorphic":
            return one_hom(i, g)
        g32 = g.astype(jnp.float32).reshape(-1)
        total = g32.shape[0]
        s1 = _slice_len(total, per_host, block_size)
        g32 = jnp.pad(g32, (0, per_host * s1 - total))
        leaf_key = jax.random.fold_in(key, i) if key is not None else None
        peak = _resolve_peak(bucket_peaks, ordinal, i)
        # 1. ICI reduce_scatter: my [s1] region of the host sum —
        # the EF-mirrored transform, so the adaptive peak applies HERE;
        # the DCN hop's requantization stays static int8 (untracked
        # round-2-style noise, same as the flat scheme's round 2)
        partial = _q2r_scatter_stage(
            g32, ici_axis, per_host, s1, block_size, rounding, leaf_key,
            peak=peak,
        )
        # 2. full 2-round over DCN on the region only
        s2 = _slice_len(s1, hosts, block_size)
        partial = jnp.pad(partial, (0, hosts * s2 - s1))
        k_dcn = (
            jax.random.fold_in(leaf_key, 2) if leaf_key is not None else None
        )
        p2 = _q2r_scatter_stage(
            partial, dcn_axis, hosts, s2, block_size, rounding, k_dcn
        )
        k2 = jax.random.fold_in(k_dcn, 1) if k_dcn is not None else None
        region = _q2r_gather_stage(
            p2, dcn_axis, hosts, s2, block_size, rounding, k2
        )[:s1]
        # 3. reassemble over ICI (f32; ICI is the cheap link)
        full = lax.all_gather(region, ici_axis, tiled=True)
        return (full[:total] / denominator).reshape(g.shape)

    pieces, key_ids, rebuild = piece_stream(
        tree, bucket_bytes, align=block_size or 1, flat_output=flat_output,
        pipelined=pipelined, bucket_output=bucket_output,
    )
    ordinal = None if bucket_peaks is None else _bucket_ordinal(key_ids)
    outs = []
    for i, g in zip(key_ids, pieces):
        with _bucket_scope(pipelined, i):
            outs.append(one(i, g))
    return rebuild(outs)


def local_quantized_contribution(
    grads,
    axis_name: str,
    block_size: int = 0,
    rounding: str = "nearest",
    key: Optional[jax.Array] = None,
    bucket_bytes: Optional[int] = None,
    pipelined: bool = False,
    bucket_peaks=None,
    lattice_hi_peak: int = _INT8_PEAK,
):
    """What THIS worker's gradient becomes after its (shared-scale) int8
    round trip — the transmitted value whose difference from the true
    gradient is the error-feedback residual. Mirrors quantized_psum /
    round 1 of the 2-round scheme exactly (same scales, same rounding
    keys, same bucketing and key-fold discipline), so `residual = g -
    contribution` is the real on-wire error.

    ``bucket_peaks`` mirrors the adaptive-precision lattice: a tagged
    bucket's transmitted value is its quantize_lattice round trip at the
    same traced peak (skip buckets transmit exactly zero, so EF absorbs
    the WHOLE gradient as residual). The mirror quantizes into the same
    carrier dtype the wire's round-1 site uses
    (``_lattice_payload_dtype(lattice_hi_peak)``): numerically the
    transmitted value is q * scale regardless of carrier width, but
    matching the wire's (dtype, shape) site geometry is what lets the
    PSC112 analyzer prove this recomputed transform covers the wire's
    own quantization site."""
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding needs a key")
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
    if bucket_peaks is not None and rounding == "stochastic":
        raise ValueError(
            "adaptive precision (bucket_peaks) needs rounding='nearest'"
        )

    def one(i, g):
        g32 = g.astype(jnp.float32)
        leaf_key = jax.random.fold_in(key, i) if key is not None else None
        peak = _resolve_peak(bucket_peaks, ordinal, i)
        if peak is not None:
            q, scale = quantize_lattice(
                g32,
                peak,
                axis_name=axis_name,
                block_size=block_size,
                hi_peak=lattice_hi_peak,
                out_dtype=_lattice_payload_dtype(lattice_hi_peak),
            )
        else:
            q, scale = quantize_int8(
                g32,
                axis_name=axis_name,
                block_size=block_size,
                rounding=rounding,
                key=leaf_key,
            )
        return dequantize_int8(
            q.astype(jnp.int32), scale, block_size=block_size, shape=g.shape
        )

    pieces, key_ids, rebuild = piece_stream(
        grads, bucket_bytes, align=block_size or 1, pipelined=pipelined
    )
    ordinal = None if bucket_peaks is None else _bucket_ordinal(key_ids)
    return rebuild([one(i, g) for i, g in zip(key_ids, pieces)])


def aggregate_gradients(
    grads,
    axis_name: str,
    num_workers: int,
    num_aggregate=None,
    mask_key: Optional[jax.Array] = None,
    mask_mode: str = "random_k",
    compress: Optional[str] = None,
    quant_block_size: int = 0,
    quant_rounding: str = "nearest",
    quant_key: Optional[jax.Array] = None,
    return_contribution: bool = False,
    axis_sizes: Optional[tuple] = None,
    bucket_bytes: Optional[int] = None,
    flat_output: bool = False,
    pipelined: bool = False,
    bucket_output: bool = False,
    wire_domain: str = "dequant",
    bucket_peaks=None,
    lattice_hi_peak: int = _INT8_PEAK,
):
    """The full PS aggregation: mask -> (bucket) -> (quantized) reduce -> / K.

    ``bucket_bytes`` selects the wire granularity (PSConfig.bucket_bytes):
    ``None`` = the legacy message-per-leaf shape, ``0`` = one fused flat
    buffer, ``N`` = ~N-byte buckets. Every scheme and the EF contribution
    share the same piece stream (buckets.piece_stream), so residuals
    mirror the transmitted values exactly in either granularity.

    ``flat_output`` (state_layout="flat") returns the AGGREGATE as one
    padded flat f32 vector — the shape the fused vector update consumes —
    instead of scattering it back into the gradient tree. It is
    compute-side only: the masking, quantization, and every collective
    are byte-identical to the tree output, and the EF contribution (when
    requested) stays TREE-shaped because the per-worker residual state is
    per-leaf (checkpoint-portable across bucket/layout settings).

    return_contribution=True additionally returns THIS worker's
    transmitted (post-mask, post-quantization-round-trip) value — what
    error feedback subtracts from the pre-aggregation gradient to get the
    true on-wire residual. The masking and compress dispatch live HERE
    only; the EF path in ps.py must not re-implement them.

    A TUPLE axis_name (hierarchical DCN x ICI data parallelism) with
    compress="int8_2round" runs the HIERARCHICAL 2-round scheme:
    bandwidth-honest int8 all-reduce over ICI within each host first
    (denominator 1), then the same scheme across the DCN axis on the
    host-local sums — every wire crossing, intra- and inter-host, carries
    int8. Requires `axis_sizes` = (hosts, workers_per_host). The EF
    contribution mirrors the INNER ring's round-1 transform; the DCN
    round's requantization noise is not residual-tracked — measured at
    ~1e-2 of the aggregate's norm (halved by block-128 scales) for the
    flat scheme's round 2, the same transform
    (tests/test_compression.py::test_ef_untracked_round2_noise_measured).

    ``num_aggregate`` may be a TRACED int32 scalar (adaptive partial
    aggregation): the mask is then always applied (1.0 everywhere when
    the traced count equals num_workers — bit-exact against the static
    no-mask path on power-of-two meshes) and the denominator is the
    traced count itself, so the aggregate stays an average over the
    selected set at every count without retracing."""
    if wire_domain not in ("dequant", "homomorphic"):
        raise ValueError(f"bad wire_domain {wire_domain!r}")
    if bucket_peaks is not None:
        if compress in (None, "none"):
            raise ValueError(
                "adaptive precision (bucket_peaks) needs a compress mode — "
                "an uncompressed f32 wire has no lattice to retune"
            )
        if quant_rounding == "stochastic":
            raise ValueError(
                "adaptive precision (bucket_peaks) needs "
                "quant_rounding='nearest'"
            )
    if wire_domain == "homomorphic":
        if compress in (None, "none"):
            raise ValueError(
                "wire_domain='homomorphic' needs a compress mode — an "
                "uncompressed f32 psum has no compressed domain to sum in"
            )
        if quant_rounding == "stochastic":
            raise ValueError(
                "wire_domain='homomorphic' needs quant_rounding='nearest'"
            )
    dynamic = isinstance(num_aggregate, jax.Array)
    if dynamic:
        k = num_aggregate.astype(jnp.float32)
    else:
        k = (
            num_aggregate
            if (num_aggregate is not None and num_aggregate < num_workers)
            else num_workers
        )
    hier_2round = compress == "int8_2round" and isinstance(
        axis_name, (tuple, list)
    )
    if dynamic or k != num_workers:
        sel = aggregation_mask(axis_name, num_workers, num_aggregate, mask_key, mask_mode)
        grads = jax.tree_util.tree_map(lambda g: g * sel.astype(g.dtype), grads)
    denom = k if dynamic else float(k)
    if compress in (None, "none"):
        agg = psum_mean(grads, axis_name, denom,
                        bucket_bytes=bucket_bytes, flat_output=flat_output,
                        pipelined=pipelined, bucket_output=bucket_output)
        contribution = grads  # lossless transmit: residual is zero
    elif compress == "int8":
        agg = quantized_psum(
            grads,
            axis_name,
            denom,
            block_size=quant_block_size,
            rounding=quant_rounding,
            key=quant_key,
            bucket_bytes=bucket_bytes,
            flat_output=flat_output,
            pipelined=pipelined,
            bucket_output=bucket_output,
            wire_domain=wire_domain,
            num_workers=num_workers,
            bucket_peaks=bucket_peaks,
            lattice_hi_peak=lattice_hi_peak,
        )
        contribution = None
    elif hier_2round:
        if axis_sizes is None:
            raise ValueError(
                "hierarchical int8_2round needs axis_sizes=(hosts, "
                "workers_per_host)"
            )
        agg = quantized_allreduce_2round_hier(
            grads,
            tuple(axis_name),
            denom,
            tuple(axis_sizes),
            block_size=quant_block_size,
            rounding=quant_rounding,
            key=quant_key,
            bucket_bytes=bucket_bytes,
            flat_output=flat_output,
            pipelined=pipelined,
            bucket_output=bucket_output,
            wire_domain=wire_domain,
            bucket_peaks=bucket_peaks,
        )
        contribution = None
    elif compress == "int8_2round":
        agg = quantized_allreduce_2round(
            grads,
            axis_name,
            denom,
            num_workers,
            block_size=quant_block_size,
            rounding=quant_rounding,
            key=quant_key,
            bucket_bytes=bucket_bytes,
            flat_output=flat_output,
            pipelined=pipelined,
            bucket_output=bucket_output,
            wire_domain=wire_domain,
            bucket_peaks=bucket_peaks,
        )
        contribution = None
    else:
        raise ValueError(f"unknown compression {compress!r}")
    if not return_contribution:
        return agg
    if contribution is None:  # quantized modes share the round-1 transform
        contrib_key = quant_key
        if hier_2round and quant_rounding == "stochastic" and quant_key is not None:
            # mirror the hier function's own fold chain (DCN index first,
            # then local_quantized_contribution's internal ICI fold) so
            # the residual tracks the transmitted values exactly
            contrib_key = jax.random.fold_in(
                quant_key, lax.axis_index(axis_name[0])
            )
        contribution = local_quantized_contribution(
            grads,
            # hierarchical 2round quantizes round 1 with scales shared
            # over the INNER (ICI) axis only — except on the homomorphic
            # wire, whose round-1 scales are GLOBAL (pmax over the full
            # axis tuple), so the residual must mirror that
            (
                tuple(axis_name)
                if hier_2round and wire_domain == "homomorphic"
                else (axis_name[1] if hier_2round else axis_name)
            ),
            block_size=quant_block_size,
            rounding=quant_rounding,
            key=contrib_key,
            bucket_bytes=bucket_bytes,
            pipelined=pipelined,
            bucket_peaks=bucket_peaks,
            lattice_hi_peak=lattice_hi_peak,
        )
    return agg, contribution
