"""Gradient-aggregation collectives: the TPU-native replacement for the
reference master's Irecv/waitany gather loop and Blosc codec.

Reference semantics being reproduced (see SURVEY.md section 3.2):
- plain aggregation: sum of per-worker gradients divided by num_aggregate
  (sync_replicas_master_nn.py:204-208) -> `psum_mean`
- partial ("backup-worker") aggregation: only the first K of N gradients per
  layer are added, but the step is still synchronous
  (sync_replicas_master_nn.py:179-186,207) -> `aggregation_mask`, applied
  before the psum. `random_k` models "first K to *arrive*" (arrival order is
  nondeterministic in the reference); `first_k` is the deterministic variant.
- compressed communication: Blosc/snappy byte compression of each gradient
  (compression.py:18-31) -> int8 uniform quantization on the reduce path
  (`quantized_psum`): quantize with a global per-tensor scale, sum in int32,
  dequantize. Same capability (bandwidth reduction), hardware-native form.
  The Pallas TPU kernels for the quantize/dequantize hot path live in
  ops/quantize.py; this module wires them into the collective.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.quantize import dequantize_int8, quantize_int8


def aggregation_mask(
    axis_name: str,
    num_workers: int,
    num_aggregate: Optional[int],
    key: Optional[jax.Array] = None,
    mode: str = "random_k",
) -> jax.Array:
    """Per-worker {0,1} scalar: does this worker's gradient enter the sum?

    Must be called inside shard_map/pmap over `axis_name`. With
    num_aggregate None or >= num_workers, every worker participates.
    """
    if num_aggregate is None or num_aggregate >= num_workers:
        return jnp.float32(1.0)
    w = lax.axis_index(axis_name)
    if mode == "first_k":
        return (w < num_aggregate).astype(jnp.float32)
    if mode == "random_k":
        if key is None:
            raise ValueError("random_k masking needs a (replicated) PRNG key")
        perm = jax.random.permutation(key, num_workers)
        selected = jnp.zeros((num_workers,), jnp.float32).at[perm[:num_aggregate]].set(1.0)
        return selected[w]
    raise ValueError(f"unknown aggregation mode {mode!r}")


def psum_mean(tree, axis_name: str, denominator: float):
    """Sum over workers / denominator (parity: _model_update divides the
    aggregate buffer by num_aggregate, sync_replicas_master_nn.py:204-207)."""
    summed = lax.psum(tree, axis_name)
    return jax.tree_util.tree_map(lambda g: g / denominator, summed)


def quantized_psum(
    tree,
    axis_name: str,
    denominator: float,
    block_size: int = 0,
    rounding: str = "nearest",
    key: Optional[jax.Array] = None,
):
    """int8-quantized gradient all-reduce.

    Per leaf: global absmax (pmax) -> symmetric int8 quantize -> int32 psum
    -> dequantize / denominator. Deterministic (same scale on all workers) and
    exact-sum in int32 (no overflow below 2^23 workers). `block_size` > 0
    switches to per-block scales for tighter quantization error; `rounding=
    "stochastic"` makes each worker's quantization unbiased with independent
    noise (key folded by worker index and leaf), so rounding error averages
    out across the psum instead of accumulating (capabilities beyond the
    reference's lossless-but-slow Blosc path).
    """
    if rounding == "stochastic":
        if key is None:
            raise ValueError("stochastic rounding needs a key")
        key = jax.random.fold_in(key, lax.axis_index(axis_name))

    def one(i, g):
        g32 = g.astype(jnp.float32)
        leaf_key = jax.random.fold_in(key, i) if key is not None else None
        q, scale = quantize_int8(
            g32,
            axis_name=axis_name,
            block_size=block_size,
            rounding=rounding,
            key=leaf_key,
        )
        s = lax.psum(q.astype(jnp.int32), axis_name)
        deq = dequantize_int8(s, scale, block_size=block_size, shape=g.shape)
        return deq / denominator

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [one(i, g) for i, g in enumerate(leaves)]
    )


def aggregate_gradients(
    grads,
    axis_name: str,
    num_workers: int,
    num_aggregate: Optional[int] = None,
    mask_key: Optional[jax.Array] = None,
    mask_mode: str = "random_k",
    compress: Optional[str] = None,
    quant_block_size: int = 0,
    quant_rounding: str = "nearest",
    quant_key: Optional[jax.Array] = None,
):
    """The full PS aggregation: mask -> (quantized) psum -> / K."""
    k = (
        num_aggregate
        if (num_aggregate is not None and num_aggregate < num_workers)
        else num_workers
    )
    if k != num_workers:
        sel = aggregation_mask(axis_name, num_workers, num_aggregate, mask_key, mask_mode)
        grads = jax.tree_util.tree_map(lambda g: g * sel.astype(g.dtype), grads)
    if compress in (None, "none"):
        return psum_mean(grads, axis_name, float(k))
    if compress == "int8":
        return quantized_psum(
            grads,
            axis_name,
            float(k),
            block_size=quant_block_size,
            rounding=quant_rounding,
            key=quant_key,
        )
    raise ValueError(f"unknown compression {compress!r}")
