"""Tensor (model) parallelism for the transformer family.

The reference has no tensor parallelism (SURVEY.md section 2: "TP / PP / SP /
EP / CP ... absent"); this module is part of making the mesh design
future-proof beyond the reference's data-parallel-only scope. The layout is
the standard Megatron split mapped onto XLA collectives:

- attention: heads sharded over the `model` axis — `wqkv` is stored
  [D, 3, H, hd] and sharded on H, so every device computes full attention
  for its own heads with ZERO communication; `wo` is stored [H, hd, D]
  (row-parallel) and the output projection ends in one `psum`.
- MLP: `w_up` column-sharded [D, M/n] (independent GELUs), `w_down`
  row-sharded [M/n, D], one `psum` after the down-projection.
- embeddings: replicated by default; `shard_vocab=True` shards the
  embedding matrix [V, D] over the model axis (vocab-parallel): the
  lookup masks out-of-range ids and psums partial embeddings, and the
  unembedding keeps logits LOCAL [B, T, V/n] — the cross-entropy runs
  vocab-parallel (gathered row max + psum'd exp-sum plus the owner
  shard's target logit) so the full [B, T, V] tensor never exists on
  any device. Norms stay replicated.

Two psums per block per token — both ride ICI, both fused by XLA into the
surrounding matmuls. Gradients w.r.t. sharded weights are naturally local
(shard_map transposes the psum to a broadcast of the cotangent), so the
optimizer runs shard-wise with no extra collectives: tensor-parallel
training is `value_and_grad` + local optax update, exactly like the PS
engine but with sharded instead of replicated state.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.metrics import next_token_nll

# NOTE: ..models.transformer imports from this package (ring_attention), so
# importing it at module top would be circular; TransformerConfig appears
# only in (string) annotations and _rms_norm/init_transformer are imported
# lazily inside the functions that use them.
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from ..models.transformer import TransformerConfig

TP_AXIS = "model"


def make_tp_mesh(
    num_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D tensor-parallel mesh (axis 'model')."""
    from .mesh import make_mesh

    return make_mesh(num_workers=num_shards, devices=devices, axis_name=TP_AXIS)


def to_tp_layout(cfg: TransformerConfig, params: Dict) -> Dict:
    """Re-layout replicated transformer params for head/column sharding.

    wqkv [D, 3D] -> [D, 3, H, hd]  (shard dim 2)
    wo   [D, D]  -> [H, hd, D]     (shard dim 0)
    w_up [D, M] stays               (shard dim 1)
    w_down [M, D] stays             (shard dim 0)
    """
    h, hd = cfg.heads, cfg.head_dim
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["blocks"] = []
    for blk in params["blocks"]:
        b = dict(blk)
        b["wqkv"] = blk["wqkv"].reshape(cfg.dim, 3, h, hd)
        b["wo"] = blk["wo"].reshape(h, hd, cfg.dim)
        out["blocks"].append(b)
    return out


def from_tp_layout(cfg: TransformerConfig, params_tp: Dict) -> Dict:
    """Inverse of `to_tp_layout` (for checkpoint interchange)."""
    out = {k: v for k, v in params_tp.items() if k != "blocks"}
    out["blocks"] = []
    for blk in params_tp["blocks"]:
        b = dict(blk)
        b["wqkv"] = blk["wqkv"].reshape(cfg.dim, 3 * cfg.dim)
        b["wo"] = blk["wo"].reshape(cfg.dim, cfg.dim)
        out["blocks"].append(b)
    return out


def tp_param_specs(
    cfg: TransformerConfig, axis: str = TP_AXIS, shard_vocab: bool = False
) -> Dict:
    """PartitionSpec pytree matching `to_tp_layout` output."""
    blk = {
        "ln1": P(),
        "wqkv": P(None, None, axis, None),
        "wo": P(axis, None, None),
        "ln2": P(),
        "w_up": P(None, axis),
        "w_down": P(axis, None),
    }
    return {
        "embed": P(axis, None) if shard_vocab else P(),
        "pos_embed": P(),
        "out_norm": P(),
        "blocks": [dict(blk) for _ in range(cfg.depth)],
    }


def shard_params_tp(
    cfg: TransformerConfig, params_tp: Dict, mesh: Mesh, axis: str = TP_AXIS,
    shard_vocab: bool = False,
) -> Dict:
    """Place a TP-layout param tree on the mesh with the TP shardings."""
    n = mesh.shape[axis]
    if cfg.heads % n:
        raise ValueError(f"heads {cfg.heads} not divisible by {n} model shards")
    if (cfg.dim * cfg.mlp_ratio) % n:
        raise ValueError(
            f"mlp dim {cfg.dim * cfg.mlp_ratio} not divisible by {n} model shards"
        )
    if shard_vocab and cfg.vocab_size % n:
        raise ValueError(
            f"vocab {cfg.vocab_size} not divisible by {n} model shards"
        )
    from .mesh import place_on_mesh

    return place_on_mesh(params_tp, mesh, tp_param_specs(cfg, axis, shard_vocab))


def apply_transformer_tp(
    cfg: TransformerConfig,
    params: Dict,  # TP layout, LOCAL shards (inside shard_map)
    tokens: jax.Array,  # int32 [B, T] (replicated)
    axis_name: str = TP_AXIS,
    shard_vocab: bool = False,
) -> jax.Array:
    """Forward on one model shard.

    Returns replicated logits [B, T, vocab] (shard_vocab=False), or the
    LOCAL logits shard [B, T, vocab/n] (shard_vocab=True — feed to
    vocab_parallel_nll; the full logits tensor never materializes).

    Mirrors models/transformer.py:apply_transformer with the Megatron
    split; every activation entering/leaving a block is replicated, so the
    result is bit-identical (up to reduction order) to the single-device
    model.
    """
    from ..models.transformer import _rms_norm, local_attention

    attend_local = local_attention(cfg)
    b, t = tokens.shape
    pos = jnp.arange(t)
    if shard_vocab:
        # vocab-parallel lookup: my shard owns ids [off, off + v_loc);
        # out-of-range rows contribute zero, psum completes the embedding
        v_loc = params["embed"].shape[0]
        off = lax.axis_index(axis_name) * v_loc
        local_ids = jnp.clip(tokens - off, 0, v_loc - 1)
        mine = (tokens >= off) & (tokens < off + v_loc)
        emb = jnp.where(mine[..., None], params["embed"][local_ids], 0.0)
        x = lax.psum(emb, axis_name) + params["pos_embed"][pos][None]
    else:
        x = params["embed"][tokens] + params["pos_embed"][pos][None]

    cd = cfg.effective_compute_dtype

    def block(x, blk):
        x = x.astype(cd)
        blk = {k: v.astype(cd) for k, v in blk.items()}  # cast at use
        h = _rms_norm(x, blk["ln1"])
        qkv = jnp.einsum("btd,dchk->btchk", h, blk["wqkv"])  # [B,T,3,Hloc,hd]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = attend_local(q, k, v)  # local heads only
        proj = jnp.einsum("bthk,hkd->btd", o, blk["wo"])
        x = x + lax.psum(proj, axis_name)
        h = _rms_norm(x, blk["ln2"])
        down = jax.nn.gelu(h @ blk["w_up"]) @ blk["w_down"]
        return x + lax.psum(down, axis_name)

    if cfg.remat:
        block = jax.checkpoint(block)
    for blk in params["blocks"]:
        x = block(x, blk)
    xf = _rms_norm(x.astype(cd), params["out_norm"].astype(cd))
    # tied unembedding: local vocab columns only when sharded
    return xf @ params["embed"].T.astype(cd)


def vocab_parallel_nll(
    logits_local: jax.Array,  # [B, T, V/n] — this shard's vocab columns
    tokens: jax.Array,  # int32 [B, T] (replicated)
    axis_name: str = TP_AXIS,
) -> jax.Array:
    """Mean next-token NLL over vocab-sharded logits (Megatron-style).

    softmax statistics cross the mesh per position as the row max (an
    all_gather of n scalars + max — pmax has no JVP rule) and a psum'd
    exp-sum, plus the owner shard's target logit — the full [B, T, V]
    logits tensor never exists on any device.
    Matches ops/metrics.next_token_nll on gathered logits exactly (up to
    reduction order); tested in tests/test_tp.py.
    """
    lg = logits_local[:, :-1].astype(jnp.float32)  # positions predicting t+1
    tgt = tokens[:, 1:]
    v_loc = lg.shape[-1]
    off = lax.axis_index(axis_name) * v_loc

    # global row max, for stability only: its gradient cancels analytically
    # in m + log(sum exp(lg - m)), so stop_gradient is EXACT. pmax has no
    # JVP rule at all (even under stop_gradient the trace hits it), so the
    # max crosses the mesh as all_gather + max, which differentiates fine.
    m = lax.stop_gradient(
        jnp.max(lax.all_gather(jnp.max(lg, axis=-1), axis_name), axis=0)
    )
    z = lax.psum(jnp.sum(jnp.exp(lg - m[..., None]), axis=-1), axis_name)

    local_tgt = jnp.clip(tgt - off, 0, v_loc - 1)
    mine = (tgt >= off) & (tgt < off + v_loc)
    picked = jnp.take_along_axis(lg, local_tgt[..., None], axis=-1)[..., 0]
    tgt_logit = lax.psum(jnp.where(mine, picked, 0.0), axis_name)

    # log softmax(target) = tgt_logit - m - log z
    return jnp.mean(m + jnp.log(z) - tgt_logit)


def make_tp_forward(
    cfg: TransformerConfig, mesh: Mesh, axis_name: str = TP_AXIS, jit: bool = True,
    shard_vocab: bool = False,
):
    """Tensor-parallel forward: params in TP layout (sharded per
    `tp_param_specs`), tokens replicated -> logits. Replicated [B, T, V]
    by default; with shard_vocab the logits come back as a GLOBAL array
    sharded on the vocab dim (the full tensor still never lives on one
    device)."""
    mapped = jax.shard_map(
        partial(
            apply_transformer_tp, cfg, axis_name=axis_name,
            shard_vocab=shard_vocab,
        ),
        mesh=mesh,
        in_specs=(tp_param_specs(cfg, axis_name, shard_vocab), P()),
        out_specs=P(None, None, axis_name) if shard_vocab else P(),
        check_vma=False,
    )
    return jax.jit(mapped) if jit else mapped


def _is_replicated(spec: P) -> bool:
    return all(a is None for a in spec)


def opt_state_specs(opt_state, params, param_specs):
    """Spec tree for an optax state: every sub-tree that structurally
    matches the param tree (momentum/first/second-moment buffers) takes the
    param specs; every other leaf (step counters, scalars) is replicated.

    `opt_state` may be concrete arrays or `jax.eval_shape` output — only
    the structure is used.
    """
    params_treedef = jax.tree.structure(params)

    def walk(node):
        try:
            if jax.tree.structure(node) == params_treedef:
                return param_specs
        except Exception:
            pass
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(walk(c) for c in node))
        if isinstance(node, (list, tuple)):
            return type(node)(walk(c) for c in node)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return P()  # array leaf or None

    return walk(opt_state)


def _tp_param_shapes(cfg: TransformerConfig) -> Dict:
    from ..models.transformer import init_transformer

    shapes = jax.eval_shape(lambda: init_transformer(cfg, jax.random.key(0)))
    return jax.eval_shape(partial(to_tp_layout, cfg), shapes)


def init_tp_state(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    key: jax.Array,
    mesh: Mesh,
    axis_name: str = TP_AXIS,
    shard_vocab: bool = False,
):
    """Init (params_tp, opt_state) already placed with TP shardings —
    momentum buffers shard exactly like their parameters."""
    from ..models.transformer import init_transformer

    params_tp = shard_params_tp(
        cfg, to_tp_layout(cfg, init_transformer(cfg, key)), mesh, axis_name,
        shard_vocab=shard_vocab,
    )
    from .mesh import place_on_mesh

    opt_state = tx.init(params_tp)
    specs = opt_state_specs(
        opt_state, params_tp, tp_param_specs(cfg, axis_name, shard_vocab)
    )
    return params_tp, place_on_mesh(opt_state, mesh, specs)


def make_tp_train_step(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = TP_AXIS,
    donate: bool = True,
    shard_vocab: bool = False,
):
    """Jitted TP LM train step: (params_tp, opt_state, tokens) ->
    (params_tp, opt_state, loss). Params/opt state sharded over the model
    axis; tokens replicated. Gradients for sharded weights are local, so
    the optimizer update is shard-wise — no gradient collective at all
    (the two in-block psums are the only communication). With
    shard_vocab=True the embedding/logits run vocab-parallel (see
    vocab_parallel_nll)."""

    specs_tree = tp_param_specs(cfg, axis_name, shard_vocab)

    def shard_fn(params, opt_state, tokens):
        n = lax.axis_size(axis_name)

        def loss_fn(p):
            logits = apply_transformer_tp(
                cfg, p, tokens, axis_name, shard_vocab=shard_vocab
            )
            # With check_vma=False, shard_map AD computes exact grads of the
            # SUM over shards of the per-shard outputs (psum transposes to
            # psum — the correct transpose of that global function). Every
            # shard computes the identical loss, so differentiate loss/n:
            # sharded leaves' grads come out exact; replicated leaves' grads
            # come out as per-shard partials whose psum is exact (below).
            if shard_vocab:
                return vocab_parallel_nll(logits, tokens, axis_name) / n
            return next_token_nll(logits, tokens) / n

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(
            lambda g, s: lax.psum(g, axis_name) if _is_replicated(s) else g,
            grads,
            specs_tree,
            is_leaf=lambda x: isinstance(x, P),
        )
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss * n

    shapes = _tp_param_shapes(cfg)
    opt_specs = opt_state_specs(jax.eval_shape(tx.init, shapes), shapes, specs_tree)
    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(specs_tree, opt_specs, P()),
        out_specs=(specs_tree, opt_specs, P()),
        check_vma=False,
    )
    # donate params+opt state: the update writes in place in HBM instead of
    # double-buffering the model (same convention as ps.make_ps_train_step)
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())
