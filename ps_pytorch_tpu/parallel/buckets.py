"""Bucketed flat-buffer comm engine: one collective per bucket instead of
one per pytree leaf.

The reference PS sends one MPI message per layer (tag 88+l) and the
per-leaf collectives in collectives.py inherited that shape: a
ResNet/transformer gradient pytree has dozens of leaves, so every step
pays dozens of small, latency-bound collectives. The fused-buffer
all-reduce family (DynamiQ / THC, PAPERS.md) gets the wire win by
aggregating first: flatten the whole gradient into one contiguous f32
buffer, carve it into a handful of fixed-size buckets, and ship each
bucket as ONE collective — O(n_buckets) instead of O(n_leaves).

Two layers, both pure shape bookkeeping (everything here is static
Python arithmetic; the arrays never leave the traced program):

- ``TreeLayout`` — a pytree's flat geometry: per-leaf shapes/dtypes and
  element offsets into the concatenated f32 vector. ``tree_to_flat`` /
  ``flat_to_tree`` round-trip every leaf bit-exactly (dtype and shape
  preserved, empty and odd-sized leaves included). This is the engine's
  replacement for the ad-hoc ``ravel_pytree`` in the ZeRO-1 path: same
  concat order (``tree_leaves``), explicit f32 wire dtype.
- ``BucketPlan`` — a partition of the (alignment-padded) flat buffer
  into contiguous buckets. Boundaries are aligned to the int8
  quantization block size, so no quantization block ever straddles a
  bucket: each bucket quantizes with its own scale row(s) and ships
  independently.

PRNG discipline: stochastic-rounding keys are folded by each bucket's
START OFFSET in the flat buffer (``BucketPlan.starts``), not by its
enumeration index — position-stable derivation, so a bucket's noise
stream is a function of where its bytes live, not of how many buckets
precede it (collectives.py ``key_offsets``).

``FlatVector`` is the third layer (PSConfig.state_layout="flat"): a
param-shaped quantity — master params, an optimizer moment — stored AS
the padded flat f32 vector, with its TreeLayout/BucketPlan riding along
as static pytree metadata. The tree view exists only where the forward
pass needs it (``flat_to_tree``, slices XLA fuses away); the optimizer
update, the non-finite-guard rollback, and the wire all operate on the
whole vector. Checkpoints stay TREE-SHAPED at the save/restore boundary:
FlatVector registers flax serialization handlers that convert at the
edge, so checkpoints are bit-portable across ``state_layout`` (and
``bucket_bytes``), and pre-flat-state checkpoints load unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization


def _align_up(n: int, align: int) -> int:
    return -(-n // align) * align


@dataclasses.dataclass(frozen=True)
class TreeLayout:
    """Static geometry of a pytree flattened into one f32 vector."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]   # element offset of each leaf in the flat vec
    total: int                 # total elements (unpadded)


def tree_layout(tree) -> TreeLayout:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes, dtypes, offsets = [], [], []
    off = 0
    for leaf in leaves:
        shapes.append(tuple(int(d) for d in jnp.shape(leaf)))
        dtypes.append(jnp.result_type(leaf))
        offsets.append(off)
        off += int(jnp.size(leaf))
    return TreeLayout(
        treedef=treedef,
        shapes=tuple(shapes),
        dtypes=tuple(dtypes),
        offsets=tuple(offsets),
        total=off,
    )


def tree_to_flat(tree) -> jax.Array:
    """Concatenate every leaf (tree_leaves order) into one f32 vector."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate(
        [leaf.astype(jnp.float32).reshape(-1) for leaf in leaves]
    )


def flat_to_tree(layout: TreeLayout, flat: jax.Array):
    """Invert ``tree_to_flat``: slice per leaf, restore shape AND dtype.

    ``flat`` may be longer than ``layout.total`` (alignment padding);
    the tail is dropped."""
    leaves = []
    for shape, dtype, off in zip(layout.shapes, layout.dtypes,
                                 layout.offsets):
        n = 1
        for d in shape:
            n *= d
        leaves.append(
            jax.lax.slice(flat, (off,), (off + n,))
            .reshape(shape)
            .astype(dtype)
        )
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """A partition of the alignment-padded flat buffer into buckets."""

    total: int          # unpadded elements
    padded_total: int   # total rounded up to `align`
    align: int          # element alignment (int8 quantization block size)
    starts: Tuple[int, ...]  # bucket start offsets (== the PRNG fold keys)
    sizes: Tuple[int, ...]   # bucket lengths — EVERY one a multiple of
                             # `align` (padded_total is too, so the last
                             # bucket is as aligned as the rest; the
                             # sharded scatter's size // n splits rely
                             # on this)

    @property
    def n_buckets(self) -> int:
        return len(self.starts)


def plan_buckets(total: int, bucket_bytes: int, align: int = 1) -> BucketPlan:
    """Carve ``total`` f32 elements into buckets of ~``bucket_bytes``.

    ``bucket_bytes == 0`` means one fused bucket covering everything.
    Bucket boundaries are multiples of ``align`` (the int8 quantization
    block size), so per-block scale rows never straddle buckets; the
    bucket element count is ``bucket_bytes // 4`` rounded DOWN to the
    alignment (floored at one block) — a bucket never exceeds the
    requested byte budget by more than one block's padding."""
    if bucket_bytes < 0:
        raise ValueError(f"bucket_bytes must be >= 0, got {bucket_bytes}")
    align = max(int(align), 1)
    padded_total = max(_align_up(total, align), align)
    if bucket_bytes == 0:
        bucket_elems = padded_total
    else:
        bucket_elems = max((bucket_bytes // 4) // align * align, align)
    starts, sizes = [], []
    off = 0
    while off < padded_total:
        size = min(bucket_elems, padded_total - off)
        starts.append(off)
        sizes.append(size)
        off += size
    return BucketPlan(
        total=total,
        padded_total=padded_total,
        align=align,
        starts=tuple(starts),
        sizes=tuple(sizes),
    )


def split_buckets(flat_padded: jax.Array, plan: BucketPlan) -> List[jax.Array]:
    """Static slices of the padded flat buffer, one per bucket."""
    return [
        jax.lax.slice(flat_padded, (s,), (s + n,))
        for s, n in zip(plan.starts, plan.sizes)
    ]


def bucket_leaf_segments(layout: TreeLayout, plan: BucketPlan):
    """Which leaf fragments make up each bucket — the static inverse of
    "concatenate everything, then slice".

    Returns one tuple per bucket of ``(leaf_index, leaf_offset, length)``
    fragments in flat-buffer order; ``leaf_index is None`` marks the
    alignment-padding tail (zeros). This is what lets the pipelined wire
    assemble bucket ``b`` from ONLY the leaves whose bytes live in it:
    the serial spelling's global ``tree_to_flat`` concat makes every
    bucket's collective a dataflow descendant of every gradient leaf, so
    no scheduler — XLA's latency-hiding one included — may start any
    reduction before the whole backward finishes."""
    leaf_spans = []
    for i, (shape, off) in enumerate(zip(layout.shapes, layout.offsets)):
        n = 1
        for d in shape:
            n *= d
        if n:
            leaf_spans.append((off, off + n, i))
    out = []
    li = 0
    for start, size in zip(plan.starts, plan.sizes):
        end = start + size
        frags = []
        cur = start
        while li < len(leaf_spans) and leaf_spans[li][1] <= cur:
            li += 1
        j = li
        while j < len(leaf_spans) and leaf_spans[j][0] < end:
            l0, l1, idx = leaf_spans[j]
            s, e = max(cur, l0), min(end, l1)
            if s < e:
                frags.append((idx, s - l0, e - s))
                cur = e
            j += 1
        if cur < end:  # padding tail past the last leaf
            frags.append((None, 0, end - cur))
        out.append(tuple(frags))
    return tuple(out)


def assemble_bucket(leaves: Sequence[jax.Array], segments) -> jax.Array:
    """Build one contiguous f32 bucket from its own leaf fragments
    (``bucket_leaf_segments`` rows). Value-identical to slicing the
    padded global concat, but the result depends ONLY on the leaves in
    this bucket — the dataflow property the pipelined schedule needs."""
    parts = []
    for idx, off, n in segments:
        if idx is None:
            parts.append(jnp.zeros((n,), jnp.float32))
            continue
        leaf = leaves[idx].astype(jnp.float32).reshape(-1)
        if off == 0 and n == leaf.shape[0]:
            parts.append(leaf)
        else:
            parts.append(jax.lax.slice(leaf, (off,), (off + n,)))
    if not parts:
        return jnp.zeros((0,), jnp.float32)
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def leaves_from_buckets(layout: TreeLayout, plan: BucketPlan, outs):
    """Rebuild the tree from per-bucket results (CANONICAL bucket order)
    without concatenating the full vector first: each leaf gathers only
    the fragments of the buckets its bytes live in, so a leaf's rebuilt
    value is a dataflow descendant of ITS buckets alone (the per-leaf
    mirror of ``assemble_bucket``; the serial ``flat_to_tree(concat(...))``
    would chain every leaf behind every bucket's reduction)."""
    leaves = []
    for shape, dtype, off in zip(layout.shapes, layout.dtypes,
                                 layout.offsets):
        n = 1
        for d in shape:
            n *= d
        parts = []
        pos = off
        for b, (bs, sz) in enumerate(zip(plan.starts, plan.sizes)):
            be = bs + sz
            if be <= pos or bs >= off + n:
                continue
            s, e = max(pos, bs), min(off + n, be)
            if s < e:
                piece = outs[b]
                if s == bs and e == be:
                    parts.append(piece)
                else:
                    parts.append(jax.lax.slice(piece, (s - bs,), (e - bs,)))
        if not parts:
            flat = jnp.zeros((0,), jnp.float32)
        elif len(parts) == 1:
            flat = parts[0]
        else:
            flat = jnp.concatenate(parts)
        leaves.append(flat.reshape(shape).astype(dtype))
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


def readiness_bucket_order(
    plan: BucketPlan,
    layout: Optional[TreeLayout] = None,
    leaf_rank: Optional[Sequence[int]] = None,
) -> Tuple[int, ...]:
    """Bucket dispatch order for the pipelined wire: the bucket whose
    LAST-ready constituent gradient becomes available earliest goes
    first.

    ``leaf_rank[i]`` is the production rank of leaf ``i``'s gradient in
    the backward pass (smaller = produced earlier). The default rank is
    REVERSE construction order — backprop produces the last-constructed
    layers' gradients first — which for the contiguous canonical layout
    reduces to reversed bucket enumeration (the last bucket holds the
    last leaves). ``parallel/overlap.grad_leaf_readiness`` extracts the
    real production order from a traced jaxpr; tests pin that the
    default rank agrees with it on the real models, and callers with an
    exotic model can pass the measured rank instead."""
    if layout is None or leaf_rank is None:
        return tuple(reversed(range(plan.n_buckets)))
    segs = bucket_leaf_segments(layout, plan)
    n_leaves = len(layout.shapes)
    ready = []
    for b, frags in enumerate(segs):
        ranks = [
            leaf_rank[idx] for idx, _, _ in frags
            if idx is not None and idx < n_leaves
        ]
        # a bucket of pure padding is ready immediately
        ready.append((max(ranks) if ranks else -1, b))
    return tuple(b for _, b in sorted(ready))


def concat_buckets(buckets: Sequence[jax.Array]) -> jax.Array:
    return jnp.concatenate(list(buckets))


def pad_flat(flat: jax.Array, plan: BucketPlan) -> jax.Array:
    return jnp.pad(flat, (0, plan.padded_total - plan.total))


@flax.struct.dataclass
class FlatVector:
    """One param-shaped quantity stored flat (state_layout="flat").

    ``flat`` is the alignment-padded f32 vector in ``plan``'s geometry
    (``plan.padded_total`` elements; the pad tail is zero and never feeds
    the tree view). ``layout``/``plan`` are static aux data — part of the
    pytree STRUCTURE, not leaves — so jit specializes on the geometry and
    ``jax.tree_util.tree_map`` over a FlatVector is a whole-vector op.
    That makes the existing optax-style transforms fused for free: a
    ``tree_map`` over a single [P] leaf IS one vector op, and the guard's
    rollback ``jnp.where`` selects the whole state in a handful of ops.

    Serialization converts at the edge (see ``_flatvector_to_state_dict``
    below): a FlatVector's state dict is the TREE-shaped nested dict of
    its leaves, so checkpoints written from a flat-state run are
    byte-compatible with tree-state runs and with pre-flat checkpoints.
    """

    flat: jax.Array
    layout: TreeLayout = flax.struct.field(pytree_node=False)
    plan: BucketPlan = flax.struct.field(pytree_node=False)

    def tree(self):
        """Materialize the tree view (slices/reshapes XLA fuses away)."""
        return flat_to_tree(self.layout, self.flat)


def tree_view(params):
    """Tree view of a params-like object under either state layout."""
    if isinstance(params, FlatVector):
        return params.tree()
    return params


def to_flat_vector(tree, plan: BucketPlan) -> FlatVector:
    """Pack a pytree into a FlatVector with ``plan``'s padding."""
    return FlatVector(
        flat=pad_flat(tree_to_flat(tree), plan),
        layout=tree_layout(tree),
        plan=plan,
    )


def _np_flat_to_tree(layout: TreeLayout, flat):
    """Host-side (numpy) twin of flat_to_tree for the checkpoint edge —
    serialization must not touch a device."""
    flat = np.asarray(flat)
    leaves = []
    for shape, dtype, off in zip(layout.shapes, layout.dtypes,
                                 layout.offsets):
        n = 1
        for d in shape:
            n *= d
        leaves.append(flat[off:off + n].reshape(shape).astype(dtype))
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


def _np_tree_to_flat(layout: TreeLayout, plan: BucketPlan, tree):
    flat = np.zeros((plan.padded_total,), np.float32)
    for leaf, off in zip(jax.tree_util.tree_leaves(tree), layout.offsets):
        arr = np.asarray(leaf)
        flat[off:off + arr.size] = arr.astype(np.float32).reshape(-1)
    return flat


def _flatvector_to_state_dict(fv: FlatVector):
    # checkpoints are tree-shaped at the boundary: store the leaves, not
    # the buffer, so the file is identical to a tree-state run's
    return serialization.to_state_dict(
        _np_flat_to_tree(fv.layout, fv.flat)
    )


def _flatvector_from_state_dict(fv: FlatVector, state) -> FlatVector:
    # the stored dict is tree-shaped (this handler wrote it, or the
    # checkpoint predates flat state); rebuild the padded vector in the
    # TARGET's geometry — portability across bucket_bytes/state_layout
    # falls out, because the tree is the interchange format
    template = _np_flat_to_tree(
        fv.layout, np.zeros((fv.plan.padded_total,), np.float32)
    )
    tree = serialization.from_state_dict(template, state)
    return fv.replace(flat=_np_tree_to_flat(fv.layout, fv.plan, tree))


serialization.register_serialization_state(
    FlatVector,
    _flatvector_to_state_dict,
    _flatvector_from_state_dict,
    override=True,  # flax.struct registered field-wise handlers already
)


def piece_stream(tree, bucket_bytes, align: int = 1,
                 flat_output: bool = False, pipelined: bool = False,
                 bucket_output: bool = False):
    """The comm engine's one entry point: what a collective scheme ships.

    Returns ``(pieces, key_ids, rebuild)``:

    - ``pieces``: the arrays to quantize/reduce — the pytree's leaves
      verbatim when ``bucket_bytes is None`` (legacy per-leaf wire), or
      the contiguous f32 buckets of the flattened tree otherwise
      (``0`` = one fused bucket, ``N`` = ~N-byte buckets aligned to
      ``align`` elements);
    - ``key_ids``: the position-stable PRNG fold value for each piece —
      the enumeration index per leaf (the legacy discipline error-
      feedback residuals already mirror), the bucket's START OFFSET in
      the flat buffer per bucket (so a piece's stochastic-rounding
      stream depends on where its bytes live, not on how many pieces
      precede it);
    - ``rebuild``: maps the per-piece aggregation results (same shapes
      as ``pieces``) back to the original tree structure, restoring
      every leaf's dtype/shape and dropping alignment padding — or, with
      ``flat_output=True`` (state_layout="flat": the consumer is the
      fused vector update, not a per-leaf optimizer), to ONE padded flat
      f32 vector in the same ``align`` geometry, skipping the per-leaf
      scatter entirely. The pieces (and therefore the wire) are
      IDENTICAL either way — flat_output changes only the rebuild.

    ``pipelined=True`` (PSConfig.overlap="pipelined", bucketed wires
    only) keeps the SAME plan, the same leaf->bucket byte assignment,
    and the same start-offset PRNG ids — so every piece's VALUES are
    bit-identical to the serial stream — but changes the dataflow and
    the enumeration:

    - each bucket is assembled from its own leaves' fragments
      (``assemble_bucket``), never by slicing a global concat, so bucket
      b's reduction depends only on the gradients whose bytes live in b;
    - pieces stream in READINESS order (``readiness_bucket_order``:
      last-constructed leaves backprop first, so the last bucket
      dispatches first) — reverse-topological bucket enumeration;
    - the tree rebuild gathers each leaf from its own buckets
      (``leaves_from_buckets``) instead of slicing the full concat.

    ``bucket_output=True`` (pipelined flat state: the consumer is the
    PER-BUCKET vector update) makes ``rebuild`` return the list of
    per-bucket f32 aggregates in CANONICAL bucket order instead of any
    concatenation — the one spelling with no whole-vector barrier at
    all. Requires a bucketed wire."""
    if bucket_output and bucket_bytes is None:
        raise ValueError("bucket_output needs a bucketed wire "
                         "(bucket_bytes is None = per-leaf)")
    if bucket_bytes is None:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if flat_output:
            layout = tree_layout(tree)
            plan = plan_buckets(layout.total, 0, align=align)
            return (
                leaves,
                tuple(range(len(leaves))),
                lambda outs: pad_flat(
                    concat_buckets(
                        [o.astype(jnp.float32).reshape(-1) for o in outs]
                    )
                    if outs
                    else jnp.zeros((0,), jnp.float32),
                    plan,
                ),
            )
        return (
            leaves,
            tuple(range(len(leaves))),
            lambda outs: jax.tree_util.tree_unflatten(treedef, outs),
        )
    layout = tree_layout(tree)
    plan = plan_buckets(layout.total, bucket_bytes, align=align)
    if pipelined:
        order = readiness_bucket_order(plan)
        segs = bucket_leaf_segments(layout, plan)
        leaves = jax.tree_util.tree_leaves(tree)
        pieces = [assemble_bucket(leaves, segs[b]) for b in order]
        key_ids = tuple(plan.starts[b] for b in order)

        def rebuild(outs):
            canon = [None] * plan.n_buckets
            for b, o in zip(order, outs):
                canon[b] = o
            if bucket_output:
                return canon
            if flat_output:
                return concat_buckets(canon)
            return leaves_from_buckets(layout, plan, canon)

        return (pieces, key_ids, rebuild)
    pieces = split_buckets(pad_flat(tree_to_flat(tree), plan), plan)
    if bucket_output:
        rebuild = lambda outs: list(outs)
    elif flat_output:
        rebuild = concat_buckets
    else:
        rebuild = lambda outs: flat_to_tree(layout, concat_buckets(outs))
    return (pieces, plan.starts, rebuild)
