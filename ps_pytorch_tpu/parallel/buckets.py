"""Bucketed flat-buffer comm engine: one collective per bucket instead of
one per pytree leaf.

The reference PS sends one MPI message per layer (tag 88+l) and the
per-leaf collectives in collectives.py inherited that shape: a
ResNet/transformer gradient pytree has dozens of leaves, so every step
pays dozens of small, latency-bound collectives. The fused-buffer
all-reduce family (DynamiQ / THC, PAPERS.md) gets the wire win by
aggregating first: flatten the whole gradient into one contiguous f32
buffer, carve it into a handful of fixed-size buckets, and ship each
bucket as ONE collective — O(n_buckets) instead of O(n_leaves).

Two layers, both pure shape bookkeeping (everything here is static
Python arithmetic; the arrays never leave the traced program):

- ``TreeLayout`` — a pytree's flat geometry: per-leaf shapes/dtypes and
  element offsets into the concatenated f32 vector. ``tree_to_flat`` /
  ``flat_to_tree`` round-trip every leaf bit-exactly (dtype and shape
  preserved, empty and odd-sized leaves included). This is the engine's
  replacement for the ad-hoc ``ravel_pytree`` in the ZeRO-1 path: same
  concat order (``tree_leaves``), explicit f32 wire dtype.
- ``BucketPlan`` — a partition of the (alignment-padded) flat buffer
  into contiguous buckets. Boundaries are aligned to the int8
  quantization block size, so no quantization block ever straddles a
  bucket: each bucket quantizes with its own scale row(s) and ships
  independently.

PRNG discipline: stochastic-rounding keys are folded by each bucket's
START OFFSET in the flat buffer (``BucketPlan.starts``), not by its
enumeration index — position-stable derivation, so a bucket's noise
stream is a function of where its bytes live, not of how many buckets
precede it (collectives.py ``key_offsets``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp


def _align_up(n: int, align: int) -> int:
    return -(-n // align) * align


@dataclasses.dataclass(frozen=True)
class TreeLayout:
    """Static geometry of a pytree flattened into one f32 vector."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]   # element offset of each leaf in the flat vec
    total: int                 # total elements (unpadded)


def tree_layout(tree) -> TreeLayout:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes, dtypes, offsets = [], [], []
    off = 0
    for leaf in leaves:
        shapes.append(tuple(int(d) for d in jnp.shape(leaf)))
        dtypes.append(jnp.result_type(leaf))
        offsets.append(off)
        off += int(jnp.size(leaf))
    return TreeLayout(
        treedef=treedef,
        shapes=tuple(shapes),
        dtypes=tuple(dtypes),
        offsets=tuple(offsets),
        total=off,
    )


def tree_to_flat(tree) -> jax.Array:
    """Concatenate every leaf (tree_leaves order) into one f32 vector."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate(
        [leaf.astype(jnp.float32).reshape(-1) for leaf in leaves]
    )


def flat_to_tree(layout: TreeLayout, flat: jax.Array):
    """Invert ``tree_to_flat``: slice per leaf, restore shape AND dtype.

    ``flat`` may be longer than ``layout.total`` (alignment padding);
    the tail is dropped."""
    leaves = []
    for shape, dtype, off in zip(layout.shapes, layout.dtypes,
                                 layout.offsets):
        n = 1
        for d in shape:
            n *= d
        leaves.append(
            jax.lax.slice(flat, (off,), (off + n,))
            .reshape(shape)
            .astype(dtype)
        )
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """A partition of the alignment-padded flat buffer into buckets."""

    total: int          # unpadded elements
    padded_total: int   # total rounded up to `align`
    align: int          # element alignment (int8 quantization block size)
    starts: Tuple[int, ...]  # bucket start offsets (== the PRNG fold keys)
    sizes: Tuple[int, ...]   # bucket lengths — EVERY one a multiple of
                             # `align` (padded_total is too, so the last
                             # bucket is as aligned as the rest; the
                             # sharded scatter's size // n splits rely
                             # on this)

    @property
    def n_buckets(self) -> int:
        return len(self.starts)


def plan_buckets(total: int, bucket_bytes: int, align: int = 1) -> BucketPlan:
    """Carve ``total`` f32 elements into buckets of ~``bucket_bytes``.

    ``bucket_bytes == 0`` means one fused bucket covering everything.
    Bucket boundaries are multiples of ``align`` (the int8 quantization
    block size), so per-block scale rows never straddle buckets; the
    bucket element count is ``bucket_bytes // 4`` rounded DOWN to the
    alignment (floored at one block) — a bucket never exceeds the
    requested byte budget by more than one block's padding."""
    if bucket_bytes < 0:
        raise ValueError(f"bucket_bytes must be >= 0, got {bucket_bytes}")
    align = max(int(align), 1)
    padded_total = max(_align_up(total, align), align)
    if bucket_bytes == 0:
        bucket_elems = padded_total
    else:
        bucket_elems = max((bucket_bytes // 4) // align * align, align)
    starts, sizes = [], []
    off = 0
    while off < padded_total:
        size = min(bucket_elems, padded_total - off)
        starts.append(off)
        sizes.append(size)
        off += size
    return BucketPlan(
        total=total,
        padded_total=padded_total,
        align=align,
        starts=tuple(starts),
        sizes=tuple(sizes),
    )


def split_buckets(flat_padded: jax.Array, plan: BucketPlan) -> List[jax.Array]:
    """Static slices of the padded flat buffer, one per bucket."""
    return [
        jax.lax.slice(flat_padded, (s,), (s + n,))
        for s, n in zip(plan.starts, plan.sizes)
    ]


def concat_buckets(buckets: Sequence[jax.Array]) -> jax.Array:
    return jnp.concatenate(list(buckets))


def pad_flat(flat: jax.Array, plan: BucketPlan) -> jax.Array:
    return jnp.pad(flat, (0, plan.padded_total - plan.total))


def piece_stream(tree, bucket_bytes, align: int = 1):
    """The comm engine's one entry point: what a collective scheme ships.

    Returns ``(pieces, key_ids, rebuild)``:

    - ``pieces``: the arrays to quantize/reduce — the pytree's leaves
      verbatim when ``bucket_bytes is None`` (legacy per-leaf wire), or
      the contiguous f32 buckets of the flattened tree otherwise
      (``0`` = one fused bucket, ``N`` = ~N-byte buckets aligned to
      ``align`` elements);
    - ``key_ids``: the position-stable PRNG fold value for each piece —
      the enumeration index per leaf (the legacy discipline error-
      feedback residuals already mirror), the bucket's START OFFSET in
      the flat buffer per bucket (so a piece's stochastic-rounding
      stream depends on where its bytes live, not on how many pieces
      precede it);
    - ``rebuild``: maps the per-piece aggregation results (same shapes
      as ``pieces``) back to the original tree structure, restoring
      every leaf's dtype/shape and dropping alignment padding.
    """
    if bucket_bytes is None:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return (
            leaves,
            tuple(range(len(leaves))),
            lambda outs: jax.tree_util.tree_unflatten(treedef, outs),
        )
    layout = tree_layout(tree)
    plan = plan_buckets(layout.total, bucket_bytes, align=align)
    pieces = split_buckets(pad_flat(tree_to_flat(tree), plan), plan)
    return (
        pieces,
        plan.starts,
        lambda outs: flat_to_tree(layout, concat_buckets(outs)),
    )
