"""2-D parallelism: pipeline stages x expert parallelism (MoE-in-PP).

Closes the second half of round-1 NOTES gap #4: MoE models deeper than one
stage. One (stage x expert) mesh:

- block params stacked [depth, ...] and sharded over `stage` (exactly
  parallel/pp.py); the per-block expert weights [depth, E, D, M] shard
  over BOTH axes — depth over stage, experts over expert;
- the global batch shards over `expert` (the expert axis doubles as data
  parallelism, as everywhere else) and each expert column runs the GPipe
  microbatch schedule independently; within a tick, each block's MoE MLP
  all_to_alls tokens across the expert axis. Stage ppermutes and expert
  all_to_alls touch orthogonal mesh dimensions — no new primitive.

Loss/aux use the same tick-folded form as pp.py (never more than one
microbatch's [B_mb, T, V] logits live), with aux additionally masked to
VALID ticks only (warmup/drain ticks process garbage activations whose
router statistics must not leak into the load-balance loss).

Gradient rule: differentiate local/(n_stage * n_ep); replicated leaves
psum over both axes, stage-sharded block leaves psum over expert only,
(stage x expert)-sharded expert weights need no psum at all (the
all_to_all transpose routed every column's contribution home).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerConfig
from ..ops.metrics import next_token_nll
from .moe import EP_AXIS, MoEConfig, init_moe_params, moe_mlp_local
from .pp import PP_AXIS, from_pp_layout, to_pp_layout  # noqa: F401 (interchange)
from .tp import opt_state_specs


def make_mesh_pp_moe(
    num_stages: int,
    num_ep: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(num_stages x num_ep) mesh; stage outer (ppermute is cheap and
    infrequent per tick), expert inner (two all_to_alls per MoE layer —
    keep them on the fastest links)."""
    devs = list(devices if devices is not None else jax.devices())
    need = num_stages * num_ep
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(num_stages, num_ep)
    return Mesh(grid, (PP_AXIS, EP_AXIS))


def pp_moe_param_specs(cfg: TransformerConfig) -> Dict:
    blk = {
        "ln1": P(PP_AXIS),
        "wqkv": P(PP_AXIS),
        "wo": P(PP_AXIS),
        "ln2": P(PP_AXIS),
        "wg": P(PP_AXIS),
        "w_up_e": P(PP_AXIS, EP_AXIS),
        "w_down_e": P(PP_AXIS, EP_AXIS),
    }
    return {"embed": P(), "pos_embed": P(), "out_norm": P(), "blocks": blk}


def shard_tokens_pp_moe(tokens, mesh: Mesh):
    """[B_global, T] -> B sharded over the expert axis (replicated over
    stages — every stage of a column sees the same tokens, as in pp)."""
    return jax.device_put(tokens, NamedSharding(mesh, P(EP_AXIS)))


def _pp_moe_loss(
    cfg: TransformerConfig,
    moe: MoEConfig,
    params: Dict,  # PP layout, LOCAL shards
    tokens: jax.Array,  # [M, B_mb_local, T]
):
    """Tick-folded pipeline loss for the MoE transformer (the shared
    pp.gpipe_fold schedule with a MoE block body); returns (task_loss,
    aux) — task replicated within a column via the stage psum-mask, aux
    averaged per valid tick and block."""
    from ..models.transformer import _rms_norm, select_attention, transformer_block
    from .pp import gpipe_fold

    m = tokens.shape[0]
    pos = jnp.arange(tokens.shape[2])
    cd = cfg.effective_compute_dtype
    attend = select_attention(cfg, None)

    def one_block(x, blk):
        aux_cell = []

        def mlp(h):
            out, aux = moe_mlp_local(h, blk, moe, EP_AXIS)
            aux_cell.append(aux)
            return out

        x = transformer_block(cfg, x, blk, attend, mlp=mlp)
        return x, aux_cell[0]

    def local_blocks(x):
        body = lambda x, blk: one_block(x, blk)
        if cfg.remat:
            body = jax.checkpoint(body)
        x, auxes = lax.scan(body, x, params["blocks"])
        return x, jnp.sum(auxes)

    def embed(mb_idx):
        tok = lax.dynamic_index_in_dim(
            tokens, jnp.clip(mb_idx, 0, m - 1), 0, keepdims=False
        )
        return (params["embed"][tok] + params["pos_embed"][pos][None]).astype(cd)

    def mb_loss(y, tok_mb):
        xf = _rms_norm(y, params["out_norm"].astype(cd))
        logits = xf @ params["embed"].T.astype(cd)  # [B_mb, T, V]
        return next_token_nll(logits, tok_mb)

    task, aux_sum = gpipe_fold(
        PP_AXIS, tokens, cfg.dim, cd, embed, local_blocks, mb_loss
    )
    # aux_sum = sum over (valid ticks x local blocks); psum over stages
    # then normalize to mean-per-block-per-microbatch (apply_moe_transformer
    # divides by depth the same way)
    aux = lax.psum(aux_sum, PP_AXIS) / (m * cfg.depth)
    return task, aux


def make_pp_moe_train_step(
    cfg: TransformerConfig,
    moe: MoEConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    num_microbatches: int,
    donate: bool = True,
):
    """Jitted 2-D (stage x expert) MoE train step: (params_pp, opt_state,
    tokens [B_global, T]) -> (params_pp, opt_state, task_loss, aux)."""
    specs_tree = pp_moe_param_specs(cfg)

    def shard_fn(params, opt_state, tokens):
        n_pp = lax.axis_size(PP_AXIS)
        n_ep = lax.axis_size(EP_AXIS)
        bsz, t = tokens.shape
        if bsz % num_microbatches:
            raise ValueError(
                f"batch {bsz} not divisible by {num_microbatches} microbatches"
            )
        mb = tokens.reshape(num_microbatches, bsz // num_microbatches, t)

        def local_obj(p):
            task, aux = _pp_moe_loss(cfg, moe, p, mb)
            # task+aux are stage-replicated within a column; the shard sum
            # is n_pp * (sum over columns) -> scale to the column mean
            return (task + moe.aux_loss_weight * aux) / (n_pp * n_ep), (task, aux)

        (_, (task, aux)), grads = jax.value_and_grad(local_obj, has_aux=True)(
            params
        )

        def reduce_grad(g, s):
            if s == P():
                return lax.psum(g, (PP_AXIS, EP_AXIS))
            if s == P(PP_AXIS):
                return lax.psum(g, EP_AXIS)
            return g  # P(stage, expert): all_to_all already routed it home

        grads = jax.tree.map(
            reduce_grad, grads, specs_tree, is_leaf=lambda x: isinstance(x, P)
        )
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return (
            new_params,
            new_opt,
            lax.pmean(task, EP_AXIS),
            lax.pmean(aux, EP_AXIS),
        )

    shapes = jax.eval_shape(
        lambda: to_pp_layout(cfg, init_moe_params(cfg, moe, jax.random.key(0)))
    )
    opt_specs = opt_state_specs(jax.eval_shape(tx.init, shapes), shapes, specs_tree)
    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(specs_tree, opt_specs, P(EP_AXIS)),
        out_specs=(specs_tree, opt_specs, P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())


def init_pp_moe_state(
    cfg: TransformerConfig,
    moe: MoEConfig,
    tx: optax.GradientTransformation,
    key: jax.Array,
    mesh: Mesh,
):
    """Init (params_pp, opt_state) placed for the (stage x expert) mesh."""
    from .mesh import place_on_mesh

    n = mesh.shape[PP_AXIS]
    if cfg.depth % n:
        raise ValueError(f"depth {cfg.depth} not divisible by {n} stages")
    e = moe.num_experts
    if e % mesh.shape[EP_AXIS]:
        raise ValueError(
            f"{e} experts not divisible by {mesh.shape[EP_AXIS]} expert shards"
        )
    specs = pp_moe_param_specs(cfg)
    params = place_on_mesh(
        to_pp_layout(cfg, init_moe_params(cfg, moe, key)), mesh, specs
    )
    opt_state = tx.init(params)
    return params, place_on_mesh(
        opt_state, mesh, opt_state_specs(opt_state, params, specs)
    )
