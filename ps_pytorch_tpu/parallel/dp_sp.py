"""2-D parallelism: PS data parallelism x ring-attention sequence parallelism.

The composition argument made executable: because the PS engine keeps params
replicated (mesh.py docstring) and the sequence-parallel transformer keeps
them replicated too (models/transformer.py), the two axes compose on one
2-D mesh ("workers", "seq") with no weight re-sharding — batch shards ride
the dp axis, sequence shards the sp axis, gradients meet in one
pmean-over-dp + psum-over-sp.

Gradient math: each (dp, sp) device differentiates only its LOCAL slice of
the objective — loss_sum_local / count_global, with the global count a
constant — and the gradients are psum'd over sp exactly once afterwards.
Differentiating a psum'd loss inside shard_map would seed a cotangent on
every sp device and overcount each term n_sp times (the ring's ppermute
transpose already routes cross-device contributions back to the device
owning the parameters' activation path). Averaging over dp is the PS
aggregation (sync_replicas_master_nn.py:204-208 semantics, batch-mean form).

Next-token targets cross sequence-shard boundaries: the target of a shard's
last token is the NEXT shard's first token, fetched with one ppermute; the
final global position is masked out of the loss.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerConfig, apply_transformer
from .mesh import WORKER_AXIS
from .ring_attention import SEQ_AXIS


def make_mesh_2d(
    num_dp: int,
    num_sp: int,
    devices: Optional[Sequence[jax.Device]] = None,
    dp_axis: str = WORKER_AXIS,
    sp_axis: str = SEQ_AXIS,
) -> Mesh:
    """(num_dp x num_sp) mesh; dp outer so batch shards stay on neighboring
    devices (the sp ring is the inner, highest-bandwidth dimension)."""
    devs = list(devices if devices is not None else jax.devices())
    need = num_dp * num_sp
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(num_dp, num_sp)
    return Mesh(grid, (dp_axis, sp_axis))


def shard_tokens_2d(
    tokens, mesh: Mesh, dp_axis: str = WORKER_AXIS, sp_axis: str = SEQ_AXIS
):
    """[B_global, T_global] -> B over dp, T over sp."""
    return jax.device_put(tokens, NamedSharding(mesh, P(dp_axis, sp_axis)))


def lm_loss_local(
    cfg: TransformerConfig,
    params,
    tokens: jax.Array,
    sp_axis: str = SEQ_AXIS,
):
    """LOCAL slice of the global-mean next-token loss for one (dp, sp) shard
    of tokens [b_local, t_local].

    Returns loss_sum_local / count_global. The global loss is the psum of
    this over sp — do that OUTSIDE the differentiated function (see module
    docstring: differentiating through the psum overcounts gradients)."""
    b_loc, t_loc = tokens.shape
    n_sp = lax.axis_size(sp_axis)
    s = lax.axis_index(sp_axis)
    logits = apply_transformer(cfg, params, tokens, seq_axis_name=sp_axis)
    # target of my last token = next shard's first token (ring shift left)
    nxt_first = lax.ppermute(
        tokens[:, :1], sp_axis, [(j, (j - 1) % n_sp) for j in range(n_sp)]
    )
    tgt = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    pos = s * t_loc + jnp.arange(t_loc)
    valid = (pos < n_sp * t_loc - 1).astype(jnp.float32)  # drop final position
    loss_sum = jnp.sum(nll * valid[None, :])
    count = jnp.float32(b_loc) * jnp.sum(valid)
    return loss_sum / lax.psum(count, sp_axis)


def make_lm_train_step(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    dp_axis: str = WORKER_AXIS,
    sp_axis: str = SEQ_AXIS,
    donate: bool = True,
):
    """Jitted 2-D train step: (params, opt_state, tokens) ->
    (params, opt_state, loss). params/opt_state replicated; tokens sharded
    [B over dp, T over sp]."""

    def worker_fn(params, opt_state, tokens):
        loss_local, grads = jax.value_and_grad(
            lambda p: lm_loss_local(cfg, p, tokens, sp_axis)
        )(params)
        # exact sequence gradient: sum local partials over sp exactly once;
        # PS aggregation: mean over dp (each dp shard saw a disjoint slice)
        grads = lax.pmean(lax.psum(grads, sp_axis), dp_axis)
        loss = lax.pmean(lax.psum(loss_local, sp_axis), dp_axis)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    mapped = jax.shard_map(
        worker_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(dp_axis, sp_axis)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())
