"""Device-mesh construction — the TPU-native replacement for the reference's
MPI star topology (mpirun + hostfile, /root/reference/src/run_pytorch.sh:1-16,
tools/pytorch_ec2.py).

One mesh axis, `workers`, plays the role of the reference's MPI worker ranks;
the parameter server is not a separate rank but a *protocol* over the mesh
(see parallel/ps.py): params replicated (the "bcast"), gradients psum'd (the
"gather+aggregate"), optimizer state replicated or sharded (the "PS chip",
generalized). Multi-host extends the same axis over DCN via jax.distributed.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"


def make_mesh(
    num_workers: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = WORKER_AXIS,
) -> Mesh:
    """Build a 1-D mesh of `num_workers` devices (default: all devices).

    Unlike the reference — where cluster size is fixed at mpirun time by the
    hostfile (run_pytorch.sh:1) — the same process can carve any leading
    subset of visible chips into a worker mesh.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = num_workers or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} workers but only {len(devs)} devices")
    return Mesh(np.array(devs[:n]), (axis_name,))


DCN_AXIS = "dcn"


def make_hybrid_mesh(
    num_hosts: Optional[int] = None,
    per_host: Optional[int] = None,
    axis_names: tuple = (DCN_AXIS, WORKER_AXIS),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """2-level (hosts x chips-per-host) mesh for multi-host PS training:
    the outer axis crosses DCN, the inner axis stays on ICI.

    Use with `PSConfig(axis_name=(DCN_AXIS, WORKER_AXIS), num_workers=
    total_chips)`: every collective in the PS engine takes the axis-name
    tuple, so gradient aggregation psums hierarchically — XLA reduces
    within each host over ICI first and crosses DCN once with the partial
    sums, which is exactly the traffic layout the reference's star
    topology cannot express (every worker's full gradient crossed the
    network to the PS, SURVEY.md section 2 #2).

    On a real pod (jax.process_count() > 1) device placement comes from
    mesh_utils.create_hybrid_device_mesh; single-process (tests, one
    host) falls back to a reshape of the flat device list.
    """
    devs = list(devices if devices is not None else jax.devices())
    n_hosts = num_hosts or jax.process_count()
    per = per_host or len(devs) // n_hosts
    need = n_hosts * per
    if need > len(devs) or per < 1:
        raise ValueError(
            f"need {n_hosts} hosts x {max(per, 1)} chips, have {len(devs)} devices"
        )
    if jax.process_count() > 1 and devices is None:
        # subsets must stay balanced PER HOST: take the leading `per` chips
        # of each of the first n_hosts processes (a flat devs[:need] slice
        # would take all of host 0 first and leave later hosts empty)
        by_host: dict = {}
        for d in devs:
            by_host.setdefault(d.process_index, []).append(d)
        hosts = sorted(by_host)[:n_hosts]
        if any(len(by_host[h]) < per for h in hosts) or len(hosts) < n_hosts:
            raise ValueError(
                f"need {n_hosts} hosts x {per} chips, have "
                f"{ {h: len(v) for h, v in by_host.items()} }"
            )
        picked = [d for h in hosts for d in by_host[h][:per]]
        from jax.experimental import mesh_utils

        # granule = process (host), matching this function's contract
        grid = mesh_utils.create_hybrid_device_mesh(
            (1, per), (n_hosts, 1), devices=picked, process_is_granule=True
        )
    else:
        grid = np.array(devs[:need]).reshape(n_hosts, per)
    return Mesh(grid, axis_names)


def place_on_mesh(tree, mesh: Mesh, specs):
    """Place every leaf of `tree` on `mesh` with its PartitionSpec from
    `specs` (a matching pytree of PartitionSpecs). None leaves (e.g. a
    momentum-free optimizer's buffer slot) pass through untouched.

    The single implementation behind shard_params_{tp,pp,moe},
    init_{tp,pp,moe}_state, and checkpoint.restore_sharded.
    """
    return jax.tree_util.tree_map(
        lambda x, s: None if x is None else jax.device_put(x, NamedSharding(mesh, s)),
        tree,
        specs,
        is_leaf=lambda x: x is None,
    )


def batch_sharding(mesh: Mesh, axis_name: str = WORKER_AXIS) -> NamedSharding:
    """Sharding for a global batch: split along the leading (batch) dim."""
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def worker_stacked_sharding(mesh: Mesh, axis_name: str = WORKER_AXIS) -> NamedSharding:
    """Sharding for per-worker state stacked on a leading axis of size
    num_workers (used for `bn_mode='local'` BatchNorm stats)."""
    return NamedSharding(mesh, P(axis_name))


def pool_sharding(mesh: Mesh, dim: int = 1,
                  axis_name: str = WORKER_AXIS) -> NamedSharding:
    """Sharding that splits dimension ``dim`` of a pooled buffer over the
    worker axis. The serving engine's KV pool is [depth, slots, ...] —
    slots (dim 1) shard across the mesh while depth stays whole, so every
    worker owns a contiguous band of request slots and the decode step is
    embarrassingly slot-parallel (zero collectives, see serve/engine.py)."""
    return NamedSharding(mesh, P(*([None] * dim), axis_name))


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host training job over DCN (replaces mpirun's process
    spawn + rendezvous, run_pytorch.sh:1). No-op for single-process runs.

    Pass "auto" on Cloud TPU pods: jax.distributed.initialize() with no
    arguments discovers the coordinator and process ids from the TPU
    metadata service — every host runs the identical command (tools/
    run_multihost.sh relies on this).

    CPU backend note (the 2-process localhost jobs tests/test_multihost.py
    spawns): jax 0.4.37 defaults ``jax_cpu_collectives_implementation`` to
    "none", so ANY multiprocess computation — including the assert_equal
    psum hidden inside ``device_put`` onto a non-addressable sharding —
    dies with "Multiprocess computations aren't implemented on the CPU
    backend". This jaxlib ships the gloo TCP collectives, so a
    multi-process job that is explicitly pinned to CPU flips them on
    before the backend is created. Must run before anything touches
    ``jax.devices()`` (backend creation reads the flag once).

    SPMD contract: every process runs this with the same effective
    arguments, and everything downstream (mesh construction, the train
    loop's collectives) assumes bit-identical control flow across hosts.
    Host code in this module is in psdiverge's scope — guards derived
    from per-process values around collective ops are flagged as PSL006
    (ARCHITECTURE §7b); the env-var gate above stays clean because it
    guards only process-local jax.config writes, never a collective."""
    if coordinator_address is None:
        return
    import os

    plats = {
        p.strip().lower()
        for p in os.environ.get("JAX_PLATFORMS", "").split(",")
        if p.strip()
    }
    if "cpu" in plats:
        # (unset JAX_PLATFORMS is left alone: a TPU pod runs that way,
        # and perturbing its cpu client config for a backend it never
        # uses for collectives buys nothing)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # gloo TCP pairs match ops by FIFO order, not tags: with async
        # dispatch two in-flight XLA computations (a train step and a
        # host-collective psum, or a prefetch device_put's assert_equal
        # broadcast) interleave their sends nondeterministically PER
        # PROCESS, and a cross-process order mismatch aborts with
        # gloo::EnforceNotMet ("op.preamble.length <= op.nbytes").
        # Inline dispatch serializes each process's ops into strict
        # program order — identical on every process by SPMD. CPU
        # multiprocess is a test/dev topology; the throughput cost is
        # irrelevant there.
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    if coordinator_address == "auto":
        jax.distributed.initialize()
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
