"""2-D parallelism: data parallelism x Megatron tensor parallelism.

The standard LM scaling layout: batch shards ride the outer `workers`
axis (the PS data-parallel axis), each data shard's model is split over
the inner `model` axis (parallel/tp.py), which stays on the
highest-bandwidth ICI dimension where the per-block psums live. Params
are TP-sharded over `model` and replicated over `workers` — exactly the
PS engine's replication contract, so the PS semantics (mesh.py docstring)
extend unchanged to a tensor-sharded model.

Gradient math (the shard_map sum-over-shards AD rule, see tp.py): every
(dp, tp) device outputs its dp-row's loss L_i, so the traced global
function sums to n_tp * sum_i L_i. Differentiating loss/(n_tp * n_dp)
makes each device's gradient (1/n_dp) dL_i/dtheta; one psum over `workers`
for TP-sharded leaves (their copies are replicated across dp rows) and
one psum over BOTH axes for replicated leaves (their grads are also
partial across tp) recovers the exact gradient of the global batch-mean
loss. Verified one-step-exact against single-device training in
tests/test_dp_tp.py.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.metrics import next_token_nll
from .mesh import WORKER_AXIS, batch_sharding, place_on_mesh
from .tp import (
    TP_AXIS,
    _is_replicated,
    _tp_param_shapes,
    apply_transformer_tp,
    opt_state_specs,
    shard_params_tp,
    to_tp_layout,
    tp_param_specs,
)


def make_mesh_dp_tp(
    num_dp: int,
    num_tp: int,
    devices: Optional[Sequence[jax.Device]] = None,
    dp_axis: str = WORKER_AXIS,
    tp_axis: str = TP_AXIS,
) -> Mesh:
    """(num_dp x num_tp) mesh; tp inner so the per-block psums stay on
    neighboring devices. Same grid builder as dp x sp, different inner
    axis."""
    from .dp_sp import make_mesh_2d

    return make_mesh_2d(
        num_dp, num_tp, devices=devices, dp_axis=dp_axis, sp_axis=tp_axis
    )


def init_dp_tp_state(cfg, tx, key, mesh, tp_axis: str = TP_AXIS,
                     shard_vocab: bool = False):
    """Init (params_tp, opt_state): TP-sharded over `model`, replicated
    over `workers` (the specs name only the tp axis; dp replication is
    implicit)."""
    from ..models.transformer import init_transformer

    # shard_params_tp validates heads/mlp/vocab divisibility by the tp axis
    params = shard_params_tp(
        cfg, to_tp_layout(cfg, init_transformer(cfg, key)), mesh, tp_axis,
        shard_vocab=shard_vocab,
    )
    opt_state = tx.init(params)
    specs = tp_param_specs(cfg, tp_axis, shard_vocab)
    return params, place_on_mesh(
        opt_state, mesh, opt_state_specs(opt_state, params, specs)
    )


def shard_tokens_dp(tokens, mesh: Mesh, dp_axis: str = WORKER_AXIS):
    """[B_global, T] -> B sharded over dp, replicated over tp."""
    return jax.device_put(tokens, batch_sharding(mesh, dp_axis))


def make_dp_tp_train_step(
    cfg,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    dp_axis: str = WORKER_AXIS,
    tp_axis: str = TP_AXIS,
    donate: bool = True,
    shard_vocab: bool = False,
):
    """Jitted 2-D train step: (params_tp, opt_state, tokens) ->
    (params_tp, opt_state, loss). tokens sharded [B over dp]; loss is the
    global batch mean. shard_vocab runs the embedding/loss vocab-parallel
    over the tp axis (tp.vocab_parallel_nll) — the gradient scaling below
    is unchanged because the vocab-parallel loss is still identical across
    the tp shards of a dp row."""
    from .tp import vocab_parallel_nll

    specs_tree = tp_param_specs(cfg, tp_axis, shard_vocab)

    def shard_fn(params, opt_state, tokens):
        n_tp = lax.axis_size(tp_axis)
        n_dp = lax.axis_size(dp_axis)

        def loss_fn(p):
            logits = apply_transformer_tp(
                cfg, p, tokens, tp_axis, shard_vocab=shard_vocab
            )
            # scale per the module-docstring gradient math
            if shard_vocab:
                return vocab_parallel_nll(logits, tokens, tp_axis) / (n_tp * n_dp)
            return next_token_nll(logits, tokens) / (n_tp * n_dp)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(
            lambda g, s: lax.psum(g, (dp_axis, tp_axis))
            if _is_replicated(s)
            else lax.psum(g, dp_axis),
            grads,
            specs_tree,
            is_leaf=lambda x: isinstance(x, P),
        )
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # loss was pre-scaled by 1/(n_tp*n_dp); psum over dp recovers the
        # global batch mean (identical across tp already)
        return new_params, new_opt, lax.psum(loss, dp_axis) * n_tp

    shapes = _tp_param_shapes(cfg)
    opt_specs = opt_state_specs(jax.eval_shape(tx.init, shapes), shapes, specs_tree)
    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(specs_tree, opt_specs, P(dp_axis)),
        out_specs=(specs_tree, opt_specs, P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())
