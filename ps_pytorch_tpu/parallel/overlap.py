"""Schedule analysis for the pipelined bucket wire (PSConfig.overlap).

Two questions, answered from traced jaxprs (CPU-only, nothing executes):

1. **In what order do gradient leaves become ready?**
   ``grad_leaf_readiness`` walks the jaxpr of a gradient computation and
   returns, per output leaf, the position of the equation that produces
   it — the backward's production order. Backprop runs the forward graph
   in reverse, so the LAST-constructed parameters' gradients are
   produced FIRST; ``buckets.readiness_bucket_order`` encodes exactly
   that (reverse bucket enumeration over the canonical flat layout), and
   tests pin the two against each other on the real models. The engine
   uses the static order (no extra trace per step build); this module is
   the measurement that justifies it.

2. **How much freedom does the schedule have around each collective?**
   ``jaxpr_overlap_headroom`` finds the (deepest) jaxpr carrying the
   gradient-reduce collectives, builds the equation-level dataflow
   graph, and reports two numbers per reduce-kind collective:
   ``independent_frac`` — the equation weight that is neither ancestor
   nor descendant, i.e. schedulable CONCURRENTLY with the collective —
   and ``prefix_frac`` — the ancestor weight that MUST retire before
   the collective can launch. The serial wire concatenates every leaf
   before carving buckets, so each collective's ancestor cone swallows
   the whole backward (every prefix is the same large value and no
   gradient compute may run beside the wire); the pipelined wire's
   per-bucket assembly gives the first readiness-ordered bucket a
   prefix of just its own leaves' chain and leaves the other buckets'
   compute independent. These are properties of the PROGRAM (what a
   latency-hiding scheduler is allowed to do), not wall-clock
   measurements (what one backend's scheduler actually did);
   ``tools/trace_report.py overlap trace`` over a TPU profile measures
   the latter.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

# reduce-style collective primitive names (mirrors check/walker.py's
# REDUCE_KINDS without importing the static-analysis layer into the
# engine package)
_REDUCE_PRIMS = ("psum", "reduce_scatter", "psum_scatter", "all_to_all")

_CALL_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr", "body_jaxpr")


def _open(j):
    return getattr(j, "jaxpr", j)


def _sub_jaxprs(eqn):
    out = []
    for key in _CALL_KEYS:
        sub = eqn.params.get(key)
        if sub is not None:
            out.append(_open(sub))
    for br in eqn.params.get("branches", ()) or ():
        out.append(_open(br))
    return out


def _is_var(v) -> bool:
    return hasattr(v, "aval") and not hasattr(v, "val")


# ------------------------------------------------------------- readiness

def _linearize(jaxpr, prod: Dict[Any, int], counter: List[int]) -> None:
    """Depth-first global enumeration of equations; record each var's
    producing position. Call-like sub-jaxprs enumerate in place (their
    outputs map onto the eqn's outvars), which is exact enough for a
    production ORDER: jaxpr equations are already topologically
    sorted, so position is a valid readiness rank."""
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        for sub in subs:
            _linearize(sub, prod, counter)
            # map sub outputs onto the call eqn's outputs so a leaf
            # produced inside a pjit still gets its inner position
            for ov, iv in zip(eqn.outvars, sub.outvars):
                if _is_var(ov) and _is_var(iv) and iv in prod:
                    prod[ov] = prod[iv]
        counter[0] += 1
        for v in eqn.outvars:
            if _is_var(v) and v not in prod:
                prod[v] = counter[0]


def grad_leaf_readiness(fn, *example_args) -> Tuple[int, ...]:
    """Production rank of each flat output leaf of ``fn`` (typically a
    ``jax.grad`` of the loss): smaller = that leaf's value is produced
    by an earlier equation of the traced jaxpr. ``example_args`` may be
    ShapeDtypeStructs — nothing executes."""
    import jax

    closed = jax.make_jaxpr(fn)(*example_args)
    jaxpr = _open(closed)
    prod: Dict[Any, int] = {}
    _linearize(jaxpr, prod, [0])
    ranks = []
    for v in jaxpr.outvars:
        ranks.append(prod.get(v, 0) if _is_var(v) else 0)
    return tuple(ranks)


# ------------------------------------------------------- overlap headroom

def _total_eqns(jaxpr) -> int:
    n = 0
    for eqn in jaxpr.eqns:
        n += 1
        for sub in _sub_jaxprs(eqn):
            n += _total_eqns(sub)
    return n


def _find_collective_jaxpr(jaxpr):
    """The deepest jaxpr that itself contains reduce-kind collective
    eqns — for the PS engine that is the shard_map body, where the
    backward, the per-bucket reduces, and the update are sibling
    equations of one graph."""
    for eqn in jaxpr.eqns:
        for sub in _sub_jaxprs(eqn):
            found = _find_collective_jaxpr(sub)
            if found is not None:
                return found
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _REDUCE_PRIMS:
            return jaxpr
    return None


def jaxpr_overlap_headroom(fn, *example_args) -> dict:
    """Schedule-freedom report for the traced step ``fn(*example_args)``.

    For every reduce-kind collective equation in the (deepest) jaxpr
    that carries them: ``independent_frac`` = weight of equations that
    are neither dataflow ancestors nor descendants of it, over the total
    equation weight (sub-jaxpr bodies weigh as their internal equation
    count). Returns ``{n_collectives, total_weight, per_collective:
    [{name, independent_frac, ...}], overlap_headroom}`` where
    ``overlap_headroom`` is the mean independent fraction — 0 means
    every collective is a full barrier (nothing may run beside it), the
    serial grad->psum->update shape; the pipelined wire's per-bucket
    chains push it up."""
    import jax

    return overlap_headroom_from(jax.make_jaxpr(fn)(*example_args))


def overlap_headroom_from(closed) -> dict:
    """``jaxpr_overlap_headroom`` over an ALREADY-TRACED ClosedJaxpr —
    the tune/ cost model analyzes the same trace pscheck's rules ran on
    instead of paying a second trace per candidate."""
    body = _find_collective_jaxpr(_open(closed))
    if body is None:
        return {"n_collectives": 0, "total_weight": 0,
                "per_collective": [], "overlap_headroom": None}
    eqns = list(body.eqns)
    weights = [1 + sum(_total_eqns(s) for s in _sub_jaxprs(e)) for e in eqns]
    total = sum(weights)
    # producer map: var -> eqn index; consumer adjacency
    prod: Dict[Any, int] = {}
    for i, e in enumerate(eqns):
        for v in e.outvars:
            if _is_var(v):
                prod[v] = i
    parents: List[List[int]] = []
    for e in eqns:
        ps = sorted({
            prod[v] for v in e.invars if _is_var(v) and v in prod
        })
        parents.append(ps)
    children: List[List[int]] = [[] for _ in eqns]
    for i, ps in enumerate(parents):
        for p in ps:
            children[p].append(i)

    def cone(start: int, adj: List[List[int]]) -> set:
        seen = {start}
        stack = [start]
        while stack:
            x = stack.pop()
            for y in adj[x]:
                if y not in seen:
                    seen.add(y)
                    stack.append(y)
        return seen

    coll_idx = [
        i for i, e in enumerate(eqns) if e.primitive.name in _REDUCE_PRIMS
    ]
    per = []
    for i in coll_idx:
        e = eqns[i]
        anc = cone(i, parents)
        desc = cone(i, children)
        dependent = anc | desc  # includes the collective itself
        independent = total - sum(weights[j] for j in dependent)
        prefix = sum(weights[j] for j in anc - {i})
        per.append({
            "eqn": i,
            "prim": e.primitive.name,
            # what MAY run while this collective is in flight
            "independent_weight": independent,
            "independent_frac": round(independent / total, 4) if total else 0,
            # what MUST retire before this collective can start — the
            # pipelining number: the serial schedule's global concat
            # forces every bucket to wait for the whole backward, so
            # every prefix is the same large value; the pipelined wire's
            # first (readiness-ordered) bucket needs only its own
            # leaves' chain
            "prefix_frac": round(prefix / total, 4) if total else 0,
        })
    frac = (
        round(sum(p["independent_frac"] for p in per) / len(per), 4)
        if per else None
    )
    prefixes = sorted(p["prefix_frac"] for p in per)
    return {
        "n_collectives": len(per),
        "total_weight": total,
        "per_collective": per,
        "overlap_headroom": frac,
        # earliest/mean dispatch depth: fraction of the program that
        # gates the first (resp. average) collective's launch
        "first_dispatch_prefix": prefixes[0] if prefixes else None,
        "mean_dispatch_prefix": (
            round(sum(prefixes) / len(prefixes), 4) if prefixes else None
        ),
    }
