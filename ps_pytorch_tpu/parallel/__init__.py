"""Parallelism layer: device mesh (replaces the MPI star topology),
PS data-parallel engine (replaces master/worker runtimes), aggregation
collectives (replace the Irecv/waitany/Blosc gather path), and ring
attention for sequence/context parallelism (long-context support beyond
the reference's scope)."""

from .collectives import (
    aggregate_gradients,
    aggregation_mask,
    psum_mean,
    quantized_psum,
)
from .mesh import (
    DCN_AXIS,
    WORKER_AXIS,
    batch_sharding,
    initialize_multihost,
    make_hybrid_mesh,
    make_mesh,
    place_on_mesh,
    replicated_sharding,
)
from .ring_attention import (
    SEQ_AXIS,
    full_attention,
    make_ring_attention,
    make_seq_mesh,
    ring_attention,
    ring_flash_attention,
    shard_sequence,
)
from .dp_tp import (
    init_dp_tp_state,
    make_dp_tp_train_step,
    make_mesh_dp_tp,
    shard_tokens_dp,
)
from .moe import (
    EP_AXIS,
    MoEConfig,
    apply_moe_transformer,
    init_moe_state,
    make_ep_mesh,
    make_moe_train_step,
    shard_moe_batch,
    shard_params_moe,
)
from .pp import (
    PP_AXIS,
    from_pp_layout,
    init_pp_state,
    make_pp_mesh,
    make_pp_train_step,
    shard_params_pp,
    to_pp_layout,
)
from .tp import (
    TP_AXIS,
    apply_transformer_tp,
    from_tp_layout,
    init_tp_state,
    make_tp_forward,
    make_tp_mesh,
    make_tp_train_step,
    shard_params_tp,
    to_tp_layout,
    tp_param_specs,
    vocab_parallel_nll,
)
from .ulysses import (
    make_ulysses_attention,
    ulysses_attention,
)
from .buckets import (
    FlatVector,
    assemble_bucket,
    bucket_leaf_segments,
    leaves_from_buckets,
    readiness_bucket_order,
    tree_view,
)
from .overlap import grad_leaf_readiness, jaxpr_overlap_headroom
from .ps import (
    PSConfig,
    PSTrainState,
    batch_sharding,
    init_ps_state,
    make_ps_eval_step,
    make_ps_train_step,
    shard_batch,
    shard_state,
    state_plan,
    state_specs,
)
