"""The parameter-server data-parallel engine — shard_map over a device mesh.

This is the TPU-native re-design of the reference's L4 scheduler layer
(SURVEY.md sections 1-3): `SyncReplicasMaster_NN.start()`'s bcast/gather/
aggregate/step loop (sync_replicas_master_nn.py:133-197) and
`DistributedWorker.train()`'s fetch/forward/backward/send loop
(distributed_worker.py:104-180) collapse into ONE jitted SPMD step:

  reference protocol                      this engine
  ------------------------------------    -----------------------------------
  master bcasts step (tag 10)             XLA synchronous dispatch (implicit)
  master bcasts weights per layer         params replicated on the mesh
  worker forward/backward                 per-shard value_and_grad
  worker per-layer Isend (tag 88+l)       lax.psum / psum_scatter over ICI
  master waitany + partial aggregate      aggregation_mask + psum (collectives)
  master in-tree SGD step / num_agg       optax update, replicated or ZeRO-1
  worker BN stats stay local              bn_mode = local | pmean | synced
  Blosc codec                             int8 quantized collective (Pallas)

Optimizer placement ("where does the PS live"):
- "replicated": every chip applies the identical update — mathematically the
  reference's PS update broadcast to everyone, with zero extra comm.
- "sharded": ZeRO-1-style — gradients reduce_scatter to 1/N shards, each chip
  updates its shard of optimizer state, params all_gather back. This IS the
  parameter server, sharded across the mesh instead of parked on rank 0
  (and it cuts optimizer memory + aggregate bandwidth vs. the star topology).

BatchNorm modes (reference keeps per-worker BN stats and never syncs them —
distributed_worker.py:239-252):
- "local":  strict parity — stats stored per worker (stacked leading axis).
- "pmean":  stats averaged across workers each step (sane default).
- "synced": cross-replica BN (build the model with bn_axis_name=axis).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple, Union

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import apply_model
from ..ops.metrics import accuracy, cross_entropy_loss
from ..ops.quantize import (
    _INT8_PEAK,
    accum_dtype,
    dequantize_int8,
    precision_peaks,
    quantize_int8,
    quantize_lattice,
)
from ..resilience.guard import (
    init_guard_state,
    tree_all_finite,
    update_guard_state,
)
from .buckets import (
    BucketPlan,
    FlatVector,
    assemble_bucket,
    bucket_leaf_segments,
    concat_buckets,
    flat_to_tree,
    leaves_from_buckets,
    pad_flat,
    plan_buckets,
    readiness_bucket_order,
    to_flat_vector,
    tree_layout,
    tree_to_flat,
    tree_view,
)
from .collectives import aggregate_gradients, aggregation_mask
from .mesh import WORKER_AXIS

tree_map = jax.tree_util.tree_map


@dataclasses.dataclass(frozen=True)
class PSConfig:
    """Knobs mirroring the reference CLI (distributed_nn.py:24-68) plus the
    TPU-native extensions. `num_aggregate` <-> --num-aggregate; `compress`
    <-> --compress-grad; `mask_mode='random_k'` emulates aggregating the
    first K gradients to *arrive* (arrival order is nondeterministic)."""

    num_workers: int
    # a single mesh axis, or a TUPLE of axes for hierarchical (multi-host)
    # data parallelism — e.g. (DCN_AXIS, WORKER_AXIS) over make_hybrid_mesh,
    # where num_workers is the TOTAL chip count across hosts. Every
    # collective in the engine accepts the tuple form.
    axis_name: Union[str, Tuple[str, ...]] = WORKER_AXIS
    num_aggregate: Optional[int] = None
    mask_mode: str = "random_k"
    # adaptive partial aggregation (resilience/elastic.py): when BOTH
    # bounds are set, the train step takes a traced int32 ``agg_count``
    # argument and the host picks next window's count from observed
    # step-time statistics inside [min, max] — the reference's static
    # backup-worker knob generalized to ACE-Sync-style adaptive sync.
    # ``num_aggregate`` then only seeds the initial count (default: max).
    # The masking/denominator math is identical to the static path; a
    # full-count window multiplies by exactly 1.0 and divides by exactly
    # num_workers, so it is bit-exact against num_aggregate=None on
    # power-of-two meshes.
    num_aggregate_min: Optional[int] = None
    num_aggregate_max: Optional[int] = None
    # None | "int8" (int32-psum of int8 payloads: exact sum, compute-side
    # compression) | "int8_2round" (all_to_all + requantize + all_gather:
    # the wire itself carries int8 — a true ~4x bandwidth reduction, one
    # extra bounded quantization on the partial sums; collectives.
    # quantized_allreduce_2round)
    compress: Optional[str] = None
    quant_block_size: int = 0
    quant_rounding: str = "nearest"  # "nearest" | "stochastic" (unbiased)
    # WHAT the aggregation sums (--wire-domain): "dequant" (default) is
    # the committed-contract wire — each hop widens the quantized payload
    # to f32 to add and requantizes to ship. "homomorphic" (THC/DynamiQ,
    # PAPERS.md) sums in the COMPRESSED domain: workers agree on shared
    # per-bucket scales via the one tiny max-abs reduction the quantizer
    # already pays, payloads accumulate exactly in the minimal integer
    # dtype (ops/quantize.accum_dtype pins the no-overflow bound), every
    # wire hop carries int8/int16 — the "int8" psum halves (int16 vs
    # int32), the 2-round gather hop drops its round-2 requantization
    # and scale rows, and the hierarchical DCN x ICI path forwards
    # lattice payloads across every hop (the f32 ICI reassembly becomes
    # int8: 4x) — and dequantization defers to ONE scale-multiply per
    # bucket at the consumer (the ZeRO-1 placement dequantizes only its
    # own shard region). Needs a compress mode and nearest rounding;
    # shared scales are COARSER than per-worker scales, so parity vs the
    # dequant wire is an envelope (EF absorbs the difference), while the
    # integer accumulation itself is bit-exact.
    wire_domain: str = "dequant"
    # adaptive per-bucket precision (--precision-adapt): the train step
    # takes a traced int32 vector of PER-BUCKET precision tags (one per
    # state_plan bucket: 0=skip / 1=4-bit / 2=int8 / 3=hi) and quantizes
    # each bucket onto the lattice its tag names — same block-scale
    # geometry, shared scales, EF absorbing the extra error exactly as
    # for static int8 — with NO retrace on tag change (the tag only
    # selects the traced clipping peak). The host-side
    # resilience/precision.PrecisionController picks tags per window
    # from on-device per-bucket gradient-norm telemetry under a
    # --wire-budget-bytes target. Value-domain adaptation: the physical
    # trace bytes never change; the tags reshape what the fixed wire
    # CARRIES (a 4-bit bucket's payload occupies 16 of 256 int8 code
    # points), so the budget currency is EFFECTIVE bytes. Needs a
    # compress mode, a bucketed wire, and nearest rounding.
    precision_adapt: bool = False
    # gradient wire granularity (parallel/buckets.py): None = legacy
    # message-per-leaf collectives (the reference's tag-88+l shape), 0 =
    # ONE fused flat f32 buffer, N = ~N-byte contiguous buckets with
    # boundaries aligned to the int8 quantization block — O(n_buckets)
    # collectives per step instead of O(n_leaves). The ZeRO-1 sharded
    # placement's wire is flat by construction; there None and 0 are the
    # same fused buffer and N>0 carves the scatter into buckets. With
    # bucketing on, the non-finite guard reduces ONE fused isfinite over
    # the flat buffer instead of one per leaf.
    bucket_bytes: Optional[int] = None
    # where the master params and optimizer moments LIVE (buckets.
    # FlatVector): "flat" (default) keeps them as padded flat f32
    # vectors in the same BucketPlan geometry the wire uses — the
    # reduced flat gradient feeds ONE fused vector update, the tree
    # view the forward pass needs is materialized once per step
    # (slices XLA fuses away), the non-finite guard's rollback selects
    # a handful of whole vectors instead of every leaf, and the ZeRO-1
    # path drops its per-step tree_to_flat(params) because params
    # already live flat in shard geometry. "tree" is the legacy
    # per-leaf layout. Compute-side only: the wire (collective counts,
    # bytes, quantization noise) is byte-identical either way, and
    # checkpoints are tree-shaped at the save/restore boundary, so
    # they stay bit-portable across both settings.
    state_layout: str = "flat"
    # WHEN the wire moves (--overlap on|off): "serial" (default) reduces
    # after the whole backward — the committed-contract baseline schedule.
    # "pipelined" launches each bucket's collective as soon as its
    # leaves' gradients exist: buckets are assembled from their own leaf
    # fragments (no global-concat false dependency), streamed in
    # readiness order (reverse-topological bucket enumeration: the last
    # bucket's leaves backprop first), reduced by per-bucket collective
    # eqns, and — under state_layout="flat" — consumed by PER-BUCKET
    # optimizer updates as reductions land, so XLA's latency-hiding
    # scheduler can interleave the wire with the remaining backward AND
    # the update. Same buckets, same bytes, bit-identical values (PRNG
    # keys fold bucket START OFFSETS, so the reordered enumeration draws
    # identical noise; PSC109 pins byte equality against the serial
    # twin). The per-bucket update requires elementwise optimizer
    # transforms with per-parameter state (the repo's sgd/adam families;
    # a global-norm-coupled transform would need the whole vector).
    overlap: str = "serial"
    # error feedback (EF-SGD): each worker keeps the residual its
    # compression dropped and adds it back next step, so quantization
    # error accumulates into the update instead of being lost — the
    # standard convergence fix for aggressive compression. Requires a
    # compress mode. Works with both placements: replicated keeps
    # per-leaf residuals; the ZeRO-1 sharded placement keeps the residual
    # on the flat padded gradient vector (same wire transform, same
    # accounting). With quant_rounding="stochastic" + "int8_2round" the
    # residual is approximate (padding changes the noise draw); pair EF
    # with "nearest" for the exact on-wire residual.
    error_feedback: bool = False
    opt_placement: str = "replicated"  # "replicated" | "sharded"
    bn_mode: str = "pmean"  # "local" | "pmean" | "synced"
    # microbatches per step, accumulated in an in-step lax.scan: scales the
    # effective per-worker batch beyond HBM without touching the protocol
    # (the reference can only shrink the batch; SURVEY section 6 shows its
    # b=4096 runs were its scaling ceiling)
    grad_accum_steps: int = 1
    # >1 = hierarchical data parallelism over a (hosts x chips) hybrid mesh
    # (mesh.make_hybrid_mesh): axis_name is promoted to the axis tuple so
    # aggregation reduces over ICI within a host before crossing DCN once
    dcn_hosts: int = 1
    # non-finite gradient guard (resilience/guard.py): one int32 pmin
    # agrees mesh-wide that every worker's gradients are finite; a bad
    # step applies the identity update instead of the optimizer, counted
    # in GuardState (checkpointed with the state). Default ON — the int8
    # wire formats make overflow/NaN a when, not an if.
    nonfinite_guard: bool = True
    # dynamic loss scaling (grow-on-success / back-off-on-overflow) for
    # the compressed wire formats; requires the guard (the skip IS the
    # overflow handler) and a compress mode (uncompressed f32 psum has
    # f32 headroom and doesn't need it)
    dynamic_loss_scale: bool = False
    loss_scale_init: float = 2.0 ** 15
    loss_scale_growth_interval: int = 2000

    def __post_init__(self):
        if self.dcn_hosts > 1:
            if self.num_workers % self.dcn_hosts:
                raise ValueError(
                    f"num_workers {self.num_workers} not divisible by "
                    f"dcn_hosts {self.dcn_hosts}"
                )
            if isinstance(self.axis_name, str):
                from .mesh import DCN_AXIS

                # frozen dataclass: promote the axis via object.__setattr__
                object.__setattr__(
                    self, "axis_name", (DCN_AXIS, self.axis_name)
                )
        if self.grad_accum_steps < 1:
            raise ValueError(f"bad grad_accum_steps {self.grad_accum_steps}")
        if self.opt_placement not in ("replicated", "sharded"):
            raise ValueError(f"bad opt_placement {self.opt_placement!r}")
        if self.bn_mode not in ("local", "pmean", "synced"):
            raise ValueError(f"bad bn_mode {self.bn_mode!r}")
        if self.compress not in (None, "none", "int8", "int8_2round"):
            raise ValueError(f"bad compress {self.compress!r}")
        if self.quant_rounding not in ("nearest", "stochastic"):
            raise ValueError(f"bad quant_rounding {self.quant_rounding!r}")
        if self.state_layout not in ("tree", "flat"):
            raise ValueError(f"bad state_layout {self.state_layout!r}")
        if self.overlap not in ("serial", "pipelined"):
            raise ValueError(
                f"bad overlap {self.overlap!r} (serial | pipelined)"
            )
        if (
            self.overlap == "pipelined"
            and self.bucket_bytes is None
            and self.opt_placement != "sharded"
        ):
            # the pipelined schedule is a property of the BUCKETED wire;
            # on the replicated per-leaf wire it would silently un-fuse
            # the whole-tree psum back into one eqn per leaf (the exact
            # shape bucketing exists to avoid). The ZeRO-1 wire is flat
            # by construction (None == one fused bucket there), so it
            # pipelines fine without the knob.
            raise ValueError(
                "overlap='pipelined' needs a bucketed wire: set "
                "bucket_bytes (0 = one fused buffer, N = ~N-byte "
                "buckets) — the replicated per-leaf wire has no buckets "
                "to stream"
            )
        if self.bucket_bytes is not None and self.bucket_bytes < 0:
            raise ValueError(
                f"bad bucket_bytes {self.bucket_bytes} (None = per-leaf, "
                f"0 = one fused buffer, N>0 = ~N-byte buckets)"
            )
        if self.wire_domain not in ("dequant", "homomorphic"):
            raise ValueError(
                f"bad wire_domain {self.wire_domain!r} "
                f"(dequant | homomorphic)"
            )
        if self.wire_domain == "homomorphic":
            if self.compress in (None, "none"):
                raise ValueError(
                    "wire_domain='homomorphic' needs a compress mode "
                    "(--compress-grad compress|2round): an uncompressed "
                    "f32 psum has nothing to homomorphically sum"
                )
            if self.quant_rounding == "stochastic":
                raise ValueError(
                    "wire_domain='homomorphic' needs "
                    "quant_rounding='nearest': shared scales put every "
                    "worker on ONE lattice, and the per-worker-seeded "
                    "stochastic draws (keys fold the worker index by "
                    "design) have no coherent meaning under the "
                    "compressed-domain rescale — there is no "
                    "identically-seeded mode to opt into"
                )
            # the exact-accumulation bound: raises past the int32
            # capacity (ops/quantize.ACCUM_CAPACITY) so overflow is a
            # config error, never a silent wrap
            accum_dtype(self.num_workers)
        if self.error_feedback and self.compress in (None, "none"):
            raise ValueError("error_feedback needs a compress mode")
        if self.precision_adapt:
            if self.compress in (None, "none"):
                raise ValueError(
                    "precision_adapt needs a compress mode: an "
                    "uncompressed f32 wire has no lattice to retune"
                )
            if self.bucket_bytes is None:
                raise ValueError(
                    "precision_adapt needs a bucketed wire: set "
                    "bucket_bytes (0 = one fused buffer, N = ~N-byte "
                    "buckets) — the tags are a per-BUCKET property"
                )
            if self.quant_rounding != "nearest":
                raise ValueError(
                    "precision_adapt needs quant_rounding='nearest': the "
                    "per-worker stochastic draws are calibrated to the "
                    "int8 lattice pitch, not a per-bucket traced one"
                )
        if self.dynamic_loss_scale:
            if self.compress in (None, "none"):
                raise ValueError("dynamic_loss_scale needs a compress mode")
            if not self.nonfinite_guard:
                raise ValueError(
                    "dynamic_loss_scale needs nonfinite_guard (the skip "
                    "step is the overflow back-off trigger)"
                )
        if self.loss_scale_growth_interval < 1:
            raise ValueError(
                f"bad loss_scale_growth_interval "
                f"{self.loss_scale_growth_interval}"
            )
        if (self.num_aggregate_min is None) != (self.num_aggregate_max is None):
            raise ValueError(
                "adaptive aggregation needs BOTH num_aggregate_min and "
                "num_aggregate_max (set neither for the static mask)"
            )
        if self.num_aggregate_min is not None:
            if not (1 <= self.num_aggregate_min <= self.num_aggregate_max
                    <= self.num_workers):
                raise ValueError(
                    f"bad adaptive bounds [{self.num_aggregate_min}, "
                    f"{self.num_aggregate_max}]: need 1 <= min <= max <= "
                    f"num_workers ({self.num_workers})"
                )
            if self.num_aggregate is not None and not (
                self.num_aggregate_min <= self.num_aggregate
                <= self.num_aggregate_max
            ):
                raise ValueError(
                    f"num_aggregate {self.num_aggregate} (the initial "
                    f"adaptive count) is outside the declared bounds "
                    f"[{self.num_aggregate_min}, {self.num_aggregate_max}]"
                )
        if self.loss_scale_init <= 0.0:
            # scale 0 zeroes the loss and the unscale divides by it: every
            # step overflows and the guard aborts blaming the DATA
            raise ValueError(
                f"bad loss_scale_init {self.loss_scale_init} (must be > 0)"
            )
        if (
            self.compress == "int8_2round"
            and self.opt_placement == "sharded"
            and (
                self.dcn_hosts > 1
                or isinstance(self.axis_name, (tuple, list))
            )
        ):
            # design note, not a TODO: the sharded placement's gradient
            # wire is a single reduce_scatter over the full axis tuple;
            # an int8 all_to_all over a product of DCN x ICI axes has no
            # hierarchical routing to exploit (each chip's region still
            # crosses DCN once either way). Use compress="int8" (int32
            # psum_scatter) for sharded+DCN.
            raise ValueError(
                "int8_2round x sharded x dcn_hosts>1 is unsupported: the "
                "sharded wire is one reduce_scatter over the whole mesh, "
                "so there is no hierarchical structure for the 2-round "
                "scheme to exploit — use compress='int8' there"
            )

    @property
    def effective_aggregate(self) -> int:
        if self.num_aggregate is None or self.num_aggregate >= self.num_workers:
            return self.num_workers
        return self.num_aggregate

    @property
    def adaptive_aggregate(self) -> bool:
        """True when the train step takes a traced per-window aggregation
        count (``step(state, batch, key, agg_count)``) instead of baking
        ``num_aggregate`` in statically."""
        return self.num_aggregate_min is not None

    @property
    def initial_aggregate(self) -> int:
        """The adaptive controller's starting count: ``num_aggregate``
        when given (validated inside the bounds), else the max bound —
        start optimistic, back off when stragglers appear."""
        if not self.adaptive_aggregate:
            return self.effective_aggregate
        if self.num_aggregate is not None:
            return self.num_aggregate
        return self.num_aggregate_max


@flax.struct.dataclass
class PSTrainState:
    step: jax.Array
    # the master parameters: the model pytree (state_layout="tree") or a
    # buckets.FlatVector — ONE padded flat f32 vector in the wire's
    # BucketPlan geometry (state_layout="flat", the default). Either way
    # checkpoints store the TREE shape (FlatVector converts at the
    # serialization edge), so they are bit-portable across layouts.
    params: Any
    # optax state; under "flat" + replicated placement the moments are
    # FlatVectors too (same geometry, same tree-shaped checkpoint form)
    opt_state: Any
    batch_stats: Any
    # error-feedback residuals, worker-stacked [n, ...] per param leaf
    # (cfg.error_feedback); None otherwise — checkpointed with the state
    # so resume keeps the accumulated compression error
    comm_state: Any = None
    # non-finite guard counters + live loss scale (resilience.GuardState,
    # cfg.nonfinite_guard); None when the guard is off. Checkpointed, but
    # resettable: checkpoint.load_checkpoint re-zeros it when restoring a
    # pre-guard checkpoint (the counters are observability, not math)
    guard_state: Any = None


def _flat_padded_size(params) -> int:
    return sum(int(jnp.size(p)) for p in jax.tree_util.tree_leaves(params))


def wire_align(cfg: PSConfig) -> int:
    """Bucket-boundary alignment (f32 elements) this config's wire uses:
    the int8 quantization block for the quantized schemes (1 for
    per-tensor scales / no compression), × num_workers on the ZeRO-1
    scatter so each worker's slice of each bucket owns whole scale rows.
    The PSC106 FusionSpec derives its budget from this same function —
    keep them one expression."""
    block = (
        cfg.quant_block_size
        if cfg.compress in ("int8", "int8_2round") and cfg.quant_block_size
        else 1
    )
    return (
        cfg.num_workers * block if cfg.opt_placement == "sharded" else block
    )


def _sharded_plan(cfg: PSConfig, total: int) -> BucketPlan:
    """Bucket geometry for the ZeRO-1 flat wire (buckets.plan_buckets).

    Every bucket — and the padded total — is a multiple of
    ``num_workers * quant_block`` (wire_align), so each worker's
    scattered slice of each bucket owns whole quantization-scale rows.
    The sharded wire has always been one flat buffer, so ``bucket_bytes``
    None and 0 are the same fused plan; N>0 carves the scatter into
    ~N-byte buckets. Must be identical at init (optimizer-state buffers,
    EF residual rows) and in the update step."""
    return plan_buckets(total, cfg.bucket_bytes or 0, align=wire_align(cfg))


def _zero1_shard_size(total: int, cfg: PSConfig) -> int:
    """Per-worker flat shard length for the ZeRO-1 placement: this
    worker's 1/N of every bucket of the padded flat gradient."""
    return _sharded_plan(cfg, total).padded_total // cfg.num_workers


def state_plan(cfg: PSConfig, total: int) -> BucketPlan:
    """The flat-state geometry (state_layout="flat"): the SAME BucketPlan
    the config's gradient wire uses, so the reduced flat gradient drops
    straight into the vector update with no re-layout. Replicated:
    ``bucket_bytes`` carving aligned to ``wire_align`` (None = one fused
    buffer — only the padding matters for state). Sharded: the ZeRO-1
    scatter plan (alignment × num_workers), so params already live in
    shard geometry."""
    if cfg.opt_placement == "sharded":
        return _sharded_plan(cfg, total)
    return plan_buckets(total, cfg.bucket_bytes or 0, align=wire_align(cfg))


def precision_hi_peak(cfg: PSConfig) -> int:
    """The static clipping peak a PREC_HI (f32-passthrough-fidelity)
    bucket quantizes to under this config's wire — the widest lattice
    the scheme's narrowest integer hop can carry without overflow:

    - ``int8_2round``: the all_to_all payload is int8 by construction
      (flat round 2 / hier DCN hop / sharded a2a), so HI caps at 127 —
      on the 2-round wire the HI tag just means "never downgrade".
    - homomorphic ``int8``: payloads accumulate exactly in
      ``accum_dtype(num_workers)``, so the peak is that dtype's max
      over the worker count (4095 at 8 workers on int16) — an
      adaptive-precision dividend of PR 14's capacity analysis.
    - dequant ``int8``: the psum rides int32, bounded only by
      2^31-1 over the worker count; capped at 32767 so a HI payload
      never needs more than an int16 carrier.
    """
    n = cfg.num_workers
    if cfg.compress == "int8_2round":
        return _INT8_PEAK
    if cfg.wire_domain == "homomorphic":
        return min(int(jnp.iinfo(accum_dtype(n)).max) // n, 32767)
    return min((2 ** 31 - 1) // n, 32767)


def init_ps_state(
    model,
    tx: optax.GradientTransformation,
    cfg: PSConfig,
    rng: jax.Array,
    input_shape,
) -> PSTrainState:
    """Build the (host-side) initial state with the stacking layout the
    engine expects for the configured placement/bn modes."""
    from ..models import init_model

    params_tree, batch_stats = init_model(model, rng, input_shape)
    total = _flat_padded_size(params_tree)
    if cfg.state_layout == "flat":
        # master params become ONE padded flat f32 vector in the wire's
        # own BucketPlan geometry; the tree view is materialized per
        # step inside the jitted program (and at the checkpoint edge)
        params = to_flat_vector(params_tree, state_plan(cfg, total))
    else:
        params = params_tree
    if cfg.opt_placement == "sharded":
        shard = _zero1_shard_size(total, cfg)
        flat_zeros = jnp.zeros((shard,), jnp.float32)
        one_state = tx.init(flat_zeros)
        # identical zero-init on every worker; stacked leading axis = worker
        opt_state = tree_map(
            lambda x: jnp.broadcast_to(x, (cfg.num_workers,) + jnp.shape(x)), one_state
        )
    else:
        # under "flat", params is a FlatVector: moments initialize as
        # whole padded vectors carrying the same static layout (the
        # checkpoint edge converts them tree-shaped like the params)
        opt_state = tx.init(params)
    if cfg.bn_mode == "local" and batch_stats:
        batch_stats = tree_map(
            lambda x: jnp.broadcast_to(x, (cfg.num_workers,) + x.shape), batch_stats
        )
    comm_state = None
    if cfg.error_feedback:
        if cfg.opt_placement == "sharded":
            # the sharded wire transforms the FLAT padded gradient vector,
            # so its residual lives there too: one [L] row per worker
            flat_len = _zero1_shard_size(total, cfg) * cfg.num_workers
            comm_state = jnp.zeros(
                (cfg.num_workers, flat_len), jnp.float32
            )
        else:
            # zero residual per worker per param leaf, worker-stacked —
            # per-leaf in BOTH state layouts, so EF checkpoints stay
            # portable across bucket/layout settings
            comm_state = tree_map(
                lambda p: jnp.zeros(
                    (cfg.num_workers,) + jnp.shape(p), jnp.float32
                ),
                params_tree,
            )
    guard_state = None
    if cfg.nonfinite_guard:
        guard_state = init_guard_state(
            cfg.loss_scale_init if cfg.dynamic_loss_scale else 1.0,
            dynamic=cfg.dynamic_loss_scale,
        )
    return PSTrainState(
        step=jnp.zeros([], jnp.int32),
        params=params,
        opt_state=opt_state,
        batch_stats=batch_stats,
        comm_state=comm_state,
        guard_state=guard_state,
    )


def state_specs(cfg: PSConfig):
    """PartitionSpecs (pytree prefixes) for PSTrainState components."""
    opt_spec = P(cfg.axis_name) if cfg.opt_placement == "sharded" else P()
    bs_spec = P(cfg.axis_name) if cfg.bn_mode == "local" else P()
    return PSTrainState(
        step=P(),
        params=P(),
        opt_state=opt_spec,
        batch_stats=bs_spec,
        comm_state=P(cfg.axis_name),  # worker-stacked residuals (if any)
        guard_state=P(),  # scalar counters, replicated
    )


def shard_state(state: PSTrainState, mesh: Mesh, cfg: PSConfig) -> PSTrainState:
    """Place a host-built state onto the mesh with the right shardings."""
    specs = state_specs(cfg)

    def put(tree, spec):
        return tree_map(lambda x: jax.device_put(x, NamedSharding(mesh, spec)), tree)

    return PSTrainState(
        step=put(state.step, P()),
        params=put(state.params, specs.params),
        opt_state=put(state.opt_state, specs.opt_state),
        batch_stats=put(state.batch_stats, specs.batch_stats),
        comm_state=put(state.comm_state, specs.comm_state),
        guard_state=put(state.guard_state, specs.guard_state),
    )


def batch_sharding(mesh: Mesh, cfg: PSConfig) -> NamedSharding:
    """The per-worker batch sharding (leading dim split over the data
    axis) — pass to ``data.prefetch_to_device`` so prefetched batches
    land on the mesh already split instead of being re-laid-out inside
    the step."""
    return NamedSharding(mesh, P(cfg.axis_name))


def shard_batch(batch, mesh: Mesh, cfg: PSConfig):
    """Split the global batch across workers (leading dim)."""
    return jax.device_put(batch, batch_sharding(mesh, cfg))


def _worker_region(flat, plan: BucketPlan, w, n: int):
    """Worker ``w``'s region of a bucketed flat buffer: its 1/n slice of
    every bucket, concatenated in bucket order (one slice for the fused
    single-bucket plan)."""
    parts = []
    for start, size in zip(plan.starts, plan.sizes):
        s = size // n
        parts.append(lax.dynamic_slice(flat, (start + w * s,), (s,)))
    return concat_buckets(parts) if len(parts) > 1 else parts[0]


# ------------------------------------------------ per-bucket vector update
# (overlap="pipelined": the optimizer starts as each bucket's reduction
# lands, instead of waiting for the whole aggregate to concatenate)

def _is_flatvec(x) -> bool:
    return isinstance(x, FlatVector)


def _strip_flat(tree):
    """Replace every FlatVector node with its bare padded buffer, so the
    per-bucket slices feed tree- and flat-form optimizer transforms
    alike (a tree_map over mixed FlatVector/bare operands would reject
    the structure)."""
    return jax.tree_util.tree_map(
        lambda x: x.flat if _is_flatvec(x) else x, tree, is_leaf=_is_flatvec
    )


def _rewrap_flat(template, bare):
    """Inverse of ``_strip_flat``: restore the template's FlatVector
    wrappers (their static layout/plan metadata) around the stitched
    bare buffers, so the step's output state structure is unchanged."""
    return jax.tree_util.tree_map(
        lambda t, v: t.replace(flat=v) if _is_flatvec(t) else v,
        template, bare, is_leaf=_is_flatvec,
    )


def _bucket_opt_views(opt_bare, seg_len: int):
    """(leaves, treedef, is_seg): flatten a bare optimizer state and mark
    which leaves are per-parameter vectors of ``seg_len`` elements (the
    moment buffers — sliced per bucket) vs scalars like the step count
    (replicated into every bucket's update unchanged)."""
    leaves, treedef = jax.tree_util.tree_flatten(opt_bare)
    is_seg = [
        getattr(l, "ndim", None) == 1 and int(l.shape[0]) == seg_len
        for l in leaves
    ]
    return leaves, treedef, is_seg


def _stitch_opt(treedef, per_bucket_leaves, is_seg, first_bucket: int):
    """Reassemble the whole-vector optimizer state from per-bucket
    updates: segment leaves concatenate in CANONICAL bucket order,
    scalar leaves (every bucket computed the identical count+1) come
    from the first-dispatched bucket."""
    first = per_bucket_leaves[first_bucket]
    out = []
    for j, seg in enumerate(is_seg):
        if seg:
            out.append(jnp.concatenate(
                [pb[j] for pb in per_bucket_leaves]
            ))
        else:
            out.append(first[j])
    return jax.tree_util.tree_unflatten(treedef, out)


def _pipelined_flat_update(tx, agg_buckets, opt_state, params: FlatVector,
                           plan: BucketPlan):
    """Replicated flat-state update, one ``tx.update`` per bucket: bucket
    b's new params/moments depend only on bucket b's aggregate, so the
    update chain for an early-reduced bucket can run while later buckets
    are still on the wire. Bit-exact vs the whole-vector update for
    elementwise transforms (the repo's sgd/adam families): slicing an
    elementwise chain commutes with it, and every bucket reads the same
    input ``count``. Returns (new_params, new_opt)."""
    opt_bare = _strip_flat(opt_state)
    leaves, treedef, is_seg = _bucket_opt_views(opt_bare, plan.padded_total)
    order = readiness_bucket_order(plan)
    new_p = [None] * plan.n_buckets
    new_opt = [None] * plan.n_buckets
    for b in order:
        start, size = plan.starts[b], plan.sizes[b]
        with jax.named_scope(f"bucket_update_o{start}"):
            p_b = lax.slice(params.flat, (start,), (start + size,))
            opt_b = jax.tree_util.tree_unflatten(treedef, [
                lax.slice(l, (start,), (start + size,)) if seg else l
                for l, seg in zip(leaves, is_seg)
            ])
            u_b, opt_b_new = tx.update(agg_buckets[b], opt_b, p_b)
            new_p[b] = p_b + _strip_flat(u_b)
            new_opt[b] = jax.tree_util.tree_leaves(_strip_flat(opt_b_new))
    stitched = _stitch_opt(treedef, new_opt, is_seg, order[0])
    return (
        params.replace(flat=concat_buckets(new_p)),
        _rewrap_flat(opt_state, stitched),
    )


def _shard_reduce_bucket(bucket, size: int, axis, n: int, w, k, cfg,
                         bkey, want_contrib: bool, peak=None,
                         hi_peak: int = _INT8_PEAK):
    """One bucket of the ZeRO-1 wire: (quantize) -> psum_scatter / int8
    all_to_all -> THIS worker's dequantized 1/n shard divided by the
    aggregation count. Shared by the serial and pipelined schedules so
    the per-bucket transform (and therefore the bytes and the values)
    can never diverge between them. Returns ``(g_shard [size//n],
    contribution [size] or None)``.

    ``peak`` (adaptive precision): a traced f32 scalar selecting this
    bucket's lattice — quantize_lattice at that peak instead of the
    static int8 quantizer, same shared scales, same downstream sums
    (a lattice payload is just an int8-or-narrower payload with fewer
    live code points; ``hi_peak`` bounds the static clip so the int
    casts below stay exact)."""
    s = size // n
    bsz = cfg.quant_block_size
    if cfg.compress in ("int8", "int8_2round"):
        if peak is not None:
            q, scale = quantize_lattice(
                bucket,
                peak,
                axis_name=axis,
                block_size=bsz,
                hi_peak=hi_peak,
                out_dtype=jnp.int32,
            )
        else:
            q, scale = quantize_int8(
                bucket,
                axis_name=axis,
                block_size=bsz,
                rounding=cfg.quant_rounding,
                key=bkey,
            )
        contrib = None
        if want_contrib:
            # what the wire carries after the int8 round trip — the
            # residual is everything it dropped (incl. the whole
            # gradient on mask-excluded steps: sent==0 -> q==0 ->
            # contribution 0)
            contrib = dequantize_int8(
                q.astype(jnp.int32), scale, block_size=bsz, shape=(size,)
            )
        homomorphic = cfg.wire_domain == "homomorphic"
        if cfg.compress == "int8":
            # homomorphic: the scatter-sum rides the minimal exact
            # accumulator (int16 through 258 workers — half the dequant
            # path's int32 wire); the sums are bit-identical integers
            acc_dt = accum_dtype(n) if homomorphic else jnp.int32
            sb = lax.psum_scatter(
                q.reshape(-1).astype(acc_dt), axis, tiled=True
            )
        else:
            # the sharded 2-round wire is already compressed-domain by
            # construction (int8 a2a + LOCAL int32 sum, shard-only
            # dequant) — wire_domain changes nothing here
            q8 = q.reshape(n, s).astype(jnp.int8)
            recv = lax.all_to_all(
                q8, axis, split_axis=0, concat_axis=0, tiled=True
            )
            sb = jnp.sum(recv.astype(jnp.int32), axis=0)  # [s]
        if bsz:
            nb_loc = s // bsz
            my_scales = lax.dynamic_slice(scale, (w * nb_loc, 0), (nb_loc, 1))
            if homomorphic:
                # ONE deferred scale-multiply: the aggregation count
                # folds into the shard's own scale rows
                return (
                    sb.reshape(nb_loc, bsz).astype(jnp.float32)
                    * (my_scales / k)
                ).reshape(-1), contrib
            return (
                sb.reshape(nb_loc, bsz).astype(jnp.float32) * my_scales
            ).reshape(-1) / k, contrib
        if homomorphic:
            return dequantize_int8(sb, scale / k), contrib
        return dequantize_int8(sb, scale) / k, contrib
    return lax.psum_scatter(bucket, axis, tiled=True) / k, None


def _sharded_ps_update(params, opt_state, grads, tx, cfg, mask_key,
                       quant_key=None, err=None, agg_count=None,
                       bucket_peaks=None):
    """ZeRO-1 "sharded PS": (EF add-back) -> mask -> (quantize) ->
    reduce_scatter per bucket -> per-shard optax update -> all_gather the
    parameter delta. The flat geometry comes from the buckets engine
    (buckets.tree_layout / tree_to_flat — the same concat order and
    round-trip the replicated wire uses), carved by ``_sharded_plan``:
    one fused bucket for bucket_bytes None/0, ~N-byte buckets otherwise.
    Two compressed wires:

    - "int8": quantize, int32 psum_scatter — the sum is EXACT in int32
      but the interconnect carries int32 (compute-side compression).
    - "int8_2round": quantize, int8 all_to_all, local int32 sum — the
      wire genuinely carries int8 (~4x cut). In the sharded placement the
      reduce_scatter IS round 1 of the 2-round scheme and no second round
      exists: each chip keeps only its own region, so nothing is
      re-broadcast (parameters return via the f32 all_gather of updates,
      the analogue of the reference master's weight bcast).

    Per-bucket quantization keys fold the bucket's START OFFSET in the
    flat buffer (position-stable — the same discipline as
    collectives.piece_stream), so the noise stream a byte sees depends on
    where it lives, not on how many buckets precede it.

    `params` may be the replicated tree (state_layout="tree": flattened
    here, scattered back after the gather) or a FlatVector
    (state_layout="flat": ALREADY in this wire's shard geometry — the
    per-step tree_to_flat/flat_to_tree round trip disappears and the
    gathered update adds straight onto the flat buffer).

    `err` (error feedback) is this worker's residual on the FLAT padded
    gradient vector; returns (new_params, new_opt, new_err).

    ``agg_count`` (adaptive partial aggregation): a traced int32 count
    replacing the static ``cfg.num_aggregate`` — the mask is always
    applied (exactly 1.0 at full count) and the denominator is the
    traced count, so the same compiled program serves every count in
    the declared bounds."""
    axis, n = cfg.axis_name, cfg.num_workers
    dynamic = agg_count is not None
    if dynamic:
        k = agg_count.astype(jnp.float32)
    else:
        k = cfg.effective_aggregate
    layout = tree_layout(grads)
    total = layout.total
    plan = _sharded_plan(cfg, total)
    w = lax.axis_index(axis)
    if (
        cfg.compress in ("int8", "int8_2round")
        and cfg.quant_rounding == "stochastic"
        and quant_key is not None
    ):
        quant_key = jax.random.fold_in(quant_key, w)

    def bucket_key(start):
        return (
            jax.random.fold_in(quant_key, start)
            if quant_key is not None
            and cfg.compress in ("int8", "int8_2round")
            else None
        )

    sel = None
    if dynamic or k != n:
        sel = aggregation_mask(
            axis, n, agg_count if dynamic else cfg.num_aggregate,
            mask_key, cfg.mask_mode,
        )

    if cfg.overlap == "pipelined":
        return _sharded_ps_update_pipelined(
            params, opt_state, grads, tx, cfg, layout, plan, w, k, sel,
            bucket_key, err, bucket_peaks=bucket_peaks,
        )

    hi = precision_hi_peak(cfg) if bucket_peaks is not None else _INT8_PEAK
    flat_g = pad_flat(tree_to_flat(grads), plan)
    if err is not None:
        flat_g = flat_g + err
    sent = flat_g * sel if sel is not None else flat_g
    new_err = None
    g_shards, contribs = [], []
    for bi, (start, size) in enumerate(zip(plan.starts, plan.sizes)):
        bucket = lax.slice(sent, (start,), (start + size,))
        g_b, contrib = _shard_reduce_bucket(
            bucket, size, axis, n, w, k, cfg, bucket_key(start),
            want_contrib=err is not None,
            peak=None if bucket_peaks is None else bucket_peaks[bi],
            hi_peak=hi,
        )
        g_shards.append(g_b)
        if contrib is not None:
            contribs.append(contrib)
    g_shard = concat_buckets(g_shards)
    if err is not None:
        new_err = flat_g - concat_buckets(contribs)
    if isinstance(params, FlatVector):
        flat_p = params.flat  # already padded in this plan's geometry
    else:
        flat_p = pad_flat(tree_to_flat(params), plan)
    p_shard = _worker_region(flat_p, plan, w, n)
    upd_shard, new_opt = tx.update(g_shard, opt_state, p_shard)
    # reassemble: each bucket's shard segment gathers back tiled, in
    # bucket order, inverting _worker_region's layout exactly
    off, full = 0, []
    for size in plan.sizes:
        s = size // n
        full.append(lax.all_gather(
            lax.slice(upd_shard, (off,), (off + s,)), axis, tiled=True
        ))
        off += s
    if isinstance(params, FlatVector):
        # flat state: one vector add, no per-leaf scatter (the pad tail
        # stays zero — zero gradient => zero update)
        new_params = params.replace(flat=flat_p + concat_buckets(full))
    else:
        upd_full = concat_buckets(full)[:total]
        new_params = optax.apply_updates(
            params, flat_to_tree(layout, upd_full)
        )
    return new_params, new_opt, new_err


def _sharded_ps_update_pipelined(params, opt_state, grads, tx, cfg, layout,
                                 plan, w, k, sel, bucket_key, err,
                                 bucket_peaks=None):
    """The ZeRO-1 update as a per-bucket stream (overlap="pipelined"):
    every bucket is assembled from its own gradient leaves
    (``assemble_bucket`` — no global ``tree_to_flat`` concat, so bucket
    b's chain depends only on its leaves' gradients), reduced via the
    SAME ``_shard_reduce_bucket`` transform as the serial schedule,
    updated on its own shard segment, and gathered back — all in
    readiness order, so an early bucket's scatter/update/gather can
    overlap the rest of the backward. Values and bytes are identical to
    the serial schedule; only the dataflow (and therefore what a
    latency-hiding scheduler may interleave) changes."""
    axis, n = cfg.axis_name, cfg.num_workers
    hi = precision_hi_peak(cfg) if bucket_peaks is not None else _INT8_PEAK
    segs = bucket_leaf_segments(layout, plan)
    order = readiness_bucket_order(plan)
    g_leaves = jax.tree_util.tree_leaves(grads)
    p_is_flat = isinstance(params, FlatVector)
    p_leaves = None if p_is_flat else jax.tree_util.tree_leaves(params)
    shard_len = plan.padded_total // n
    opt_bare = _strip_flat(opt_state)
    opt_leaves, opt_def, is_seg = _bucket_opt_views(opt_bare, shard_len)
    # canonical per-bucket offsets into the worker's shard
    shard_off = []
    off = 0
    for size in plan.sizes:
        shard_off.append(off)
        off += size // n
    nb = plan.n_buckets
    new_p = [None] * nb
    new_opt = [None] * nb
    err_parts = [None] * nb
    upd_full = [None] * nb
    for b in order:
        start, size = plan.starts[b], plan.sizes[b]
        s = size // n
        with jax.named_scope(f"bucket_reduce_o{start}"):
            g_b = assemble_bucket(g_leaves, segs[b])
            if err is not None:
                g_b = g_b + lax.slice(err, (start,), (start + size,))
            sent_b = g_b * sel if sel is not None else g_b
            g_shard_b, contrib = _shard_reduce_bucket(
                sent_b, size, axis, n, w, k, cfg, bucket_key(start),
                want_contrib=err is not None,
                peak=None if bucket_peaks is None else bucket_peaks[b],
                hi_peak=hi,
            )
            if err is not None:
                err_parts[b] = g_b - contrib
        with jax.named_scope(f"bucket_update_o{start}"):
            if p_is_flat:
                p_b = lax.dynamic_slice(
                    params.flat, (start + w * s,), (s,)
                )
            else:
                p_b = lax.dynamic_slice(
                    assemble_bucket(p_leaves, segs[b]), (w * s,), (s,)
                )
            opt_b = jax.tree_util.tree_unflatten(opt_def, [
                lax.slice(l, (shard_off[b],), (shard_off[b] + s,))
                if seg else l
                for l, seg in zip(opt_leaves, is_seg)
            ])
            u_b, opt_b_new = tx.update(g_shard_b, opt_b, p_b)
            gathered = lax.all_gather(_strip_flat(u_b), axis, tiled=True)
            if p_is_flat:
                new_p[b] = (
                    lax.slice(params.flat, (start,), (start + size,))
                    + gathered
                )
            else:
                upd_full[b] = gathered
            new_opt[b] = jax.tree_util.tree_leaves(_strip_flat(opt_b_new))
    stitched = _stitch_opt(opt_def, new_opt, is_seg, order[0])
    new_opt_state = _rewrap_flat(opt_state, stitched)
    if p_is_flat:
        new_params = params.replace(flat=concat_buckets(new_p))
    else:
        # per-leaf rebuild of the gathered updates — each leaf waits on
        # its own buckets only (the pipelined mirror of flat_to_tree)
        new_params = optax.apply_updates(
            params, leaves_from_buckets(layout, plan, upd_full)
        )
    new_err = concat_buckets(err_parts) if err is not None else None
    return new_params, new_opt_state, new_err


def make_ps_train_step(
    model,
    tx: optax.GradientTransformation,
    cfg: PSConfig,
    mesh: Mesh,
    preprocess: Optional[Callable[[jax.Array, jax.Array], jax.Array]] = None,
    donate: bool = True,
    faults=None,
):
    """Build the jitted SPMD train step: (state, batch, key) -> (state, metrics).

    `batch` is {"image": uint8 [B,...], "label": int32 [B]} with B divisible by
    num_workers; `key` drives augmentation/dropout (per-worker folded) and the
    random-K aggregation mask (shared). One call = one global step of the
    reference protocol (master step N + all workers' iteration N together).

    With cfg.nonfinite_guard the step carries its own defense: a per-worker
    all-finite reduction over the gradients, one int32 pmin for mesh
    consensus (4 B on the wire, no host transfer), and a `jnp.where` select
    that turns the whole state update into the identity on a bad step —
    the guard decision never leaves the device. Under state_layout="flat"
    that rollback selects a handful of whole flat vectors (params + each
    optimizer moment) instead of every pytree leaf.

    cfg.state_layout="flat" (default) keeps master params and optimizer
    moments as padded flat f32 vectors end to end: the forward pass reads
    a once-per-step tree view, the reduced flat gradient feeds one fused
    vector update, and the ZeRO-1 path skips its per-step
    tree_to_flat(params). Compute-side only — the wire is byte-identical
    to "tree" (pscheck's layout-parity gate pins this).

    `faults` (resilience.FaultPlan) bakes deterministic NaN/Inf gradient
    injection into the compiled step at the planned global steps — the
    chaos harness that proves the guard end-to-end.

    cfg.adaptive_aggregate (num_aggregate_min/max set) changes the step
    signature to ``(state, batch, key, agg_count) -> (state, metrics)``:
    ``agg_count`` is a traced int32 scalar the host updates per window
    (resilience/elastic.AdaptiveMaskController), clipped on device to the
    declared bounds so a host bug can never divide by zero or mask out
    everything. Same compiled program for every count — no retrace on
    adaptation.

    cfg.precision_adapt appends a traced int32 ``prec_tags`` [n_buckets]
    argument (after ``agg_count`` when both are on): per-bucket lattice
    tags (skip/4-bit/int8/hi) the host-side PrecisionController updates
    per window from the ``bucket_sqnorm`` metrics row this step emits.
    Tags are clamped on device and only select traced clipping peaks, so
    — like the count — every tag vector runs the same compiled program.
    """
    axis, n = cfg.axis_name, cfg.num_workers
    specs = state_specs(cfg)
    # per-axis sizes for the hierarchical (DCN x ICI) 2-round scheme
    hier_sizes = (
        tuple(mesh.shape[a] for a in axis)
        if isinstance(axis, (tuple, list))
        else None
    )

    def worker_fn(step_idx, params, opt_state, batch_stats, comm_state,
                  guard_state, images, labels, key, *extras):
        # traced per-window controller inputs, in declaration order:
        # agg_count (cfg.adaptive_aggregate), prec_tags (cfg.precision_adapt)
        extras = list(extras)
        agg_count = extras.pop(0) if cfg.adaptive_aggregate else None
        prec_tags = extras.pop(0) if cfg.precision_adapt else None
        if agg_count is not None:
            # device-side clamp to the declared bounds: the contract the
            # PSC108 envelope relies on must hold even against a buggy
            # host-side controller
            agg_count = jnp.clip(
                agg_count, cfg.num_aggregate_min, cfg.num_aggregate_max
            ).astype(jnp.int32)
        bucket_peaks = None
        hi_peak = _INT8_PEAK
        if prec_tags is not None:
            # same defense for the precision controller: clamp every tag
            # into the declared lattice set, then gather the traced
            # clipping peaks (0 / 7 / 127 / hi) the quantizer selects on
            hi_peak = precision_hi_peak(cfg)
            prec_tags = jnp.clip(prec_tags, 0, 3).astype(jnp.int32)
            bucket_peaks = jnp.asarray(
                precision_peaks(hi_peak), jnp.float32
            )[prec_tags]
        w = lax.axis_index(axis)
        k_step = jax.random.fold_in(key, step_idx)
        k_mask = jax.random.fold_in(k_step, 0xA66)
        k_aug, k_drop = jax.random.split(jax.random.fold_in(k_step, w + 1))

        x = preprocess(k_aug, images) if preprocess else images.astype(jnp.float32)

        params_in, opt_in, bs_in_raw, comm_in = (
            params, opt_state, batch_stats, comm_state
        )
        # tree view for the forward/backward pass; under state_layout=
        # "flat" this is the once-per-step flat_to_tree materialization
        # (static slices/reshapes XLA fuses into the consumers), and the
        # master `params` stays the padded flat vector end to end
        params_t = tree_view(params)
        scale = (
            guard_state.scale
            if cfg.nonfinite_guard and cfg.dynamic_loss_scale
            else None
        )

        if cfg.opt_placement == "sharded":
            opt_state = tree_map(lambda a: a[0], opt_state)
        bs = tree_map(lambda a: a[0], batch_stats) if cfg.bn_mode == "local" else batch_stats

        def fwd_bwd(bs_in, xi, yi, kd):
            def loss_fn(p):
                logits, new_bs = apply_model(
                    model, p, bs_in, xi, train=True, dropout_rng=kd
                )
                loss = cross_entropy_loss(logits, yi)
                if scale is not None:
                    loss = loss * scale
                return loss, (logits, new_bs)

            (loss, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(params_t)
            if scale is not None:
                # unscale immediately: everything downstream (EF residual,
                # quantization, the finite check) sees true-magnitude
                # gradients; overflow shows up as inf surviving the divide
                loss = loss / scale
                g = tree_map(lambda t: t / scale, g)
            return (loss, aux), g

        if cfg.grad_accum_steps > 1:
            a = cfg.grad_accum_steps
            if x.shape[0] % a:  # static shape: raises at trace time
                raise ValueError(
                    f"per-worker batch {x.shape[0]} not divisible by "
                    f"grad_accum_steps={a}"
                )
            xm = x.reshape(a, x.shape[0] // a, *x.shape[1:])
            ym = labels.reshape(a, -1)

            def micro(carry, inp):
                bs_c, gsum, lsum, p1sum, p5sum = carry
                i, xi, yi = inp
                (loss_i, (logits_i, bs_i)), g_i = fwd_bwd(
                    bs_c, xi, yi, jax.random.fold_in(k_drop, i)
                )
                p1_i, p5_i = accuracy(logits_i, yi, (1, 5))
                carry = (
                    bs_i,
                    tree_map(jnp.add, gsum, g_i),
                    lsum + loss_i,
                    p1sum + p1_i,
                    p5sum + p5_i,
                )
                return carry, None

            zeros = tree_map(jnp.zeros_like, params_t)
            (new_bs, gsum, lsum, p1sum, p5sum), _ = lax.scan(
                micro,
                (bs, zeros, 0.0, 0.0, 0.0),
                (jnp.arange(a), xm, ym),
            )
            grads = tree_map(lambda g: g / a, gsum)
            loss, prec1, prec5 = lsum / a, p1sum / a, p5sum / a
        else:
            (loss, (logits, new_bs)), grads = fwd_bwd(bs, x, labels, k_drop)
            prec1, prec5 = accuracy(logits, labels, (1, 5))

        if faults is not None and (faults.nan_grads or faults.inf_grads):
            # deterministic chaos: poison the gradients at the planned
            # global steps (host numbering: step_idx is pre-increment)
            host_step = step_idx + 1
            for steps, val in ((faults.nan_grads, jnp.nan),
                               (faults.inf_grads, jnp.inf)):
                if steps:
                    hit = jnp.any(host_step == jnp.asarray(steps, jnp.int32))
                    grads = tree_map(
                        lambda g, h=hit, v=val: jnp.where(h, v, g), grads
                    )

        bucket_sqnorm = None
        if cfg.precision_adapt:
            # per-bucket telemetry for the host-side PrecisionController:
            # mesh-mean squared gradient norm per state_plan bucket,
            # measured on the RAW per-worker gradients (pre-EF add-back,
            # pre-mask — the controller ranks signal density, not wire
            # artifacts). Static slices over the same flat buffer the
            # guard probe flattens, so XLA CSEs the concat; one [n_buckets]
            # f32 pmean rides the metrics dict the host already fetches.
            lay = tree_layout(grads)
            splan = state_plan(cfg, lay.total)
            flat_raw = pad_flat(tree_to_flat(grads), splan)
            bucket_sqnorm = lax.pmean(
                jnp.stack([
                    jnp.sum(
                        jnp.square(lax.slice(flat_raw, (s0,), (s0 + sz,)))
                    )
                    for s0, sz in zip(splan.starts, splan.sizes)
                ]),
                axis,
            )

        finite = None
        if cfg.nonfinite_guard:
            # mesh-wide agreement on "every worker's gradients are
            # finite": one int32 pmin — 4 bytes on the interconnect, no
            # host transfer, and every worker takes the same branch.
            # With bucketing on, the per-worker half reduces ONE fused
            # isfinite over the flat buffer (XLA CSEs the concat with
            # the wire's own flatten) instead of one reduction per leaf.
            probe = (
                tree_to_flat(grads)
                if cfg.bucket_bytes is not None
                else grads
            )
            finite = lax.pmin(
                tree_all_finite(probe).astype(jnp.int32), axis
            ) > 0

        new_comm = comm_state
        quant_key = (
            jax.random.fold_in(k_step, 0x5E) if cfg.compress else None
        )
        if cfg.opt_placement == "sharded":
            err = comm_state[0] if cfg.error_feedback else None
            params, new_opt, new_err = _sharded_ps_update(
                params, opt_state, grads, tx, cfg, k_mask,
                quant_key=quant_key, err=err, agg_count=agg_count,
                bucket_peaks=bucket_peaks,
            )
            new_opt = tree_map(lambda a: a[None], new_opt)
            if cfg.error_feedback:
                new_comm = new_err[None]
        else:
            if cfg.error_feedback:
                # EF-SGD: add back last step's compression residual before
                # transmitting; the new residual is what the wire dropped
                # — including the ENTIRE gradient on mask-excluded steps
                # (EF subsumes stale-gradient accumulation for the
                # backup-worker mode)
                err = tree_map(lambda a: a[0], comm_state)
                grads = tree_map(jnp.add, grads, err)
            is_flat = cfg.state_layout == "flat"
            pipelined = cfg.overlap == "pipelined"
            # pipelined x flat x bucketed: the aggregate stays a LIST of
            # per-bucket vectors so the optimizer can start per bucket —
            # the only spelling with no whole-vector barrier at all
            bucket_out = (
                pipelined and is_flat and cfg.bucket_bytes is not None
            )
            out = aggregate_gradients(
                grads,
                axis,
                n,
                num_aggregate=(
                    agg_count if agg_count is not None else cfg.num_aggregate
                ),
                mask_key=k_mask,
                mask_mode=cfg.mask_mode,
                compress=cfg.compress,
                quant_block_size=cfg.quant_block_size,
                quant_rounding=cfg.quant_rounding,
                quant_key=quant_key,
                return_contribution=cfg.error_feedback,
                axis_sizes=hier_sizes,
                bucket_bytes=cfg.bucket_bytes,
                flat_output=is_flat and not bucket_out,
                pipelined=pipelined,
                bucket_output=bucket_out,
                wire_domain=cfg.wire_domain,
                bucket_peaks=bucket_peaks,
                lattice_hi_peak=hi_peak,
            )
            if cfg.error_feedback:
                # the contribution (and the residual it defines) stays
                # per-leaf in both layouts — checkpoint portability
                agg, contribution = out
                new_err = tree_map(lambda a, b: a - b, grads, contribution)
                new_comm = tree_map(lambda a: a[None], new_err)
            else:
                agg = out
            if bucket_out:
                # per-bucket fused vector updates, dispatched as each
                # bucket's reduction lands (state_plan and the wire share
                # one BucketPlan, so the per-bucket aggregates drop
                # straight onto the state's own carving)
                params, new_opt = _pipelined_flat_update(
                    tx, agg, opt_state, params, params.plan
                )
            else:
                if is_flat:
                    # the reduced flat gradient, already in the state's
                    # BucketPlan geometry (piece_stream and state_plan
                    # share wire_align) — wrap it and run ONE fused
                    # vector update
                    agg = params.replace(flat=agg)
                updates, new_opt = tx.update(agg, opt_state, params)
                params = optax.apply_updates(params, updates)

        if cfg.bn_mode == "local":
            out_bs = tree_map(lambda a: a[None], new_bs)
        else:
            out_bs = lax.pmean(new_bs, axis) if new_bs else new_bs

        metrics = lax.pmean(
            {"loss": loss, "prec1": prec1, "prec5": prec5}, axis
        )
        if bucket_sqnorm is not None:
            # already pmean'd; a VECTOR row in the metrics dict — the
            # trainer pops it before its scalar float() sweep
            metrics["bucket_sqnorm"] = bucket_sqnorm
        new_guard = guard_state
        if cfg.nonfinite_guard:
            # skip-step: a non-finite step becomes the identity update —
            # params, optimizer state, BN stats, and EF residuals all keep
            # their pre-step values bit-identically; only the guard
            # counters (and the loss scale) advance. The aggregation
            # collectives still ran (NaNs flow through them harmlessly),
            # so the per-step wire accounting is step-invariant.
            def sel(new, old):
                return tree_map(
                    lambda a, b: jnp.where(finite, a, b), new, old
                )

            params = sel(params, params_in)
            new_opt = sel(new_opt, opt_in)
            out_bs = sel(out_bs, bs_in_raw)
            new_comm = sel(new_comm, comm_in)
            new_guard = update_guard_state(
                guard_state, finite, cfg.dynamic_loss_scale,
                cfg.loss_scale_growth_interval,
            )
            # ride the metrics dict the host already fetches once per log
            # window — the guard adds no per-step host transfer
            metrics["skipped_steps"] = new_guard.skipped.astype(jnp.float32)
            metrics["skip_streak"] = new_guard.consec.astype(jnp.float32)
            if cfg.dynamic_loss_scale:
                metrics["loss_scale"] = new_guard.scale
        return params, new_opt, out_bs, new_comm, new_guard, metrics

    base_in_specs = (
        P(),
        specs.params,
        specs.opt_state,
        specs.batch_stats,
        specs.comm_state,
        specs.guard_state,
        P(axis),
        P(axis),
        P(),
    )
    out_specs = (
        specs.params,
        specs.opt_state,
        specs.batch_stats,
        specs.comm_state,
        specs.guard_state,
        P(),
    )
    # the adaptive signatures thread the traced controller inputs through
    # shard_map (replicated scalar count, replicated [n_buckets] tag
    # vector — in that order); the static path keeps the 9-arg shape so
    # its jaxpr — and the committed comm contract — is untouched
    extra_specs = ()
    if cfg.adaptive_aggregate:
        extra_specs += (P(),)
    if cfg.precision_adapt:
        extra_specs += (P(),)
    mapped = jax.shard_map(
        worker_fn,
        mesh=mesh,
        in_specs=base_in_specs + extra_specs,
        out_specs=out_specs,
        check_vma=False,
    )

    def step(state: PSTrainState, batch, key, *agg):
        params, opt_state, batch_stats, comm_state, guard_state, metrics = (
            mapped(
                state.step,
                state.params,
                state.opt_state,
                state.batch_stats,
                state.comm_state,
                state.guard_state,
                batch["image"],
                batch["label"],
                key,
                *agg,
            )
        )
        new_state = PSTrainState(
            step=state.step + 1,
            params=params,
            opt_state=opt_state,
            batch_stats=batch_stats,
            comm_state=comm_state,
            guard_state=guard_state,
        )
        return new_state, metrics

    # fixed-arity wrappers so the jitted signature names its extra args
    # (count first, tags second — matching extra_specs above). The
    # `donate_argnums=... if donate else ()` conditional stays inline in
    # each return: pslint's PSL005 donor discovery reads exactly this
    # idiom to learn the factory's donated positions and honor callers'
    # donate=False opt-outs.
    if cfg.adaptive_aggregate and cfg.precision_adapt:
        def step_both(state: PSTrainState, batch, key, agg_count,
                      prec_tags):
            return step(state, batch, key, agg_count, prec_tags)

        return jax.jit(step_both, donate_argnums=(0,) if donate else ())
    if cfg.adaptive_aggregate:
        def step_adaptive(state: PSTrainState, batch, key, agg_count):
            return step(state, batch, key, agg_count)

        return jax.jit(step_adaptive, donate_argnums=(0,) if donate else ())
    if cfg.precision_adapt:
        def step_precision(state: PSTrainState, batch, key, prec_tags):
            return step(state, batch, key, prec_tags)

        return jax.jit(step_precision, donate_argnums=(0,) if donate else ())
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_ps_eval_step(model, cfg: PSConfig, mesh: Mesh, preprocess=None):
    """Sharded evaluation step: (state, batch) -> metrics (pmean'd)."""
    axis = cfg.axis_name

    def worker_fn(params, batch_stats, images, labels):
        bs = tree_map(lambda a: a[0], batch_stats) if cfg.bn_mode == "local" else batch_stats
        x = preprocess(None, images) if preprocess else images.astype(jnp.float32)
        logits, _ = apply_model(model, tree_view(params), bs, x, train=False)
        loss = cross_entropy_loss(logits, labels)
        prec1, prec5 = accuracy(logits, labels, (1, 5))
        return lax.pmean({"loss": loss, "prec1": prec1, "prec5": prec5}, axis)

    specs = state_specs(cfg)
    mapped = jax.shard_map(
        worker_fn,
        mesh=mesh,
        in_specs=(specs.params, specs.batch_stats, P(axis), P(axis)),
        out_specs=P(),
        check_vma=False,
    )

    def step(state: PSTrainState, batch):
        return mapped(state.params, state.batch_stats, batch["image"], batch["label"])

    return jax.jit(step)
