"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context support is out of the reference's scope (SURVEY.md section 5:
CNN image workloads only, "no attention, no sequence dimension anywhere"),
but it is first-class here: the same ICI ring that carries the PS gradient
collectives carries blockwise attention, so sequences scale with the mesh
instead of with one chip's HBM.

Algorithm (blockwise online softmax, flash-attention style accumulation):
each of the N devices holds a [B, T/N, H, D] shard of Q/K/V. K/V blocks
rotate around the ring with `lax.ppermute` (neighbor exchange over ICI —
N-1 hops total, each overlapped by XLA with the local QK^T/PV compute);
every hop updates a running (max m, denominator l, numerator o) triple, so
softmax is exact without ever materializing the [T, T] score matrix.
Causality is enforced per (query-block, key-block) pair from the devices'
ring positions — fully-masked pairs contribute nothing and skip no hops
(uniform control flow keeps the loop compilable).

The N=1 degenerate case is exact full attention; tests check the sharded
result against it bit-for-tolerance on the virtual CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SEQ_AXIS = "seq"

_NEG_BIG = -1e30  # mask value; avoids -inf - -inf = nan in the max trick


def _block_attend(q, k, v, mask, scale):
    """One (query-block x key-block) contribution.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; mask: [Tq, Tk] bool or None.
    Returns (m_blk [B, H, Tq], p_sum [B, H, Tq], pv [B, Tq, H, D]).

    Softmax statistics and accumulators are f32 regardless of input dtype
    (bf16 stats lose the max-trick's cancellation; matmuls still run on
    the inputs' dtype through the MXU with f32 accumulation).
    """
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)
    m_blk = jnp.max(scores, axis=-1)  # [B, H, Tq]
    p = jnp.exp(scores - m_blk[..., None])
    if mask is not None:
        # rows with no valid key: m_blk == _NEG_BIG and p would be exp(0)=1
        p = jnp.where(mask[None, None], p, 0.0)
    p_sum = jnp.sum(p, axis=-1)
    # PV runs on the inputs' dtype (bf16 MXU path) with f32 accumulation;
    # only the stats (m, l) and the running output stay f32
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_blk, p_sum, pv


def _accumulate(acc, m_blk, p_sum, pv):
    """Fold one block's (max, sum, numerator) into the running triple."""
    o, m, l = acc
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)  # rescale old accumulators
    beta = jnp.exp(m_blk - m_new)  # rescale this block
    l_new = l * alpha + p_sum * beta
    o_new = (
        o * alpha.transpose(0, 2, 1)[..., None]
        + pv * beta.transpose(0, 2, 1)[..., None]
    )
    return o_new, m_new, l_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    bidirectional: bool = False,
) -> jax.Array:
    """Exact attention over sequence shards rotating on a ring.

    Call inside shard_map with q/k/v sharded [B, T_local, H, D] along the
    sequence axis `axis_name`. Returns the local output shard.

    `bidirectional=True` rotates K/V both ways simultaneously and processes
    two blocks per hop: same total traffic, half the sequential hops, and
    both ICI directions of a physical ring in use. Falls back to the
    one-way ring for n <= 2 (nothing to overlap).
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    q_pos = me * t_loc + jnp.arange(t_loc)  # global query positions

    def block_mask(k_blk):
        if not causal:
            return None
        k_pos = k_blk * t_loc + jnp.arange(t_loc)
        return k_pos[None, :] <= q_pos[:, None]  # [Tq, Tk]

    o0 = jnp.zeros(q.shape, jnp.float32)  # f32 accumulators (see _block_attend)
    m0 = jnp.full((b, h, t_loc), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, t_loc), jnp.float32)

    # send my k/v block to the PREVIOUS device each hop: after s hops,
    # device i holds key block (i + s) mod n
    perm_fwd = [(j, (j - 1) % n) for j in range(n)]

    if not bidirectional or n <= 2:

        def hop(carry, s):
            o, m, l, k_cur, v_cur = carry
            m_blk, p_sum, pv = _block_attend(
                q, k_cur, v_cur, block_mask((me + s) % n), scale
            )
            acc = _accumulate((o, m, l), m_blk, p_sum, pv)
            # uniform rotation every hop keeps the loop body identical for
            # XLA (the final hop's permute returns k/v home)
            k_nxt = lax.ppermute(k_cur, axis_name, perm_fwd)
            v_nxt = lax.ppermute(v_cur, axis_name, perm_fwd)
            return (*acc, k_nxt, v_nxt), None

        # scan (not fori_loop): reverse-mode AD must flow through the ring
        # for training; ppermute transposes to the inverse rotation
        (o, m, l, _, _), _ = lax.scan(hop, (o0, m0, l0, k, v), jnp.arange(n))
    else:
        perm_bwd = [(j, (j + 1) % n) for j in range(n)]
        # own block first (no comm), then ceil((n-1)/2) two-block hops
        acc = _accumulate(
            (o0, m0, l0), *_block_attend(q, k, v, block_mask(me), scale)
        )
        n_hops = (n - 1 + 1) // 2
        # offsets +s (fwd) and -s (bwd) cover 1..n-1; for even n the offset
        # n/2 arrives on both streams — drop the bwd duplicate at s = n/2
        use_bwd = np.ones(n_hops, bool)
        if n % 2 == 0:
            use_bwd[-1] = False

        def hop2(carry, xs):
            s, bwd_ok = xs
            o, m, l, k_f, v_f, k_b, v_b = carry
            k_f = lax.ppermute(k_f, axis_name, perm_fwd)
            v_f = lax.ppermute(v_f, axis_name, perm_fwd)
            k_b = lax.ppermute(k_b, axis_name, perm_bwd)
            v_b = lax.ppermute(v_b, axis_name, perm_bwd)
            acc = _accumulate(
                (o, m, l),
                *_block_attend(q, k_f, v_f, block_mask((me + s) % n), scale),
            )
            m_blk, p_sum, pv = _block_attend(
                q, k_b, v_b, block_mask((me - s) % n), scale
            )
            # mask the duplicate block to a no-op contribution
            m_blk = jnp.where(bwd_ok, m_blk, _NEG_BIG)
            p_sum = jnp.where(bwd_ok, p_sum, 0.0)
            pv = jnp.where(bwd_ok, pv, 0.0)
            acc = _accumulate(acc, m_blk, p_sum, pv)
            return (*acc, k_f, v_f, k_b, v_b), None

        (o, m, l, *_), _ = lax.scan(
            hop2,
            (*acc, k, v, k, v),
            (jnp.arange(1, n_hops + 1), jnp.asarray(use_bwd)),
        )
    # causal guarantees >= 1 valid key per query (its own position), so l > 0
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


# ---------------------------------------------------------- ring + flash
# Flash WITHIN each hop: the jnp ring above materializes a [T_loc, T_loc]
# score block per hop; here each hop runs the Pallas partial-triple kernel
# (ops/flash_attention.flash_partial), so per-hop memory is O(block) and
# the full attention over N shards never builds a T_loc^2 tensor anywhere.
# Gradients are a custom VJP: a second ring pass in which dk/dv
# accumulators TRAVEL WITH their k/v shards (n rotations = home), each hop
# adding its exact contribution computed from the globally-merged
# (lse, delta) stats — summing to the exact flash backward.


def _fold_heads(x):  # [B, T, H, D] -> [B*H, T, D] (kernel layout)
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unfold_heads(x3, b, h):  # inverse of _fold_heads
    bh, t, d = x3.shape
    return x3.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _merge_triple(acc, hop):
    """Online-softmax merge of two (pv [BH,T,D], m [BH,T], l [BH,T])."""
    pv, m, l = acc
    pv_h, m_h, l_h = hop
    m_new = jnp.maximum(m, m_h)
    # guard fully-masked-so-far rows: exp(_NEG_BIG - _NEG_BIG) = 1 is fine
    # (l contributions are 0 there), but exp below must not overflow
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(m_h - m_new)
    return (
        pv * alpha[..., None] + pv_h * beta[..., None],
        m_new,
        l * alpha + l_h * beta,
    )


_NOOP_M = _NEG_BIG  # a masked hop contributes (pv=0, m=_NEG_BIG, l=0)


def _mask_triple(ok, triple):
    """Reduce a (pv, m, l) hop contribution to a no-op when not ok."""
    pv, m, l = triple
    return (
        jnp.where(ok, pv, 0.0),
        jnp.where(ok, m, _NOOP_M),
        jnp.where(ok, l, 0.0),
    )


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    bidirectional: bool = False,
) -> jax.Array:
    """ring_attention with the Pallas flash kernel inside each hop.

    Call inside shard_map with q/k/v sharded [B, T_local, H, D] along
    `axis_name`. Exact (same math as ring_attention/full_attention); falls
    back to kernel interpret mode off-TPU. Memory per hop is O(block_q x
    block_k) VMEM scratch + the O(T_loc) (pv, m, l) running triple.

    bidirectional=True rotates K/V both ways and merges two partial
    triples per hop — same total traffic, half the sequential hops, both
    ICI directions in use (the flash analogue of ring_attention's
    bidirectional mode; falls back to one-way for n <= 2)."""
    o, _ = _ring_flash_fwd(
        q, k, v, axis_name, causal, scale, block_q, block_k, bidirectional
    )
    return o


def _bidir_plan(n):
    """Offsets 1..n-1 covered by +s (fwd) and -s (bwd) streams; for even n
    the offset n/2 arrives on both — drop the bwd duplicate."""
    n_hops = (n - 1 + 1) // 2
    use_bwd = np.ones(n_hops, bool)
    if n % 2 == 0 and n_hops:
        use_bwd[-1] = False
    return n_hops, use_bwd


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                    bidirectional):
    from ..ops.flash_attention import flash_partial

    n = lax.axis_size(axis_name)
    # global positions are only consumed by the causal mask; without it,
    # deriving the shard offsets from lax.axis_index would strand a
    # partition-id op on the kernel's (then-unused) SMEM offsets operand,
    # which XLA's SPMD partitioner refuses to place (the ring_flash-bidir
    # CPU failure) — so the non-causal ring simply doesn't ask where it is
    me = lax.axis_index(axis_name) if causal else 0
    b, t_loc, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    q3, k3, v3 = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    q_off = me * t_loc

    pv0 = jnp.zeros(q3.shape, jnp.float32)
    m0 = jnp.full(q3.shape[:2], _NEG_BIG, jnp.float32)
    l0 = jnp.zeros(q3.shape[:2], jnp.float32)
    perm_fwd = [(j, (j - 1) % n) for j in range(n)]

    def partial_at(k_c, v_c, blk_idx):
        return flash_partial(
            q3, k_c, v_c, scale, causal, q_off,
            blk_idx * t_loc if causal else 0,
            block_q, block_k,
        )

    if not bidirectional or n <= 2:

        def hop(carry, s):
            pv, m, l, k_c, v_c = carry
            triple = partial_at(k_c, v_c, (me + s) % n)
            pv, m, l = _merge_triple((pv, m, l), triple)
            k_c = lax.ppermute(k_c, axis_name, perm_fwd)
            v_c = lax.ppermute(v_c, axis_name, perm_fwd)
            return (pv, m, l, k_c, v_c), None

        # k/v come home after n rotations; scan keeps one hop's buffers live
        (pv, m, l, k3, v3), _ = lax.scan(
            hop, (pv0, m0, l0, k3, v3), jnp.arange(n)
        )
    else:
        perm_bwd = [(j, (j + 1) % n) for j in range(n)]
        acc = _merge_triple((pv0, m0, l0), partial_at(k3, v3, me))
        n_hops, use_bwd = _bidir_plan(n)

        def hop2(carry, xs):
            s, bwd_ok = xs
            pv, m, l, k_f, v_f, k_b, v_b = carry
            k_f = lax.ppermute(k_f, axis_name, perm_fwd)
            v_f = lax.ppermute(v_f, axis_name, perm_fwd)
            k_b = lax.ppermute(k_b, axis_name, perm_bwd)
            v_b = lax.ppermute(v_b, axis_name, perm_bwd)
            acc = _merge_triple(
                (pv, m, l), partial_at(k_f, v_f, (me + s) % n)
            )
            tb = _mask_triple(bwd_ok, partial_at(k_b, v_b, (me - s) % n))
            acc = _merge_triple(acc, tb)
            return (*acc, k_f, v_f, k_b, v_b), None

        (pv, m, l, *_), _ = lax.scan(
            hop2,
            (*acc, k3, v3, k3, v3),
            (jnp.arange(1, n_hops + 1), jnp.asarray(use_bwd)),
        )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o3 = pv / l_safe[..., None]
    lse = m + jnp.log(l_safe)
    o = _unfold_heads(o3, b, h).astype(q.dtype)
    return o, (q3, k3, v3, o3, lse)


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                        bidirectional):
    return _ring_flash_fwd(
        q, k, v, axis_name, causal, scale, block_q, block_k, bidirectional
    )


def _ring_flash_vjp_bwd(axis_name, causal, scale, block_q, block_k,
                        bidirectional, res, do):
    from ..ops.flash_attention import flash_grads_partial

    q3, k3, v3, o3, lse = res
    b, t_loc, h, d = do.shape  # static shape/dtype info rides on the cotangent
    in_dtype = do.dtype
    n = lax.axis_size(axis_name)
    # same rule as _ring_flash_fwd: only the causal mask consumes global
    # positions, and a dead axis_index strands an unplaceable partition-id
    me = lax.axis_index(axis_name) if causal else 0
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    do3 = _fold_heads(do).astype(q3.dtype)
    delta = jnp.sum(do3.astype(jnp.float32) * o3, axis=-1)  # [BH, T_loc]
    q_off = me * t_loc
    perm_fwd = [(j, (j - 1) % n) for j in range(n)]

    dq0 = jnp.zeros(q3.shape, jnp.float32)
    dkv0 = jnp.zeros(k3.shape, jnp.float32)

    def grads_at(k_c, v_c, blk_idx):
        return flash_grads_partial(
            q3, k_c, v_c, do3, lse, delta, scale, causal,
            q_off, blk_idx * t_loc if causal else 0, block_q, block_k,
        )

    if not bidirectional or n <= 2:

        def hop(carry, s):
            dq, k_c, v_c, dk_c, dv_c = carry
            dq_h, dk_h, dv_h = grads_at(k_c, v_c, (me + s) % n)
            dq = dq + dq_h
            dk_c = dk_c + dk_h
            dv_c = dv_c + dv_h
            # dk/dv accumulators travel WITH their k/v shard; after n
            # rotations every shard (and its gradient) is home
            k_c = lax.ppermute(k_c, axis_name, perm_fwd)
            v_c = lax.ppermute(v_c, axis_name, perm_fwd)
            dk_c = lax.ppermute(dk_c, axis_name, perm_fwd)
            dv_c = lax.ppermute(dv_c, axis_name, perm_fwd)
            return (dq, k_c, v_c, dk_c, dv_c), None

        (dq, _, _, dk, dv), _ = lax.scan(
            hop, (dq0, k3, v3, dkv0, dkv0), jnp.arange(n)
        )
    else:
        perm_bwd = [(j, (j + 1) % n) for j in range(n)]
        dq, dk_own, dv_own = grads_at(k3, v3, me)  # own block, no comm
        n_hops, use_bwd = _bidir_plan(n)

        def hop2(carry, xs):
            s, bwd_ok = xs
            dq, k_f, v_f, dk_f, dv_f, k_b, v_b, dk_b, dv_b = carry
            k_f = lax.ppermute(k_f, axis_name, perm_fwd)
            v_f = lax.ppermute(v_f, axis_name, perm_fwd)
            dk_f = lax.ppermute(dk_f, axis_name, perm_fwd)
            dv_f = lax.ppermute(dv_f, axis_name, perm_fwd)
            k_b = lax.ppermute(k_b, axis_name, perm_bwd)
            v_b = lax.ppermute(v_b, axis_name, perm_bwd)
            dk_b = lax.ppermute(dk_b, axis_name, perm_bwd)
            dv_b = lax.ppermute(dv_b, axis_name, perm_bwd)
            dq_f, dkh_f, dvh_f = grads_at(k_f, v_f, (me + s) % n)
            dq_b, dkh_b, dvh_b = grads_at(k_b, v_b, (me - s) % n)
            dq = dq + dq_f + jnp.where(bwd_ok, dq_b, 0.0)
            dk_f = dk_f + dkh_f
            dv_f = dv_f + dvh_f
            dk_b = dk_b + jnp.where(bwd_ok, dkh_b, 0.0)
            dv_b = dv_b + jnp.where(bwd_ok, dvh_b, 0.0)
            return (dq, k_f, v_f, dk_f, dv_f, k_b, v_b, dk_b, dv_b), None

        (dq, _, _, dk_f, dv_f, _, _, dk_b, dv_b), _ = lax.scan(
            hop2,
            (dq, k3, v3, dkv0, dkv0, k3, v3, dkv0, dkv0),
            (jnp.arange(1, n_hops + 1), jnp.asarray(use_bwd)),
        )
        # deliver the traveling accumulators home in ONE rotation each:
        # after n_hops fwd rotations, device j's fwd accumulator describes
        # block (j + n_hops) % n -> send to that device; mirror for bwd
        home_f = [(j, (j + n_hops) % n) for j in range(n)]
        home_b = [(j, (j - n_hops) % n) for j in range(n)]
        dk = (
            dk_own
            + lax.ppermute(dk_f, axis_name, home_f)
            + lax.ppermute(dk_b, axis_name, home_b)
        )
        dv = (
            dv_own
            + lax.ppermute(dv_f, axis_name, home_f)
            + lax.ppermute(dv_b, axis_name, home_b)
        )

    unfold = lambda x3: _unfold_heads(x3, b, h).astype(in_dtype)
    return unfold(dq), unfold(dk), unfold(dv)


ring_flash_attention.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Single-device reference: exact softmax attention, [B, T, H, D]."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    # f32 softmax regardless of input dtype (matches the ring/flash paths)
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def make_seq_mesh(num_shards: Optional[int] = None) -> Mesh:
    """1-D sequence-parallel mesh (axis 'seq')."""
    from .mesh import make_mesh

    return make_mesh(num_workers=num_shards, axis_name=SEQ_AXIS)


def make_ring_attention(
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    bidirectional: bool = False,
    impl: str = "naive",
):
    """Jitted sequence-sharded attention: (q, k, v) [B, T, H, D] global ->
    [B, T, H, D] global, T sharded over the mesh axis.

    impl="flash" uses the Pallas partial-triple kernel per hop
    (ring_flash_attention), one-way or bidirectional."""
    if impl == "flash":
        fn = partial(
            ring_flash_attention, axis_name=axis_name, causal=causal,
            bidirectional=bidirectional,
        )
    else:
        fn = partial(
            ring_attention,
            axis_name=axis_name,
            causal=causal,
            bidirectional=bidirectional,
        )
    mapped = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )
    return jax.jit(mapped)


def shard_sequence(x: jax.Array, mesh: Mesh, axis_name: str = SEQ_AXIS):
    """Place [B, T, ...] with T sharded along the mesh axis."""
    return jax.device_put(x, NamedSharding(mesh, P(None, axis_name)))
