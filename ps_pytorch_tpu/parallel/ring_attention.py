"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context support is out of the reference's scope (SURVEY.md section 5:
CNN image workloads only, "no attention, no sequence dimension anywhere"),
but it is first-class here: the same ICI ring that carries the PS gradient
collectives carries blockwise attention, so sequences scale with the mesh
instead of with one chip's HBM.

Algorithm (blockwise online softmax, flash-attention style accumulation):
each of the N devices holds a [B, T/N, H, D] shard of Q/K/V. K/V blocks
rotate around the ring with `lax.ppermute` (neighbor exchange over ICI —
N-1 hops total, each overlapped by XLA with the local QK^T/PV compute);
every hop updates a running (max m, denominator l, numerator o) triple, so
softmax is exact without ever materializing the [T, T] score matrix.
Causality is enforced per (query-block, key-block) pair from the devices'
ring positions — fully-masked pairs contribute nothing and skip no hops
(uniform control flow keeps the loop compilable).

The N=1 degenerate case is exact full attention; tests check the sharded
result against it bit-for-tolerance on the virtual CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SEQ_AXIS = "seq"

_NEG_BIG = -1e30  # mask value; avoids -inf - -inf = nan in the max trick


def _block_attend(q, k, v, mask, scale):
    """One (query-block x key-block) contribution.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; mask: [Tq, Tk] bool or None.
    Returns (m_blk [B, H, Tq], p_sum [B, H, Tq], pv [B, Tq, H, D]).

    Softmax statistics and accumulators are f32 regardless of input dtype
    (bf16 stats lose the max-trick's cancellation; matmuls still run on
    the inputs' dtype through the MXU with f32 accumulation).
    """
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)
    m_blk = jnp.max(scores, axis=-1)  # [B, H, Tq]
    p = jnp.exp(scores - m_blk[..., None])
    if mask is not None:
        # rows with no valid key: m_blk == _NEG_BIG and p would be exp(0)=1
        p = jnp.where(mask[None, None], p, 0.0)
    p_sum = jnp.sum(p, axis=-1)
    # PV runs on the inputs' dtype (bf16 MXU path) with f32 accumulation;
    # only the stats (m, l) and the running output stay f32
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_blk, p_sum, pv


def _accumulate(acc, m_blk, p_sum, pv):
    """Fold one block's (max, sum, numerator) into the running triple."""
    o, m, l = acc
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)  # rescale old accumulators
    beta = jnp.exp(m_blk - m_new)  # rescale this block
    l_new = l * alpha + p_sum * beta
    o_new = (
        o * alpha.transpose(0, 2, 1)[..., None]
        + pv * beta.transpose(0, 2, 1)[..., None]
    )
    return o_new, m_new, l_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    bidirectional: bool = False,
) -> jax.Array:
    """Exact attention over sequence shards rotating on a ring.

    Call inside shard_map with q/k/v sharded [B, T_local, H, D] along the
    sequence axis `axis_name`. Returns the local output shard.

    `bidirectional=True` rotates K/V both ways simultaneously and processes
    two blocks per hop: same total traffic, half the sequential hops, and
    both ICI directions of a physical ring in use. Falls back to the
    one-way ring for n <= 2 (nothing to overlap).
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    q_pos = me * t_loc + jnp.arange(t_loc)  # global query positions

    def block_mask(k_blk):
        if not causal:
            return None
        k_pos = k_blk * t_loc + jnp.arange(t_loc)
        return k_pos[None, :] <= q_pos[:, None]  # [Tq, Tk]

    o0 = jnp.zeros(q.shape, jnp.float32)  # f32 accumulators (see _block_attend)
    m0 = jnp.full((b, h, t_loc), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, t_loc), jnp.float32)

    # send my k/v block to the PREVIOUS device each hop: after s hops,
    # device i holds key block (i + s) mod n
    perm_fwd = [(j, (j - 1) % n) for j in range(n)]

    if not bidirectional or n <= 2:

        def hop(carry, s):
            o, m, l, k_cur, v_cur = carry
            m_blk, p_sum, pv = _block_attend(
                q, k_cur, v_cur, block_mask((me + s) % n), scale
            )
            acc = _accumulate((o, m, l), m_blk, p_sum, pv)
            # uniform rotation every hop keeps the loop body identical for
            # XLA (the final hop's permute returns k/v home)
            k_nxt = lax.ppermute(k_cur, axis_name, perm_fwd)
            v_nxt = lax.ppermute(v_cur, axis_name, perm_fwd)
            return (*acc, k_nxt, v_nxt), None

        # scan (not fori_loop): reverse-mode AD must flow through the ring
        # for training; ppermute transposes to the inverse rotation
        (o, m, l, _, _), _ = lax.scan(hop, (o0, m0, l0, k, v), jnp.arange(n))
    else:
        perm_bwd = [(j, (j + 1) % n) for j in range(n)]
        # own block first (no comm), then ceil((n-1)/2) two-block hops
        acc = _accumulate(
            (o0, m0, l0), *_block_attend(q, k, v, block_mask(me), scale)
        )
        n_hops = (n - 1 + 1) // 2
        # offsets +s (fwd) and -s (bwd) cover 1..n-1; for even n the offset
        # n/2 arrives on both streams — drop the bwd duplicate at s = n/2
        use_bwd = np.ones(n_hops, bool)
        if n % 2 == 0:
            use_bwd[-1] = False

        def hop2(carry, xs):
            s, bwd_ok = xs
            o, m, l, k_f, v_f, k_b, v_b = carry
            k_f = lax.ppermute(k_f, axis_name, perm_fwd)
            v_f = lax.ppermute(v_f, axis_name, perm_fwd)
            k_b = lax.ppermute(k_b, axis_name, perm_bwd)
            v_b = lax.ppermute(v_b, axis_name, perm_bwd)
            acc = _accumulate(
                (o, m, l),
                *_block_attend(q, k_f, v_f, block_mask((me + s) % n), scale),
            )
            m_blk, p_sum, pv = _block_attend(
                q, k_b, v_b, block_mask((me - s) % n), scale
            )
            # mask the duplicate block to a no-op contribution
            m_blk = jnp.where(bwd_ok, m_blk, _NEG_BIG)
            p_sum = jnp.where(bwd_ok, p_sum, 0.0)
            pv = jnp.where(bwd_ok, pv, 0.0)
            acc = _accumulate(acc, m_blk, p_sum, pv)
            return (*acc, k_f, v_f, k_b, v_b), None

        (o, m, l, *_), _ = lax.scan(
            hop2,
            (*acc, k, v, k, v),
            (jnp.arange(1, n_hops + 1), jnp.asarray(use_bwd)),
        )
    # causal guarantees >= 1 valid key per query (its own position), so l > 0
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Single-device reference: exact softmax attention, [B, T, H, D]."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    # f32 softmax regardless of input dtype (matches the ring/flash paths)
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def make_seq_mesh(num_shards: Optional[int] = None) -> Mesh:
    """1-D sequence-parallel mesh (axis 'seq')."""
    from .mesh import make_mesh

    return make_mesh(num_workers=num_shards, axis_name=SEQ_AXIS)


def make_ring_attention(
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    bidirectional: bool = False,
):
    """Jitted sequence-sharded attention: (q, k, v) [B, T, H, D] global ->
    [B, T, H, D] global, T sharded over the mesh axis."""
    mapped = jax.shard_map(
        partial(
            ring_attention,
            axis_name=axis_name,
            causal=causal,
            bidirectional=bidirectional,
        ),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )
    return jax.jit(mapped)


def shard_sequence(x: jax.Array, mesh: Mesh, axis_name: str = SEQ_AXIS):
    """Place [B, T, ...] with T sharded along the mesh axis."""
    return jax.device_put(x, NamedSharding(mesh, P(None, axis_name)))
