"""Mixture-of-Experts with expert parallelism (all_to_all dispatch).

Absent from the reference (SURVEY.md section 2: "TP / PP / SP / EP / CP ...
absent"); built here to complete the mesh's parallelism axes. The design is
the Switch-Transformer / Mesh-TensorFlow formulation mapped onto XLA
collectives:

- every block's dense MLP is replaced by E experts (stacked [E, D, M] /
  [E, M, D] weights, sharded over the `expert` mesh axis — each device owns
  E/n experts);
- the batch is sharded over the SAME axis (the expert axis doubles as data
  parallelism outside the MoE region);
- top-1 gating with capacity C = ceil(tokens_local * capacity_factor / E):
  per (token, expert) dispatch/combine tensors built with a one-hot cumsum
  rank (overflowing tokens are dropped — they ride the residual only, the
  standard Switch behavior);
- dispatch: einsum to [E, C, D] -> `lax.all_to_all` (split E over devices,
  concatenate senders) -> [E/n, n*C, D] expert compute -> all_to_all back
  -> combine-weighted sum. Two all_to_alls per MoE layer, both on ICI.
- a Switch-style load-balance auxiliary loss (E * sum f_e p_e) is returned
  alongside the task loss.

Gradients: same shard_map AD rule as tp.py/pp.py — each shard returns its
LOCAL loss; AD computes exact grads of the sum over shards; differentiate
local/n, then psum the replicated leaves (all_to_all's transpose is
all_to_all, which is exact under this convention).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, TYPE_CHECKING

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.metrics import next_token_nll
from .tp import opt_state_specs

if TYPE_CHECKING:  # pragma: no cover
    from ..models.transformer import TransformerConfig

EP_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """MoE knobs layered on top of a TransformerConfig."""

    num_experts: int = 8
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    # 1 = Switch routing; 2 = GShard-style top-2 (renormalized gates,
    # second choices queue behind first choices for capacity slots)
    top_k: int = 1

    def __post_init__(self):
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2, got {self.top_k}")


def make_ep_mesh(
    num_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D expert-parallel mesh (axis 'expert')."""
    from .mesh import make_mesh

    return make_mesh(num_workers=num_shards, devices=devices, axis_name=EP_AXIS)


def init_moe_params(
    cfg: "TransformerConfig", moe: MoEConfig, key: jax.Array
) -> Dict:
    """Transformer params with every block's dense MLP replaced by a gate
    + stacked expert weights."""
    from ..models.transformer import init_transformer

    params = init_transformer(cfg, key)
    mlp_dim = cfg.dim * cfg.mlp_ratio
    e = moe.num_experts
    for i, blk in enumerate(params["blocks"]):
        bk = jax.random.split(jax.random.fold_in(key, 1000 + i), 3)
        del blk["w_up"], blk["w_down"]
        scale = 1.0 / (cfg.dim ** 0.5)
        blk["wg"] = (jax.random.normal(bk[0], (cfg.dim, e)) * scale).astype(
            cfg.dtype
        )
        blk["w_up_e"] = (
            jax.random.normal(bk[1], (e, cfg.dim, mlp_dim)) * scale
        ).astype(cfg.dtype)
        blk["w_down_e"] = (
            jax.random.normal(bk[2], (e, mlp_dim, cfg.dim)) * (1.0 / mlp_dim ** 0.5)
        ).astype(cfg.dtype)
    return params


def moe_param_specs(cfg: "TransformerConfig", axis: str = EP_AXIS) -> Dict:
    blk = {
        "ln1": P(),
        "wqkv": P(),
        "wo": P(),
        "ln2": P(),
        "wg": P(),
        "w_up_e": P(axis),
        "w_down_e": P(axis),
    }
    return {
        "embed": P(),
        "pos_embed": P(),
        "out_norm": P(),
        "blocks": [dict(blk) for _ in range(cfg.depth)],
    }


def shard_params_moe(
    cfg: "TransformerConfig", params: Dict, mesh: Mesh, axis: str = EP_AXIS
) -> Dict:
    n = mesh.shape[axis]
    e = params["blocks"][0]["w_up_e"].shape[0]
    if e % n:
        raise ValueError(f"{e} experts not divisible by {n} expert shards")
    from .mesh import place_on_mesh

    return place_on_mesh(params, mesh, moe_param_specs(cfg, axis))


def _choice_dispatch(onehot, capacity, offset):
    """Queue one routing choice into capacity slots.

    onehot [N, E]; offset [E] = slots already taken per expert by earlier
    (higher-priority) choices. Returns the [N, E, C] dispatch tensor
    (1.0 where a token owns a slot; overflow rows are all-zero).
    """
    rank = jnp.cumsum(onehot, axis=0) * onehot - onehot  # [N, E] within-choice
    rank = rank + offset[None, :] * onehot
    kept = (rank < capacity) * onehot
    pos = jax.nn.one_hot(
        jnp.sum(rank * onehot, axis=-1), capacity, dtype=jnp.float32
    )  # [N, C]
    return kept[:, :, None] * pos[:, None, :]


def _gate_and_dispatch(x2d, wg, capacity, top_k: int = 1):
    """Top-1 (Switch) or top-2 (GShard) gating over flat tokens [N, D].

    Returns (dispatch [N, E, C] float {0,1}, combine [N, E, C], aux scalar).
    For top-2, gates are renormalized over the two choices and second
    choices queue behind ALL first choices for an expert's capacity slots.
    """
    logits = x2d @ wg  # [N, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    e = wg.shape[-1]

    expert1 = jnp.argmax(probs, axis=-1)  # [N]
    gate1 = jnp.take_along_axis(probs, expert1[:, None], axis=-1)[:, 0]
    onehot1 = jax.nn.one_hot(expert1, e, dtype=jnp.float32)  # [N, E]
    dispatch = _choice_dispatch(onehot1, capacity, jnp.zeros((e,)))  # [N,E,C]

    if top_k == 2:
        probs2 = probs * (1.0 - onehot1)  # mask the first choice
        expert2 = jnp.argmax(probs2, axis=-1)
        gate2 = jnp.take_along_axis(probs, expert2[:, None], axis=-1)[:, 0]
        onehot2 = jax.nn.one_hot(expert2, e, dtype=jnp.float32)
        # second choices queue behind every first choice (capped at C)
        taken = jnp.minimum(jnp.sum(onehot1, axis=0), capacity)
        dispatch2 = _choice_dispatch(onehot2, capacity, taken)
        # renormalize over the two choices (dropped choices contribute 0)
        denom = gate1 + gate2 + 1e-9
        combine = (
            dispatch * (gate1 / denom)[:, None, None]
            + dispatch2 * (gate2 / denom)[:, None, None]
        )
        dispatch = dispatch + dispatch2
    else:
        combine = dispatch * gate1[:, None, None]

    # aux load-balance loss on first-choice assignment (Switch form):
    # E * sum_e (fraction routed to e) * (mean prob of e)
    f = jnp.mean(onehot1, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(f * p)
    return dispatch, combine, aux


def moe_mlp_local(h, blk, moe: MoEConfig, axis_name: Optional[str]):
    """MoE MLP on local tokens h [B, T, D]; returns ([B, T, D], aux).

    With axis_name=None this is the single-device (all experts local)
    oracle; inside shard_map the two all_to_alls route tokens to the
    devices owning their experts and back.
    """
    b, t, d = h.shape
    x2d = h.reshape(b * t, d)
    e = moe.num_experts
    capacity = int(np.ceil(b * t * moe.top_k * moe.capacity_factor / e))
    # cast at use: params may be stored f32 while activations run bf16
    dispatch, combine, aux = _gate_and_dispatch(
        x2d, blk["wg"].astype(h.dtype), capacity, top_k=moe.top_k
    )
    # gating runs in f32; the dispatch/combine one-hots drop back to the
    # activation dtype so the expert matmuls stay on the bf16 path
    dispatch = dispatch.astype(h.dtype)
    combine = combine.astype(h.dtype)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x2d)  # [E, C, D]

    if axis_name is not None:
        # to expert owners: split E, concat senders' capacity slots
        expert_in = lax.all_to_all(
            expert_in, axis_name, split_axis=0, concat_axis=1, tiled=True
        )  # [E/n, n*C, D]
    w_up = blk["w_up_e"].astype(h.dtype)  # local experts, compute dtype
    w_down = blk["w_down_e"].astype(h.dtype)
    expert_out = jnp.einsum(
        "ecm,emd->ecd",
        jax.nn.gelu(jnp.einsum("ecd,edm->ecm", expert_in, w_up)),
        w_down,
    )
    if axis_name is not None:
        # back to token owners
        expert_out = lax.all_to_all(
            expert_out, axis_name, split_axis=1, concat_axis=0, tiled=True
        )  # [E, C, D]

    out = jnp.einsum("nec,ecd->nd", combine, expert_out)
    return out.reshape(b, t, d).astype(h.dtype), aux


def apply_moe_transformer(
    cfg: "TransformerConfig",
    moe: MoEConfig,
    params: Dict,
    tokens: jax.Array,  # int32 [B_local, T_local]
    axis_name: Optional[str] = None,
    seq_axis_name: Optional[str] = None,
) -> tuple:
    """Forward -> (logits [B_local, T_local, vocab], mean aux loss).

    `seq_axis_name` composes expert parallelism with sequence parallelism
    (parallel/ep_sp.py): attention runs on the ring/Ulysses over that axis
    and positions index globally, while the MoE dispatch all_to_alls stay
    on the expert axis — the two collectives touch orthogonal mesh
    dimensions, so neither needs to know about the other."""
    from ..models.transformer import (
        _rms_norm,
        select_attention,
        transformer_block,
    )

    b, t = tokens.shape
    if seq_axis_name is not None:
        pos = lax.axis_index(seq_axis_name) * t + jnp.arange(t)
    else:
        pos = jnp.arange(t)
    x = params["embed"][tokens] + params["pos_embed"][pos][None]
    attend = select_attention(cfg, seq_axis_name)

    def block_fn(x, blk):
        # transformer_block calls mlp(h) exactly once; the cell carries the
        # aux loss out of the callback and returns it as a proper output
        # (so jax.checkpoint can wrap the whole block)
        aux_cell = []

        def mlp(h):
            out, aux = moe_mlp_local(h, blk, moe, axis_name)
            aux_cell.append(aux)
            return out

        x = transformer_block(cfg, x, blk, attend, mlp=mlp)
        return x, aux_cell[0]

    if cfg.remat:
        block_fn = jax.checkpoint(block_fn)

    aux_total = 0.0
    for blk in params["blocks"]:
        x, aux = block_fn(x, blk)
        aux_total = aux_total + aux

    cd = cfg.effective_compute_dtype
    xf = _rms_norm(x.astype(cd), params["out_norm"].astype(cd))
    logits = xf @ params["embed"].T.astype(cd)
    return logits, aux_total / cfg.depth


def make_moe_train_step(
    cfg: "TransformerConfig",
    moe: MoEConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = EP_AXIS,
    donate: bool = True,
):
    """Jitted MoE LM train step: (params, opt_state, tokens [B, T]) ->
    (params, opt_state, loss, aux). Expert weights + batch sharded over the
    expert axis; everything else replicated (the axis is simultaneously the
    data-parallel axis)."""
    specs_tree = moe_param_specs(cfg, axis_name)

    def shard_fn(params, opt_state, tokens):
        n = lax.axis_size(axis_name)

        def loss_fn(p):
            logits, aux = apply_moe_transformer(cfg, moe, p, tokens, axis_name)
            task = next_token_nll(logits, tokens)
            local = task + moe.aux_loss_weight * aux
            # sum-over-shards AD rule (see module docstring): local/n
            return local / n, (task, aux)

        (_, (task_loss, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        grads = jax.tree.map(
            lambda g, s: lax.psum(g, axis_name) if s == P() else g,
            grads,
            specs_tree,
            is_leaf=lambda x: isinstance(x, P),
        )
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return (
            new_params,
            new_opt,
            lax.pmean(task_loss, axis_name),
            lax.pmean(aux, axis_name),
        )

    shapes = _moe_param_shapes(cfg, moe)
    opt_specs = opt_state_specs(jax.eval_shape(tx.init, shapes), shapes, specs_tree)
    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(specs_tree, opt_specs, P(axis_name)),
        out_specs=(specs_tree, opt_specs, P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())


def _moe_param_shapes(cfg: "TransformerConfig", moe: MoEConfig) -> Dict:
    return jax.eval_shape(
        lambda: init_moe_params(cfg, moe, jax.random.key(0))
    )


def init_moe_state(
    cfg: "TransformerConfig",
    moe: MoEConfig,
    tx: optax.GradientTransformation,
    key: jax.Array,
    mesh: Mesh,
    axis_name: str = EP_AXIS,
):
    """Init (params, opt_state) placed with EP shardings."""
    params = shard_params_moe(
        cfg, init_moe_params(cfg, moe, key), mesh, axis_name
    )
    from .mesh import place_on_mesh

    opt_state = tx.init(params)
    specs = opt_state_specs(opt_state, params, moe_param_specs(cfg, axis_name))
    return params, place_on_mesh(opt_state, mesh, specs)


def shard_moe_batch(tokens, mesh: Mesh, axis_name: str = EP_AXIS):
    """[B_global, T] -> B sharded over the expert axis."""
    return jax.device_put(tokens, NamedSharding(mesh, P(axis_name)))
