"""Ulysses (all-to-all) sequence parallelism — the alternative long-context
scheme to the ring (parallel/ring_attention.py).

DeepSpeed-Ulysses layout: activations are sequence-sharded [B, T/n, H, D]
everywhere except inside attention. Two `lax.all_to_all`s per attention
call re-shard seq->heads and back:

    [B, T/n, H, D] --a2a(split H, concat T)--> [B, T, H/n, D]
        full softmax attention over the COMPLETE sequence, local heads
    [B, T, H/n, D] --a2a(split T, concat H)--> [B, T/n, H, D]

versus the ring's n-1 neighbor hops: Ulysses moves each token exactly
twice (O(T·D/n) per device per a2a, head-count must divide the axis) while
the ring moves K/V n-1 times but keeps heads whole. On a TPU torus the
ring rides neighbor ICI links; Ulysses uses the switched all_to_all —
which one wins is sequence-length- and topology-dependent, so both are
first-class here and share the same transformer (TransformerConfig.
sp_attention selects the scheme; everything else is identical).

The reference has no sequence dimension at all (SURVEY.md section 5
"Long-context / sequence parallelism — absent"); both schemes are
capability extensions with no counterpart to cite.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .ring_attention import SEQ_AXIS, full_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "naive",
) -> jax.Array:
    """Exact attention over sequence shards via two all_to_alls.

    Call inside shard_map with q/k/v sharded [B, T_local, H, D] along the
    sequence axis. Requires H % axis_size == 0. Returns the local output
    shard [B, T_local, H, D].

    impl="flash" runs the Pallas blockwise kernel on the gathered
    full-sequence/local-heads layout (attention here is an ordinary
    single-chip call — the a2a already localized it), so the [T, T] score
    matrix is never materialized.
    """
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"heads {h} not divisible by sequence axis size {n}")

    def seq_to_heads(x):  # [B, T/n, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # [B, T, H/n, D] -> [B, T/n, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    if impl == "flash":
        from ..ops.flash_attention import flash_attention as attend
    else:
        attend = full_attention
    o = attend(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
        causal=causal, scale=scale,
    )
    return heads_to_seq(o)


def make_ulysses_attention(
    mesh: Mesh, axis_name: str = SEQ_AXIS, causal: bool = False
):
    """Jitted sequence-sharded attention: (q, k, v) [B, T, H, D] global ->
    [B, T, H, D] global, T sharded over the mesh axis (same contract as
    ring_attention.make_ring_attention)."""
    mapped = jax.shard_map(
        partial(ulysses_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )
    return jax.jit(mapped)
