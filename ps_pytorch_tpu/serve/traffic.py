"""Open-loop synthetic traffic for the serving engine.

Open-loop means arrivals are a fixed schedule (Poisson process at
``rate_rps``), independent of completions — the generator never waits
for the engine, so queueing delay shows up in the latency tail exactly
the way overload does in production. Everything is seeded: the same
TrafficConfig replays the same request set (arrival times, prompt
lengths, prompt tokens, new-token budgets) bit-for-bit, which is what
lets the bench leg and the smoke leg assert on the result.

``run_open_loop`` drives an engine against the schedule on a real or
virtual clock and reduces the completions to the serving headline:
tokens/sec plus p50/p99 per-token latency (the per-token series is
time-to-first-token for a request's first token, inter-token gap for
the rest — the tail therefore covers prefill, queueing, AND rollover
drains).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .engine import ServingEngine
from .scheduler import Completion, Request


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    n_requests: int = 32
    rate_rps: float = 100.0      # Poisson arrival rate
    prompt_len_min: int = 4
    prompt_len_max: int = 16
    new_tokens_min: int = 8
    new_tokens_max: int = 32
    vocab_size: int = 256
    seed: int = 0
    # seeded bursty mode (overload drills): (rate_mult, start_s, dur_s)
    # square-wave rate modulation — arrivals inside [start, start+dur)
    # come at rate_rps * rate_mult, outside at rate_rps. None = plain
    # Poisson (bit-identical to the pre-spike generator: same rng draw
    # order).
    spike: Optional[tuple] = None
    # relative per-request deadline: each request's absolute deadline is
    # arrival_s + deadline_s on the open-loop clock. None = no deadlines.
    deadline_s: Optional[float] = None


def make_requests(
    tc: TrafficConfig,
    prompt_source: Optional[Callable[[np.random.RandomState, int], np.ndarray]] = None,
) -> List[Request]:
    """The deterministic request set for a TrafficConfig.

    ``prompt_source(rng, length) -> int32 [length]`` overrides prompt
    token generation (cli/serve feeds held-out Markov-chain walks so the
    served model sees its training distribution); the default is uniform
    random tokens."""
    if tc.n_requests < 1:
        raise ValueError("n_requests must be >= 1")
    if tc.rate_rps <= 0:
        raise ValueError("rate_rps must be > 0")
    if not 1 <= tc.prompt_len_min <= tc.prompt_len_max:
        raise ValueError("need 1 <= prompt_len_min <= prompt_len_max")
    if not 1 <= tc.new_tokens_min <= tc.new_tokens_max:
        raise ValueError("need 1 <= new_tokens_min <= new_tokens_max")
    if tc.deadline_s is not None and tc.deadline_s <= 0:
        raise ValueError("deadline_s must be > 0 (None disables)")
    rng = np.random.RandomState(tc.seed)
    if tc.spike is None:
        # Poisson process: exponential inter-arrival gaps at rate_rps
        gaps = rng.exponential(1.0 / tc.rate_rps, size=tc.n_requests)
        arrivals = np.cumsum(gaps)
    else:
        mult, start_s, dur_s = (float(x) for x in tc.spike)
        if mult <= 0 or start_s < 0 or dur_s <= 0:
            raise ValueError(
                f"spike needs rate_mult > 0, start_s >= 0, dur_s > 0, "
                f"got {tc.spike!r}"
            )
        # square-wave rate modulation: each gap is drawn at the rate in
        # force when it begins — a seeded two-state renewal process, so
        # the overload drill replays the identical burst bit-for-bit
        t = 0.0
        arrivals = np.empty(tc.n_requests, np.float64)
        for i in range(tc.n_requests):
            rate = tc.rate_rps * (
                mult if start_s <= t < start_s + dur_s else 1.0
            )
            t += float(rng.exponential(1.0 / rate))
            arrivals[i] = t
    out: List[Request] = []
    for rid in range(tc.n_requests):
        plen = int(rng.randint(tc.prompt_len_min, tc.prompt_len_max + 1))
        if prompt_source is not None:
            prompt = np.asarray(prompt_source(rng, plen), np.int32)
        else:
            prompt = rng.randint(0, tc.vocab_size, size=plen).astype(np.int32)
        arrival = float(arrivals[rid])
        out.append(Request(
            rid=rid,
            prompt=prompt,
            max_new_tokens=int(
                rng.randint(tc.new_tokens_min, tc.new_tokens_max + 1)
            ),
            arrival_s=arrival,
            deadline_s=(
                arrival + tc.deadline_s if tc.deadline_s is not None
                else None
            ),
        ))
    return out


def run_open_loop(
    engine: ServingEngine,
    requests: Sequence[Request],
    poll_interval_s: float = 0.0,
    clock: Optional[Callable[[], float]] = None,
) -> Dict:
    """Serve a fixed arrival schedule to completion; returns the summary.

    ``poll_interval_s`` > 0 polls the engine's checkpoint directory for
    a hot rollover at that cadence (drain-then-swap — see engine).
    ``clock`` defaults to time.perf_counter, rebased so the schedule's
    t=0 is the call time; the engine idles (sleeps) until the next
    arrival when nothing is in flight."""
    # closed-loop requests (arrival_s=None) are welcome in an open-loop
    # drive: they simply arrive at the schedule's t=0
    requests = [
        r if r.arrival_s is not None else dataclasses.replace(r, arrival_s=0.0)
        for r in requests
    ]
    requests = sorted(requests, key=lambda r: r.arrival_s)
    base = (clock or time.perf_counter)()
    now = lambda: (clock or time.perf_counter)() - base
    # arrival times and the engine's latency clock must share a timeline
    # (TTFT counts from ARRIVAL — queueing delay is part of serving)
    engine.clock = now
    t0 = now()
    pending = list(requests)
    completions: List[Completion] = []
    last_poll = t0
    while pending or not engine.scheduler.idle or engine.draining:
        t = now()
        while pending and pending[0].arrival_s <= t:
            engine.submit(pending.pop(0))
        if poll_interval_s > 0 and t - last_poll >= poll_interval_s:
            last_poll = t
            engine.poll_rollover()
        if engine.scheduler.idle and not engine.draining and pending:
            if clock is None:
                # open-loop idle: nothing to decode until the next arrival
                time.sleep(min(pending[0].arrival_s - t, 0.01))
            else:
                # injected (virtual) clock: real sleep cannot advance it —
                # fast-forward by submitting the next arrival immediately
                # (arrival ORDER is preserved; gaps collapse)
                engine.submit(pending.pop(0))
            continue
        completions.extend(engine.tick())
    elapsed = now() - t0
    return summarize(completions, elapsed, engine)


def summarize(completions: Sequence[Completion], elapsed_s: float,
              engine: Optional[ServingEngine] = None) -> Dict:
    """Reduce completions to the serving headline record.

    Alongside raw tokens/sec: GOODPUT (tokens of completions that met
    their deadline — the number overload actually degrades; without
    deadlines every completed token is good by definition) and the
    lifecycle counts (shed/expired from the engine's ledger, so the
    record accounts for every submitted request, not just the winners).
    The TTFT percentiles are over ADMITTED requests that emitted a first
    token: completions AND mid-decode expiries (whose TTFT the scheduler
    preserves on the Expired record) — dropping the latter would hide
    exactly the worst admitted waits from the tail under overload. Shed
    and pre-admission expiries never produce a first token."""
    latencies = np.asarray(
        [lat for c in completions for lat in c.latencies_s], np.float64
    )
    ttft = np.asarray(
        [c.latencies_s[0] for c in completions if c.latencies_s]
        + (
            [e.ttft_s for e in engine.expired if e.ttft_s is not None]
            if engine is not None else []
        ),
        np.float64,
    )
    n_tokens = int(sum(len(c.tokens) for c in completions))
    good_tokens = int(sum(
        len(c.tokens) for c in completions if c.met_deadline
    ))
    out = {
        "requests_completed": len(completions),
        "new_tokens": n_tokens,
        "elapsed_s": round(float(elapsed_s), 6),
        "tokens_per_sec": round(n_tokens / elapsed_s, 2) if elapsed_s > 0 else None,
        "goodput_tokens": good_tokens,
        "goodput_tokens_per_sec": (
            round(good_tokens / elapsed_s, 2) if elapsed_s > 0 else None
        ),
        "p50_token_latency_s": _pct(latencies, 50),
        "p99_token_latency_s": _pct(latencies, 99),
        "p50_ttft_s": _pct(ttft, 50),
        "p99_ttft_s": _pct(ttft, 99),
    }
    # TTFT decomposition (scheduler.Completion): queue + prefill == TTFT
    # per request, so a fat TTFT tail is attributable — queueing delay
    # (admission pressure, rollover drains) vs prefill cost. decode_s is
    # the whole inter-token tail of one request, not a per-token gap.
    for comp in ("queue_s", "prefill_s", "decode_s"):
        xs = np.asarray([getattr(c, comp) for c in completions], np.float64)
        out[f"p50_{comp}"] = _pct(xs, 50)
        out[f"p99_{comp}"] = _pct(xs, 99)
    if engine is not None:
        out["weights_step"] = engine.step
        out["rollovers"] = list(engine.rollovers)
        out["rollover_aborts"] = list(engine.rollover_aborts)
        # the lifecycle counters (warmup's negative rids excluded): every
        # submitted request lands in exactly one bucket — the
        # zero-silent-drops audit the chaos smoke runs on this record.
        # Counters, not the bounded per-request ledger: totals must
        # survive a long-lived server's ledger eviction.
        counts = engine.outcome_counts
        out["requests_submitted"] = sum(counts.values())
        out["requests_shed"] = counts["shed"]
        out["requests_expired"] = counts["expired"]
    return out


def _pct(xs: np.ndarray, q: float) -> Optional[float]:
    if xs.size == 0:
        return None
    return round(float(np.percentile(xs, q)), 6)
