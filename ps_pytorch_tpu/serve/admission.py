"""SLO-aware admission control with load shedding (ARCHITECTURE §7i).

The serving engine's front door under overload: when arrivals outrun
the decode capacity, the queue — not the decode step — eats the p99.
An unbounded queue converts a traffic spike into unbounded TTFT for
every later arrival; shedding at submit time converts the same spike
into bounded TTFT for the admitted and an explicit, evented refusal
for the rest.

``AdmissionController`` mirrors ``resilience.elastic.
AdaptiveMaskController``: pure host (no jax import — this module can
never add a sync to the request loop it governs), windowed statistics,
and every state change emits one structured JSONL event. The control
signal is the TTFT queue component the PR 8 tracer decomposed
(ARCHITECTURE §7g): the projected queue wait for a NEW arrival is

    projected_wait_s = queue_depth / drain_rate

where ``drain_rate`` is the admissions-per-second measured over the
last closed window — i.e. how fast the queue's head actually moved,
which already folds in slot count, decode speed, injected stalls, and
rollover drains. Policy, deliberately simple and deterministic (the
chaos suite drives it through ``FaultPlan``):

- ENTER shedding the moment a submit's projected wait exceeds the SLO
  budget (a submit-time decision — waiting for a window close would
  admit a whole window of doomed arrivals);
- while shedding, refuse arrivals subject to a bounded shed rate: at
  most ``shed_max_frac`` of a window's submits are shed, so a trickle
  always gets through and the drain-rate estimate keeps refreshing
  (a controller that sheds 100% can never observe recovery);
- EXIT shedding only after ``recover_windows`` consecutive window
  closes with projected wait under ``recover_frac`` x budget —
  hysteresis, so a queue hovering at the budget does not flap the
  controller every window.

The controller never observes device state and the engine applies its
decisions only at submit time, so a buggy controller can degrade
goodput but can never corrupt a decode: admitted requests flow through
the exact same scheduler/slot machinery as an uncontrolled engine.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

# projected waits are capped finite so the evidence fields stay valid
# JSON (a zero drain rate would otherwise project infinity)
_WAIT_CAP_S = 1e9


class AdmissionController:
    """Windowed submit-time load shedding against an SLO budget.

    The engine feeds it three signals, all on the scheduler clock:
    ``observe_tick(now, queue_depth)`` once per tick (rolls the window),
    ``record_admit(now)`` per admission (the drain-rate numerator), and
    ``offered(now, queue_depth)`` per submit — which returns
    ``(shed, projected_wait_s)``, the decision plus its evidence."""

    def __init__(
        self,
        slo_budget_s: float,
        window_s: float = 0.25,
        shed_max_frac: float = 0.9,
        recover_frac: float = 0.5,
        recover_windows: int = 2,
        event_sink: Optional[Callable[[dict], None]] = None,
    ):
        if slo_budget_s <= 0:
            raise ValueError(f"slo_budget_s must be > 0, got {slo_budget_s}")
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if not 0.0 < shed_max_frac <= 1.0:
            raise ValueError(
                f"shed_max_frac must be in (0, 1], got {shed_max_frac}"
            )
        if not 0.0 < recover_frac < 1.0:
            raise ValueError(
                f"recover_frac must be in (0, 1), got {recover_frac}"
            )
        if recover_windows < 1:
            raise ValueError(
                f"recover_windows must be >= 1, got {recover_windows}"
            )
        self.slo_budget_s = float(slo_budget_s)
        self.window_s = float(window_s)
        self.shed_max_frac = float(shed_max_frac)
        self.recover_frac = float(recover_frac)
        self.recover_windows = int(recover_windows)
        self.shedding = False
        self.shed_total = 0
        self.admitted_total = 0
        self.windows_closed = 0
        self.adaptations = 0          # shedding state flips
        self._sink = event_sink
        self._drain_rate: Optional[float] = None  # req/s, last closed window
        self._win_start: Optional[float] = None
        self._win_admits = 0
        self._win_submits = 0
        self._win_sheds = 0
        self._clean = 0               # consecutive recovered windows
        self._depth = 0               # queue depth at the last signal

    # ------------------------------------------------------------- signals
    def observe_tick(self, now_s: float, queue_depth: int) -> None:
        """Per-tick heartbeat: tracks queue depth and closes windows on
        schedule even when no submits arrive (recovery needs closes)."""
        self._roll(now_s, queue_depth)

    def record_admit(self, now_s: float) -> None:
        """One request left the queue for a slot — the drain-rate
        numerator."""
        self._win_admits += 1
        self.admitted_total += 1

    def offered(self, now_s: float, queue_depth: int) -> Tuple[bool, float]:
        """Submit-time decision for one arrival: (shed?, projected wait).
        The projected wait is the evidence either way — the engine puts
        it in the ``request_shed`` event."""
        self._roll(now_s, queue_depth)
        self._win_submits += 1
        projected = self.projected_wait_s(queue_depth)
        if not self.shedding and projected > self.slo_budget_s:
            self.shedding = True
            self._clean = 0
            self.adaptations += 1
            self._emit("shedding", projected)
        if (
            self.shedding
            and self._win_sheds + 1 <= self.shed_max_frac * self._win_submits
        ):
            self._win_sheds += 1
            self.shed_total += 1
            return True, projected
        return False, projected

    # ------------------------------------------------------------ modeling
    def projected_wait_s(self, queue_depth: int) -> float:
        """Expected queue wait for an arrival landing behind
        ``queue_depth`` requests, at the last closed window's drain rate.
        0.0 while no evidence exists (never shed before the first window
        of admissions) and for an empty queue (next free slot admits)."""
        if queue_depth <= 0 or self._drain_rate is None:
            return 0.0
        if self._drain_rate <= 0.0:
            return _WAIT_CAP_S
        return min(queue_depth / self._drain_rate, _WAIT_CAP_S)

    # ------------------------------------------------------------- windows
    def _roll(self, now_s: float, queue_depth: int) -> None:
        self._depth = int(queue_depth)
        if self._win_start is None:
            self._win_start = now_s
            return
        if now_s < self._win_start:
            # the clock was rebased under us (run_open_loop re-zeros the
            # engine clock at drive start): restart the window on the
            # new timeline instead of never closing again
            self._win_start = now_s
            self._win_admits = 0
            self._win_submits = 0
            self._win_sheds = 0
            return
        if now_s - self._win_start >= self.window_s:
            self._close(now_s)

    def _close(self, now_s: float) -> None:
        elapsed = max(now_s - self._win_start, 1e-9)
        if self._win_admits and elapsed <= 2.0 * self.window_s:
            # only a window that actually admitted updates the estimate
            # (an idle window carries no drain evidence, and a shedding
            # window's bounded leak-through keeps admits flowing), and
            # only a window that closed ON TIME: an engine that idled
            # through a traffic lull closes its open window at the next
            # signal with lull-inflated elapsed time, and dividing the
            # pre-lull admits by it would collapse the rate estimate and
            # shed the first healthy burst after the lull
            self._drain_rate = self._win_admits / elapsed
        self.windows_closed += 1
        if self.shedding:
            projected = self.projected_wait_s(self._depth)
            if projected <= self.recover_frac * self.slo_budget_s:
                self._clean += 1
                if self._clean >= self.recover_windows:
                    self.shedding = False
                    self._clean = 0
                    self.adaptations += 1
                    self._emit("admitting", projected)
            else:
                self._clean = 0
        self._win_start = now_s
        self._win_admits = 0
        self._win_submits = 0
        self._win_sheds = 0

    def _emit(self, state: str, projected: float) -> None:
        if self._sink is not None:
            self._sink({
                "kind": "admission_adapt",
                "state": state,
                "projected_wait_s": round(projected, 6),
                "queue_depth": self._depth,
                "window_submits": self._win_submits,
                "window_sheds": self._win_sheds,
                "windows": self.windows_closed,
                "slo_budget_s": self.slo_budget_s,
            })
