"""Continuous-batching slot scheduler — pure host bookkeeping, no jax.

The device side of the serving engine is a fixed pool of ``n_slots``
KV-cache slots stepped by ONE compiled decode program; this module
decides which request occupies which slot at each tick:

- ``submit`` queues a request (FIFO; shape-validated against the pool
  geometry at submit time, so a too-long request fails loudly at the
  front door instead of corrupting a slot);
- ``admit`` pops queued requests into free slots (lowest slot id first —
  deterministic, so a replay of the same arrival order reproduces the
  same slot assignment bit-for-bit);
- ``record_token`` appends one generated token + its latency to the
  slot's in-flight state and reports whether the request just finished
  (its ``max_new_tokens`` reached);
- ``evict`` frees a finished slot and returns the ``Completion``;
- ``expire_queued`` / ``expire_slot`` terminate requests whose deadline
  passed — in the queue before admission, or mid-decode with partial
  tokens. An expired slot is freed exactly like an evicted one, so the
  next occupant's decode stays token-exact (the masked-write argument:
  every position the dead sequence scribbled is overwritten before it
  is first attended).

Slot lifecycle:  FREE -> (admit) -> ACTIVE -> (record_token x N,
last one finishing) -> FINISHED -> (evict) -> FREE, with a second exit
ACTIVE -> (expire_slot) -> FREE when the deadline passes mid-decode.
Eviction, expiry, and admission all happen between device steps, so a
slot freed at tick t is re-usable at tick t+1 with no recompilation —
static shapes, the masks do the rest (serve/engine.py).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Request:
    """One decode request: a prompt and a new-token budget."""

    rid: int
    prompt: np.ndarray           # int32 [prompt_len], prompt_len >= 1
    max_new_tokens: int
    # open-loop traffic: arrival time on the caller's clock (0.0 is a
    # legitimate instant). None = closed-loop request with no arrival —
    # TTFT is then measured from admission.
    arrival_s: Optional[float] = None
    # ABSOLUTE deadline on the same clock as arrival_s (the scheduler
    # clock). None = no deadline. A request whose deadline passes before
    # its budget is reached terminates as 'expired' — at submit, in the
    # queue, or mid-decode — never silently.
    deadline_s: Optional[float] = None


@dataclasses.dataclass
class Completion:
    """A finished request: generated tokens + per-token latencies."""

    rid: int
    prompt: np.ndarray
    tokens: List[int]
    # per-token wall-clock latency: tokens[0]'s entry is time-to-first-
    # token measured from arrival; later entries are inter-token gaps
    latencies_s: List[float]
    finished_s: float = 0.0
    # the checkpoint step whose weights generated this completion (the
    # drain-then-swap rollover rule means it is ONE step, never a mix)
    weights_step: Optional[int] = None
    # TTFT decomposition (ARCHITECTURE §7g): latencies_s[0] ==
    # queue_s + prefill_s by construction.
    #   queue_s   arrival -> admission (0.0 for closed-loop requests,
    #             whose TTFT base IS the admission instant)
    #   prefill_s admission -> first token emitted (covers the padded
    #             prefill AND the first decode step — the engine fuses
    #             them into one tick)
    #   decode_s  first token -> last token (the inter-token tail)
    queue_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0
    # the request's absolute deadline, carried through so goodput (tokens
    # completed WITHIN deadline) is computable from completions alone
    deadline_s: Optional[float] = None

    @property
    def met_deadline(self) -> bool:
        return self.deadline_s is None or self.finished_s <= self.deadline_s


@dataclasses.dataclass
class Expired:
    """A request whose deadline passed before completion. ``where`` names
    the lifecycle stage that observed the expiry: ``submit`` (deadline
    already past on arrival), ``queue`` (expired waiting for a slot), or
    ``decode`` (evicted mid-decode; ``tokens`` holds the partial
    output — generated, but never a Completion)."""

    rid: int
    where: str
    deadline_s: float
    expired_s: float
    tokens: List[int] = dataclasses.field(default_factory=list)
    # time-to-first-token, when the request got far enough to emit one
    # (where=decode only) — admitted-request TTFT statistics must count
    # these, or the worst admitted waits vanish from the percentiles
    ttft_s: Optional[float] = None


@dataclasses.dataclass
class _InFlight:
    request: Request
    slot: int
    tokens: List[int]
    latencies_s: List[float]
    last_token_s: float          # arrival at admission; then last emit
    admitted_s: float = 0.0      # admission instant (scheduler clock)
    first_token_s: Optional[float] = None


class SlotScheduler:
    """Admit/evict bookkeeping for a fixed pool of decode slots."""

    def __init__(self, n_slots: int, max_len: int, max_prompt_len: int):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if not 1 <= max_prompt_len <= max_len:
            raise ValueError(
                f"need 1 <= max_prompt_len ({max_prompt_len}) <= "
                f"max_len ({max_len})"
            )
        self.n_slots = n_slots
        self.max_len = max_len
        self.max_prompt_len = max_prompt_len
        self._free: List[int] = sorted(range(n_slots), reverse=True)
        self._queue: Deque[Request] = deque()
        self._inflight: Dict[int, _InFlight] = {}

    # ------------------------------------------------------------- intake
    def submit(self, request: Request) -> None:
        plen = int(request.prompt.shape[0])
        if plen < 1:
            raise ValueError(f"request {request.rid}: empty prompt")
        if plen > self.max_prompt_len:
            raise ValueError(
                f"request {request.rid}: prompt length {plen} exceeds "
                f"max_prompt_len {self.max_prompt_len}"
            )
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.rid}: max_new_tokens must be >= 1"
            )
        if plen + request.max_new_tokens > self.max_len:
            raise ValueError(
                f"request {request.rid}: prompt {plen} + new "
                f"{request.max_new_tokens} exceeds slot length "
                f"{self.max_len}"
            )
        self._queue.append(request)

    # ------------------------------------------------------------- expiry
    def expire_queued(self, now_s: float) -> List[Request]:
        """Remove and return queued requests whose deadline has passed
        (deadline <= now: the deadline instant itself is too late to
        start). Survivors keep their FIFO order."""
        expired = [
            r for r in self._queue
            if r.deadline_s is not None and r.deadline_s <= now_s
        ]
        if expired:
            dead = {id(r) for r in expired}
            self._queue = deque(
                r for r in self._queue if id(r) not in dead
            )
        return expired

    def expire_slot(self, slot: int, now_s: float) -> Expired:
        """Evict an in-flight request mid-decode because its deadline
        passed; the slot is freed for reuse exactly like a normal evict
        (the next occupant's prefill+decode overwrite every position the
        dead sequence wrote before it is first attended — token-exact by
        the same masked-write argument)."""
        inf = self._inflight.pop(slot)
        self._free.append(slot)
        self._free.sort(reverse=True)
        return Expired(
            rid=inf.request.rid,
            where="decode",
            deadline_s=float(inf.request.deadline_s),
            expired_s=now_s,
            tokens=list(inf.tokens),
            ttft_s=inf.latencies_s[0] if inf.latencies_s else None,
        )

    # ---------------------------------------------------------- admission
    def admit(self, now_s: float = 0.0) -> List[Tuple[int, Request]]:
        """Move queued requests into free slots (FIFO x lowest-slot-first);
        returns the (slot, request) pairs admitted this tick — the engine
        prefills exactly these."""
        admitted: List[Tuple[int, Request]] = []
        while self._queue and self._free:
            req = self._queue.popleft()
            slot = self._free.pop()
            # TTFT base: the request's ARRIVAL when it carries one on the
            # caller's clock (open-loop traffic — queueing delay counts,
            # and 0.0 is a legitimate arrival instant), else the
            # admission instant (closed-loop/default requests)
            self._inflight[slot] = _InFlight(
                request=req, slot=slot, tokens=[], latencies_s=[],
                last_token_s=(
                    req.arrival_s if req.arrival_s is not None else now_s
                ),
                admitted_s=now_s,
            )
            admitted.append((slot, req))
        return admitted

    # ------------------------------------------------------------- decode
    def record_token(self, slot: int, token: int, now_s: float) -> bool:
        """Append one generated token; True when the request just hit its
        new-token budget (caller evicts)."""
        inf = self._inflight[slot]
        if not inf.tokens:
            inf.first_token_s = now_s
        inf.tokens.append(int(token))
        inf.latencies_s.append(max(now_s - inf.last_token_s, 0.0))
        inf.last_token_s = now_s
        return len(inf.tokens) >= inf.request.max_new_tokens

    def evict(self, slot: int, now_s: float = 0.0,
              weights_step: Optional[int] = None) -> Completion:
        inf = self._inflight.pop(slot)
        self._free.append(slot)
        self._free.sort(reverse=True)
        # TTFT decomposition on the scheduler's own clock: the same
        # instants the latencies were measured with, so the components
        # sum exactly (queue + prefill == latencies_s[0]). The TTFT base
        # is max(admission, arrival): an injected-clock fast-forward
        # (traffic.run_open_loop) can admit BEFORE the nominal arrival,
        # and prefill must then count from the arrival the first-token
        # latency counts from, or the components would sum past it.
        arrival = (
            inf.request.arrival_s
            if inf.request.arrival_s is not None
            else inf.admitted_s
        )
        first = (
            inf.first_token_s if inf.first_token_s is not None else now_s
        )
        base = max(inf.admitted_s, arrival)
        return Completion(
            rid=inf.request.rid,
            prompt=inf.request.prompt,
            tokens=inf.tokens,
            latencies_s=inf.latencies_s,
            finished_s=now_s,
            weights_step=weights_step,
            queue_s=max(inf.admitted_s - arrival, 0.0),
            prefill_s=max(first - base, 0.0),
            decode_s=max(inf.last_token_s - first, 0.0),
            deadline_s=inf.request.deadline_s,
        )

    # ----------------------------------------------------------- queries
    @property
    def active_slots(self) -> Sequence[int]:
        return sorted(self._inflight)

    def request_in(self, slot: int) -> Request:
        return self._inflight[slot].request

    def tokens_in(self, slot: int) -> List[int]:
        return self._inflight[slot].tokens

    @property
    def n_inflight(self) -> int:
        return len(self._inflight)

    @property
    def n_queued(self) -> int:
        return len(self._queue)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def idle(self) -> bool:
        return not self._inflight and not self._queue
