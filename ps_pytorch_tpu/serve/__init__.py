"""Serving subsystem: continuous-batching decode on the mesh with hot
checkpoint rollover (ARCHITECTURE §7e).

- ``engine``: the slot-pool decode engine (one compiled prefill + one
  compiled decode step, FlatVector weights, drain-then-swap rollover);
- ``scheduler``: host-side admit/evict slot bookkeeping;
- ``kv``: the pooled KV cache (compute-dtype or int8 block-scale);
- ``traffic``: seeded open-loop traffic + the latency summary.

Entry point: ``python -m ps_pytorch_tpu.cli.serve``.
"""

from .engine import (
    ServeConfig,
    ServingEngine,
    make_decode_step,
    make_prefill_step,
)
from .kv import init_kv_pool
from .scheduler import Completion, Request, SlotScheduler
from .traffic import TrafficConfig, make_requests, run_open_loop, summarize

__all__ = [
    "Completion",
    "Request",
    "ServeConfig",
    "ServingEngine",
    "SlotScheduler",
    "TrafficConfig",
    "init_kv_pool",
    "make_decode_step",
    "make_prefill_step",
    "make_requests",
    "run_open_loop",
    "summarize",
]
