"""Serving subsystem: continuous-batching decode on the mesh with hot
checkpoint rollover and SLO-aware resilience (ARCHITECTURE §7e, §7i).

- ``engine``: the slot-pool decode engine (one compiled prefill + one
  compiled decode step, FlatVector weights, drain-then-swap rollover
  hardened with swap-time re-reads and a drain watchdog);
- ``scheduler``: host-side admit/evict/expire slot bookkeeping with
  per-request deadlines;
- ``admission``: SLO-aware admission control (windowed projected-wait
  load shedding, hysteretic recovery);
- ``kv``: the pooled KV cache (compute-dtype or int8 block-scale);
- ``traffic``: seeded open-loop traffic (Poisson or square-wave burst)
  + the latency/goodput summary.

Entry point: ``python -m ps_pytorch_tpu.cli.serve``.
"""

from .admission import AdmissionController
from .engine import (
    ServeConfig,
    ServingEngine,
    make_decode_step,
    make_prefill_step,
)
from .kv import init_kv_pool
from .scheduler import Completion, Expired, Request, SlotScheduler
from .traffic import TrafficConfig, make_requests, run_open_loop, summarize

__all__ = [
    "AdmissionController",
    "Completion",
    "Expired",
    "Request",
    "ServeConfig",
    "ServingEngine",
    "SlotScheduler",
    "TrafficConfig",
    "init_kv_pool",
    "make_decode_step",
    "make_prefill_step",
    "make_requests",
    "run_open_loop",
    "summarize",
]
