"""Slot-pooled KV cache for the continuous-batching serving engine.

The training-side decode path (models/decode.py) holds one cache per
`generate()` call — every sequence in the batch shares a position. The
serving pool generalizes that to a FIXED pool of request slots: one
[depth, slots, max_len, heads, head_dim] buffer pair, each slot an
independent sequence at its own position, admitted and evicted without
recompilation (static shapes; per-slot length masks do the rest).

Two storage formats, selected by ``ServeConfig.kv_int8``:

- compute-dtype (f32/bf16) K/V, attended by the SAME ``_attend_cached``
  the single-request decoder uses (per-slot length vector) — the
  token-exactness oracle path;
- int8 K/V with per-(position, head) block scales, reusing the wire's
  block-scale quantizer (ops/quantize.quantize_int8, block = head_dim:
  one symmetric absmax scale per head vector, so a slot write never
  straddles a quantization block and per-position scatter writes stay
  local). Attention keeps the int8 payload in the einsum operands and
  applies the scales to the f32 score/probability rows instead of
  materializing a dequantized pool — the memory win is the point.

Write paths are static-shape: a whole-slot ``lax.dynamic_update_slice``
at admission (prefill) and a per-slot scatter (`.at[depth, slot, pos]`)
inside the decode step.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models.decode import NEG_INF, _attend_cached
from ..models.transformer import TransformerConfig
from ..ops.quantize import quantize_int8


def init_kv_pool(cfg: TransformerConfig, slots: int, max_len: int,
                 int8: bool = False) -> Dict:
    """Zeroed slot pool. Compute-dtype buffers, or int8 payloads plus
    f32 per-(position, head) scale rows when ``int8``."""
    shape = (cfg.depth, slots, max_len, cfg.heads, cfg.head_dim)
    if not int8:
        cd = cfg.effective_compute_dtype
        return {"k": jnp.zeros(shape, cd), "v": jnp.zeros(shape, cd)}
    sshape = (cfg.depth, slots, max_len, cfg.heads, 1)
    return {
        "k_q": jnp.zeros(shape, jnp.int8),
        "k_s": jnp.zeros(sshape, jnp.float32),
        "v_q": jnp.zeros(shape, jnp.int8),
        "v_s": jnp.zeros(sshape, jnp.float32),
    }


def pool_is_int8(pool: Dict) -> bool:
    return "k_q" in pool


def _quant_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Quantize [..., H, hd] to int8 with one scale per head vector.

    The wire's block-scale quantizer flattens to [n_blocks, block] rows;
    block = head_dim divides the flattened size exactly, so no padding
    and no block ever straddles a (position, head) boundary — the same
    carving-invariance the bucketed gradient wire relies on."""
    hd = x.shape[-1]
    q, s = quantize_int8(x.astype(jnp.float32), block_size=hd)
    return q.reshape(x.shape), s.reshape(x.shape[:-1] + (1,))


def write_slot(pool: Dict, block: int, slot: jax.Array,
               k: jax.Array, v: jax.Array) -> Dict:
    """Admission write: this block's full-prompt K/V [T, H, hd] into slot
    positions [0, T) — one dynamic_update_slice per buffer (slot is a
    traced scalar, T is static)."""
    pool = dict(pool)
    if not pool_is_int8(pool):
        for name, val in (("k", k), ("v", v)):
            buf = pool[name]
            pool[name] = lax.dynamic_update_slice(
                buf, val.astype(buf.dtype)[None, None], (block, slot, 0, 0, 0)
            )
        return pool
    for name, val in (("k", k), ("v", v)):
        q, s = _quant_rows(val)
        pool[name + "_q"] = lax.dynamic_update_slice(
            pool[name + "_q"], q[None, None], (block, slot, 0, 0, 0)
        )
        pool[name + "_s"] = lax.dynamic_update_slice(
            pool[name + "_s"], s[None, None], (block, slot, 0, 0, 0)
        )
    return pool


def write_token(pool: Dict, block: int, pos: jax.Array,
                k: jax.Array, v: jax.Array) -> Dict:
    """Decode-step write: one token's K/V [S, H, hd] at each slot's OWN
    position (``pos`` int [S]) — a scatter, because unlike the
    single-request cache there is no shared position to slice at."""
    pool = dict(pool)
    sl = jnp.arange(k.shape[0])
    if not pool_is_int8(pool):
        for name, val in (("k", k), ("v", v)):
            buf = pool[name]
            pool[name] = buf.at[block, sl, pos].set(val.astype(buf.dtype))
        return pool
    for name, val in (("k", k), ("v", v)):
        q, s = _quant_rows(val)
        pool[name + "_q"] = pool[name + "_q"].at[block, sl, pos].set(q)
        pool[name + "_s"] = pool[name + "_s"].at[block, sl, pos].set(s)
    return pool


def attend_pool(pool: Dict, block: int, q: jax.Array, lengths: jax.Array,
                scale: float) -> jax.Array:
    """q [S, 1, H, hd] against this block's pool rows; per-slot positions
    >= lengths[s] masked. Compute-dtype pools go through the single-
    request decoder's own ``_attend_cached`` (token-exactness by shared
    code); int8 pools run the same f32-score softmax with the block
    scales folded into the score/probability rows."""
    if not pool_is_int8(pool):
        return _attend_cached(q, pool["k"][block], pool["v"][block],
                              lengths, scale)
    k_q, k_s = pool["k_q"][block], pool["k_s"][block]
    v_q, v_s = pool["v_q"][block], pool["v_s"][block]
    # scores[b,h,1,l] = (q . k_q[l,h]) * k_s[l,h]: int8 payload feeds the
    # MXU-side contraction; the per-row scale lands on the f32 score
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k_q.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    row_scale = jnp.swapaxes(k_s[..., 0], 1, 2)[:, :, None, :]  # [S,H,1,L]
    scores = scores * row_scale
    pos = jnp.arange(k_q.shape[1])
    mask = pos[None, None, None, :] < jnp.reshape(lengths, (-1, 1, 1, 1))
    scores = jnp.where(mask, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    # fold v's scale into the probability row, keep v int8 in the einsum
    pv = p * jnp.swapaxes(v_s[..., 0], 1, 2)[:, :, None, :]
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", pv, v_q.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
