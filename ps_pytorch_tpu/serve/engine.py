"""Continuous-batching serving engine with hot checkpoint rollover.

The third role in the reference deployment — the evaluator that polls a
shared checkpoint directory and runs inference out-of-band — grown into
a serving loop (ROADMAP item 3): a fixed pool of KV-cache slots stepped
by ONE compiled decode program, requests admitted and evicted per step
by the host-side scheduler (serve/scheduler.py), weights hot-swapped
mid-serve when the trainer lands a new checkpoint.

Static shapes everywhere, exactly two compiled programs:

- ``prefill``: one slot's padded prompt ([max_prompt_len] int32; the
  pad tail's K/V is causally downstream of the real prompt only, never
  attended — decode overwrites each position before its first read)
  through the batched causal forward, K/V captured per block and written
  into the slot with ``lax.dynamic_update_slice``;
- ``decode``: every slot advances one token — per-slot positions,
  per-slot length masks (models/decode._attend_cached generalized to a
  length VECTOR), scatter writes at each slot's own position, greedy
  argmax. Finished/empty slots ride along masked (their writes land in
  regions the next occupant overwrites before attending), so admit/
  evict never recompiles.

Weights: the checkpoint's param tree lives on device as ONE padded flat
f32 vector in the flat-state engine's own layout
(parallel/buckets.FlatVector, the same geometry the trainer trains in),
so a checkpoint rollover is a single flat-buffer swap — the compiled
steps see an identical aval and never retrace. Rollover semantics are
PINNED as drain-then-swap: when a newer valid checkpoint appears
(checkpoint.load_latest_valid — the read-only single-read fast path),
admission pauses, in-flight sequences FINISH ON THE WEIGHTS THAT
STARTED THEM, then the buffer swaps and admission resumes. A completion
therefore always carries exactly one ``weights_step``, never a mix.

Rollover is HARDENED against a staged checkpoint going bad during the
drain (ARCHITECTURE §7i): staging records only the step number (the
poll validated the bytes it read, then discards them), and the swap
re-reads the file from disk. A corrupt or unreadable re-read ABORTS
the swap — one ``rollover_abort`` event, admissions resume on the OLD
weights token-exact (the flat buffer was never touched), nothing is
quarantined (the serving process never writes the training
directory), and the next poll retries whatever is then newest. A
``drain_timeout_s`` watchdog bounds how long a drain may pause
admissions before the engine gives up on the staged step entirely.

Request lifecycle contract (§7i): every submitted request terminates
in EXACTLY one of completed | shed | expired, each with a structured
JSONL event through ``event_sink`` — ``request_done``,
``request_shed`` (the AdmissionController refused the arrival), or
``deadline_expired`` (at submit, in queue, or evicted mid-decode).
``outcomes`` is the ledger the chaos drill audits for silent drops.

On a mesh the pool shards over the slot axis (parallel/mesh.
pool_sharding) with weights replicated: the decode step is
embarrassingly slot-parallel — ZERO collectives, a property the
``serve_decode`` pscheck contract (PSC107) pins at the jaxpr level.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..checkpoint import (
    CheckpointCorruptError,
    checkpoint_path,
    listify_raw,
    load_checkpoint_raw,
    load_latest_valid,
)
from ..models.transformer import (
    TransformerConfig,
    _rms_norm,
    select_attention,
    transformer_block,
)
from ..parallel.buckets import (
    FlatVector,
    _np_tree_to_flat,
    plan_buckets,
    tree_layout,
    tree_view,
)
from ..obs import NULL_TRACER
from ..parallel.mesh import pool_sharding, replicated_sharding
from ..utils import get_logger
from .kv import attend_pool, init_kv_pool, write_slot, write_token
from .scheduler import Completion, Expired, Request, SlotScheduler

logger = get_logger()


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Pool geometry + storage policy for one serving engine."""

    slots: int = 8
    max_len: int = 256           # cache positions per slot
    max_prompt_len: int = 64     # static prefill width (pad target)
    kv_int8: bool = False        # int8 K/V payload + block scales
    donate: bool = True          # donate the pool through both steps


def make_prefill_step(cfg: TransformerConfig, serve: ServeConfig):
    """(params, pool, prompt [max_prompt_len], slot) -> pool.

    The same block math as models/decode.prefill (transformer_block +
    the config's within-chip attention), targeted at one pool slot."""

    def prefill(params_any, pool, prompt, slot):
        params = tree_view(params_any)
        cd = cfg.effective_compute_dtype
        t = prompt.shape[0]
        pos = jnp.arange(t)
        x = (params["embed"][prompt] + params["pos_embed"][pos]).astype(cd)
        x = x[None]  # [1, T, D]
        base_attend = select_attention(cfg, None)

        for i, blk in enumerate(params["blocks"]):

            def attend(q, k, v, _i=i):
                nonlocal pool
                pool = write_slot(pool, _i, slot, k[0], v[0])
                return base_attend(q, k, v)

            x = transformer_block(cfg, x, blk, attend)
        return pool

    return prefill


def make_decode_step(cfg: TransformerConfig, serve: ServeConfig):
    """(params, pool, tok [S], pos [S], active [S])
    -> (pool, next [S], next_pos [S]).

    One greedy token for every slot at once. Inactive slots hold their
    token and position (the argmax is masked away) and their cache write
    is benign: the position they scribble is re-written by the slot's
    next occupant before it is ever attended. next/next_pos are returned
    so steady-state ticks can thread them straight back in as the next
    step's device inputs — zero host->device transfers between
    admissions/evictions (see ServingEngine.tick)."""

    def step(params_any, pool, tok, pos, active):
        params = tree_view(params_any)
        cd = cfg.effective_compute_dtype
        x = (params["embed"][tok] + params["pos_embed"][pos]).astype(cd)
        x = x[:, None]  # [S, 1, D]
        scale = 1.0 / (cfg.head_dim ** 0.5)
        lengths = pos + 1

        for i, blk in enumerate(params["blocks"]):

            def attend(q, k, v, _i=i):
                nonlocal pool
                pool = write_token(pool, _i, pos, k[:, 0], v[:, 0])
                return attend_pool(pool, _i, q, lengths, scale)

            x = transformer_block(cfg, x, blk, attend)

        xf = _rms_norm(x[:, 0].astype(cd), params["out_norm"].astype(cd))
        logits = (xf @ params["embed"].T.astype(cd)).astype(jnp.float32)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, tok)
        return pool, nxt, pos + active.astype(jnp.int32)

    return step


def _flat_params(layout, plan, tree) -> np.ndarray:
    """Host-side pack of a param tree into the engine's flat geometry."""
    return _np_tree_to_flat(layout, plan, tree)


class ServingEngine:
    """One model, one slot pool, one request loop.

    Greedy decode only (the serving contract is determinism: the same
    request set replays to the same tokens regardless of batching —
    pinned by tests/test_serve.py against per-sequence models/decode)."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Dict,
        serve: ServeConfig,
        mesh=None,
        model_dir: Optional[str] = None,
        step: Optional[int] = None,
        clock=None,
        tracer=None,
        admission=None,
        faults=None,
        event_sink=None,
        drain_timeout_s: Optional[float] = None,
        sleep=None,
    ):
        if not cfg.causal:
            raise ValueError("serving decode is autoregressive: cfg.causal")
        if serve.max_len > cfg.max_seq_len:
            raise ValueError(
                f"serve.max_len {serve.max_len} exceeds the model's "
                f"positional range {cfg.max_seq_len}"
            )
        if mesh is not None and serve.slots % mesh.devices.size:
            raise ValueError(
                f"slots ({serve.slots}) must divide over the mesh "
                f"({mesh.devices.size} devices) for slot sharding"
            )
        self.cfg = cfg
        self.serve = serve
        self.mesh = mesh
        self.model_dir = model_dir
        self.step = step
        # the latency clock: read at admission and again after each
        # token fetch. The open-loop driver (serve/traffic.py) rebases it
        # so arrival times and emission times share one timeline; tests
        # inject a virtual clock for determinism.
        self.clock = clock or time.perf_counter
        # span tracer (obs/trace.py): serve-tick phases + per-request
        # lifecycle spans. NULL_TRACER (the default) is inert — tick()
        # stays at exactly one host sync either way (PSL004 pins it).
        # Spans run on the tracer's REAL clock, independent of the
        # latency clock above (which tests inject/virtualize).
        self.tracer = tracer if tracer is not None else NULL_TRACER
        # SLO-aware admission (serve/admission.AdmissionController): when
        # set, every submit is offered to the controller first; sheds are
        # evented refusals, never silent drops
        self.admission = admission
        # serve-side FaultPlan (resilience/faults.py): slow_decode ticks
        # and rollover_corrupt staging hooks
        self.faults = faults
        # structured lifecycle events (request_done / request_shed /
        # deadline_expired / rollover_abort) — obs/schema.py kinds
        self._event_sink = event_sink
        # drain watchdog: how long a staged rollover may pause admissions
        # before the engine gives up on the staged step (None = forever)
        self.drain_timeout_s = drain_timeout_s
        # injectable stall primitive for fault hooks: virtual-clock tests
        # advance their clock here instead of real-sleeping
        self._sleep = sleep if sleep is not None else time.sleep
        self.scheduler = SlotScheduler(
            serve.slots, serve.max_len, serve.max_prompt_len
        )

        # weights: ONE padded flat f32 vector in the flat-state layout
        # (single bucket — the rollover swap is one buffer either way)
        self._layout = tree_layout(params)
        self._plan = plan_buckets(self._layout.total, 0, align=1)
        flat = _flat_params(self._layout, self._plan, params)
        self._params = FlatVector(
            flat=self._place_flat(flat), layout=self._layout, plan=self._plan
        )

        pool = init_kv_pool(cfg, serve.slots, serve.max_len, int8=serve.kv_int8)
        if mesh is not None:
            sh = pool_sharding(mesh, dim=1)
            pool = {k: jax.device_put(v, sh) for k, v in pool.items()}
        self._pool = pool

        donate = (1,) if serve.donate else ()
        self._prefill = jax.jit(
            make_prefill_step(cfg, serve), donate_argnums=donate
        )
        self._decode = jax.jit(
            make_decode_step(cfg, serve), donate_argnums=donate
        )

        s = serve.slots
        self._tok = np.zeros((s,), np.int32)
        self._pos = np.zeros((s,), np.int32)
        self._active = np.zeros((s,), bool)
        # device-side (tok, pos, active) triple: rebuilt from the host
        # arrays only on ticks AFTER an admission/eviction (dirty);
        # otherwise the previous step's own outputs thread straight back
        # in — steady-state ticks pay zero host->device transfers
        self._dev: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None
        self._dirty = True
        # a staged rollover is the STEP NUMBER only: the swap re-reads
        # the file from disk so damage landing between stage and swap is
        # discovered (and aborted) instead of served
        self._pending: Optional[int] = None
        self.rollovers: List[Dict[str, Any]] = []
        self.rollover_aborts: List[Dict[str, Any]] = []
        # the lifecycle ledger: rid -> "completed" | "shed" | "expired".
        # Every submit lands exactly one entry; the chaos drill audits it
        # against the event stream for silent drops. The per-request
        # records are BOUNDED (a long-lived server must not grow its
        # audit without limit — same stance as the tracer ring); the
        # totals live in outcome_counts and never saturate.
        self._ledger_cap = 65536
        self.outcomes: Dict[int, str] = {}
        self.outcome_counts: Dict[str, int] = {
            "completed": 0, "shed": 0, "expired": 0,
        }
        self.shed: Deque[Dict[str, Any]] = deque(maxlen=self._ledger_cap)
        self.expired: Deque[Expired] = deque(maxlen=self._ledger_cap)
        # a step the drain watchdog gave up on: never re-staged (only a
        # strictly newer checkpoint supersedes it)
        self._abandoned_step: Optional[int] = None
        self._tick_no = 0
        # per-slot admission instant on the TRACER clock (request
        # lifecycle spans) and the open drain's start, if any
        self._admit_tr_t: Dict[int, float] = {}
        self._drain_tr_t0: Optional[float] = None
        # the drain's start on the LATENCY clock (tests virtualize it) —
        # the watchdog's timebase, distinct from the tracer clock above
        self._drain_clk_t0: Optional[float] = None

    # ------------------------------------------------------- construction
    @classmethod
    def from_checkpoint(
        cls,
        model_dir: str,
        serve: ServeConfig,
        step: Optional[int] = None,
        mesh=None,
        compute_dtype=None,
        tracer=None,
        **engine_kw,
    ) -> "ServingEngine":
        """Load a cli/train_lm checkpoint (dense LMs; the evaluator's
        scheme-agnostic raw layout) into a serving engine.
        ``engine_kw`` passes through to the constructor (admission,
        faults, event_sink, drain_timeout_s, clock, sleep)."""
        if step is None:
            found = load_latest_valid(model_dir)
            if found is None:
                raise FileNotFoundError(f"no valid checkpoints in {model_dir}")
            step, raw = found
        else:
            raw = load_checkpoint_raw(model_dir, step)
        cfg, params = checkpoint_model(raw, compute_dtype)
        return cls(cfg, params, serve, mesh=mesh, model_dir=model_dir,
                   step=step, tracer=tracer, **engine_kw)

    def _place_flat(self, flat: np.ndarray) -> jax.Array:
        if self.mesh is not None:
            return jax.device_put(flat, replicated_sharding(self.mesh))
        return jnp.asarray(flat)

    # ---------------------------------------------------------- rollover
    def poll_rollover(self) -> Optional[int]:
        """Stage the newest valid checkpoint newer than the serving step
        (single-read validate). Returns the staged step, or None. Only
        the STEP is staged — the swap re-reads the file after the drain,
        so corruption landing in between is discovered, not served. The
        swap itself waits for the drain — see tick()."""
        if self.model_dir is None:
            return None
        # while a rollover is already staged, only a STRICTLY newer step
        # re-stages — repeated polls during a drain stay one cheap
        # listdir; a step the drain watchdog abandoned is never retried
        after = max(
            x for x in (self._pending, self._abandoned_step, self.step)
            if x is not None
        )
        found = load_latest_valid(self.model_dir, after_step=after)
        if found is None:
            return None
        new_step, raw = found
        cfg, params = checkpoint_model(raw, self.cfg.compute_dtype)
        layout = tree_layout(params)
        if layout.shapes != self._layout.shapes:
            raise ValueError(
                f"checkpoint step {new_step} has a different param "
                f"geometry than the serving model — rollover would "
                f"require a recompile, refusing"
            )
        if self._drain_tr_t0 is None:
            self._drain_tr_t0 = self.tracer.now()
        if self._drain_clk_t0 is None:
            self._drain_clk_t0 = self.clock()
        self._pending = new_step
        if self.faults is not None:
            # chaos hook: damage the staged file AFTER validation — the
            # swap-time re-read must catch it (rollover_abort)
            self.faults.maybe_corrupt_staged(
                checkpoint_path(self.model_dir, new_step), new_step
            )
        logger.info(
            "rollover staged: step %s -> %d (draining %d in-flight)",
            self.step, new_step, self.scheduler.n_inflight,
        )
        return new_step

    def _close_drain_span(self, to_step: int, outcome: str) -> None:
        if self._drain_tr_t0 is not None:
            # the drain interval spans ticks: staged in one poll, ended
            # (swap or abort) ticks later — record it as one explicit
            # span so the timeline shows WHY admission paused
            self.tracer.add(
                "rollover_drain", self._drain_tr_t0,
                self.tracer.now() - self._drain_tr_t0, cat="serve",
                from_step=self.step, to_step=to_step, outcome=outcome,
            )
            self._drain_tr_t0 = None
        self._drain_clk_t0 = None

    def _try_swap(self, now_s: float) -> None:
        """Drain complete: re-read the staged checkpoint and swap the
        flat buffer — or abort onto the old weights if the bytes on disk
        went bad since staging."""
        new_step = self._pending
        try:
            # read_attempts=1: an unreadable staged file is an abort
            # verdict, not something to retry-backoff INSIDE the request
            # loop — the next poll is the retry
            raw = load_checkpoint_raw(self.model_dir, new_step,
                                      read_attempts=1)
            _, params = checkpoint_model(raw, self.cfg.compute_dtype)
            if tree_layout(params).shapes != self._layout.shapes:
                raise ValueError(
                    f"staged checkpoint step {new_step} changed param "
                    f"geometry between stage and swap"
                )
            flat = _flat_params(self._layout, self._plan, params)
        except (CheckpointCorruptError, OSError, ValueError) as e:
            # the staged bytes are gone/bad: abort the swap, keep serving
            # the OLD weights (the flat buffer was never touched — token-
            # exact by construction), retry whatever the next poll finds.
            # Nothing is quarantined: the serving process never writes
            # the training directory.
            self._abort_rollover(now_s, reason="corrupt_staged",
                                 error=str(e))
            return
        self._pending = None
        self._close_drain_span(new_step, outcome="swap")
        with self.tracer.span(
            "rollover_swap", cat="serve",
            from_step=self.step, to_step=new_step,
        ):
            self._params = FlatVector(
                flat=self._place_flat(flat),
                layout=self._layout,
                plan=self._plan,
            )
        self.rollovers.append(
            {"from_step": self.step, "to_step": new_step, "at_s": now_s}
        )
        logger.info("rollover complete: now serving step %d", new_step)
        self.step = new_step

    def _abort_rollover(self, now_s: float, reason: str,
                        error: str = "") -> None:
        staged = self._pending
        self._pending = None
        self._close_drain_span(staged, outcome="abort")
        if reason == "drain_timeout":
            # the watchdog gave up on this step: only a strictly newer
            # checkpoint may stage again (a corrupt abort retries — the
            # next poll re-validates the directory from scratch)
            self._abandoned_step = staged
        rec = {
            "kind": "rollover_abort",
            "from_step": self.step,
            "staged_step": staged,
            "reason": reason,
            "error": error,
            "at_s": round(now_s, 6),
        }
        self.rollover_aborts.append(dict(rec))
        self._emit(rec)
        self.tracer.instant(
            "rollover_abort", cat="serve", from_step=self.step,
            staged_step=staged, reason=reason,
        )
        logger.warning(
            "rollover abort (%s): staying on step %s, staged step %s "
            "dropped%s",
            reason, self.step, staged, f" ({error})" if error else "",
        )

    @property
    def draining(self) -> bool:
        return self._pending is not None

    # ------------------------------------------------------------ intake
    def _emit(self, record: Dict[str, Any]) -> None:
        if self._event_sink is not None:
            self._event_sink(record)

    def _record_outcome(self, rid: int, outcome: str) -> None:
        if rid >= 0:  # warmup probes (negative rids) are not traffic
            self.outcome_counts[outcome] += 1
        self.outcomes[rid] = outcome
        while len(self.outcomes) > self._ledger_cap:
            self.outcomes.pop(next(iter(self.outcomes)))

    def _record_expired(self, exp: Expired) -> None:
        self._record_outcome(exp.rid, "expired")
        self.expired.append(exp)
        self._emit({
            "kind": "deadline_expired",
            "rid": exp.rid,
            "where": exp.where,
            "deadline_s": round(exp.deadline_s, 6),
            "expired_s": round(exp.expired_s, 6),
            "tokens_done": len(exp.tokens),
        })

    def submit(self, request: Request) -> None:
        """Front door: a request terminates right here when its deadline
        already passed (expired) or the admission controller refuses it
        (shed) — both evented, neither ever queued. Everything else goes
        to the scheduler's FIFO."""
        now_s = self.clock()
        if request.deadline_s is not None and request.deadline_s <= now_s:
            self._record_expired(Expired(
                rid=request.rid, where="submit",
                deadline_s=float(request.deadline_s), expired_s=now_s,
            ))
            return
        if self.admission is not None:
            shed, projected = self.admission.offered(
                now_s, self.scheduler.n_queued
            )
            if shed:
                rec = {
                    "kind": "request_shed",
                    "rid": request.rid,
                    "projected_wait_s": round(projected, 6),
                    "queue_depth": self.scheduler.n_queued,
                    "slo_budget_s": self.admission.slo_budget_s,
                    "at_s": round(now_s, 6),
                }
                self._record_outcome(request.rid, "shed")
                self.shed.append(dict(rec))
                self._emit(rec)
                return
        self.scheduler.submit(request)

    # -------------------------------------------------------------- loop
    def _expire_deadlines(self, now_s: float) -> None:
        """Terminate queued and in-flight requests whose deadline passed:
        queued ones never admit; in-flight ones are evicted mid-decode
        (their slot is freed and masked out — the next occupant stays
        token-exact, same argument as a normal evict)."""
        for req in self.scheduler.expire_queued(now_s):
            self._record_expired(Expired(
                rid=req.rid, where="queue",
                deadline_s=float(req.deadline_s), expired_s=now_s,
            ))
        for slot in list(self.scheduler.active_slots):
            req = self.scheduler.request_in(slot)
            if req.deadline_s is not None and req.deadline_s <= now_s:
                exp = self.scheduler.expire_slot(slot, now_s)
                self._active[slot] = False
                self._dirty = True
                t0 = self._admit_tr_t.pop(slot, None)
                if t0 is not None:
                    self.tracer.add(
                        "request", t0, self.tracer.now() - t0,
                        cat="request", slot=slot, rid=exp.rid,
                        outcome="expired", new_tokens=len(exp.tokens),
                    )
                self._record_expired(exp)

    def tick(self) -> List[Completion]:
        """One scheduler round: expire deadlines, swap-if-drained (or
        abort), admit, one decode step, record/evict. Returns the
        completions that finished this tick."""
        self._tick_no += 1
        tr = self.tracer
        if self.faults is not None:
            # injected per-tick stall (chaos: drives queue growth and
            # with it the admission controller) — host-side, pre-decode
            self.faults.maybe_slow_decode(self._tick_no, sleep=self._sleep)
        now_s = self.clock()
        self._expire_deadlines(now_s)
        if self._pending is not None:
            if self.scheduler.n_inflight == 0:
                self._try_swap(now_s)
            elif (
                self.drain_timeout_s is not None
                and self._drain_clk_t0 is not None
                and now_s - self._drain_clk_t0 > self.drain_timeout_s
            ):
                # drain watchdog: a drain may not pause admissions
                # forever — give up on the staged step, resume service
                self._abort_rollover(now_s, reason="drain_timeout")
        if self.admission is not None:
            self.admission.observe_tick(now_s, self.scheduler.n_queued)
        if self._pending is None:
            for slot, req in self.scheduler.admit(now_s):
                self._admit_slot(slot, req)
                if self.admission is not None:
                    self.admission.record_admit(now_s)
        if self.scheduler.n_inflight == 0:
            return []

        with tr.span("decode_dispatch", cat="serve", tick=self._tick_no):
            if self._dirty or self._dev is None:
                self._dev = (
                    jnp.asarray(self._tok), jnp.asarray(self._pos),
                    jnp.asarray(self._active),
                )
                self._dirty = False
            tok_d, pos_d, act_d = self._dev
            self._pool, nxt, new_pos = self._decode(
                self._params, self._pool, tok_d, pos_d, act_d
            )
            self._dev = (nxt, new_pos, act_d)
        # THE per-tick host sync: the scheduler cannot admit/evict
        # without this step's tokens — one fused [slots] fetch, not a
        # per-request read
        with tr.span("token_fetch", cat="serve", tick=self._tick_no):
            tokens = np.asarray(jax.device_get(nxt))  # psl: sync-ok
        # latency is measured at emission (after the fetch retires), not
        # at tick entry — the fetch IS the serving latency's device half
        emit_s = self.clock()

        done: List[Completion] = []
        with tr.span("evict", cat="serve", tick=self._tick_no):
            for slot in list(self.scheduler.active_slots):
                token = int(tokens[slot])
                self._tok[slot] = token
                self._pos[slot] += 1
                if self.scheduler.record_token(slot, token, emit_s):
                    self._active[slot] = False
                    self._dirty = True  # next tick rebuilds the triple
                    c = self.scheduler.evict(
                        slot, emit_s, weights_step=self.step
                    )
                    self._record_outcome(c.rid, "completed")
                    self._emit({
                        "kind": "request_done",
                        "rid": c.rid,
                        "new_tokens": len(c.tokens),
                        "weights_step": c.weights_step,
                        "met_deadline": c.met_deadline,
                        "ttft_s": round(c.latencies_s[0], 6)
                        if c.latencies_s else None,
                    })
                    t0 = self._admit_tr_t.pop(slot, None)
                    if t0 is not None:
                        # request lifecycle (admission -> finish on the
                        # tracer clock); the queue component — arrival ->
                        # admission, measured on the latency clock —
                        # rides as an attribute
                        tr.add(
                            "request", t0, tr.now() - t0, cat="request",
                            slot=slot,
                            rid=c.rid, queue_s=round(c.queue_s, 6),
                            prefill_s=round(c.prefill_s, 6),
                            decode_s=round(c.decode_s, 6),
                            new_tokens=len(c.tokens),
                            weights_step=c.weights_step,
                        )
                    done.append(c)
        if tr.enabled and self._tick_no % 256 == 0:
            # the serve loop's "log window": bounded-latency flushes off
            # the ring so a long-lived server never loses old spans
            tr.flush()
        return done

    def _admit_slot(self, slot: int, req: Request) -> None:
        with self.tracer.span(
            "admit_prefill", cat="serve", slot=slot, rid=req.rid
        ):
            self._admit_tr_t[slot] = self.tracer.now()
            plen = int(req.prompt.shape[0])
            if plen > 1:
                padded = np.zeros((self.serve.max_prompt_len,), np.int32)
                padded[:plen] = req.prompt
                self._pool = self._prefill(
                    self._params, self._pool, jnp.asarray(padded),
                    np.int32(slot),
                )
            self._tok[slot] = int(req.prompt[plen - 1])
            self._pos[slot] = plen - 1
            self._active[slot] = True
            self._dirty = True  # next tick rebuilds the device triple

    # ------------------------------------------------------- conveniences
    def compiled_decode_text(self) -> str:
        """Optimized-HLO text of the decode step (bench op-count probe).
        Lowered over the live avals — tracing only, nothing executes and
        no pool buffer is donated by a .lower()."""
        s = self.serve.slots
        return self._decode.lower(
            self._params, self._pool,
            jax.ShapeDtypeStruct((s,), jnp.int32),
            jax.ShapeDtypeStruct((s,), jnp.int32),
            jax.ShapeDtypeStruct((s,), jnp.bool_),
        ).compile().as_text()

    def warmup(self) -> None:
        """Compile both steps (one throwaway request through prefill +
        decode) so served latency measures the engine, not XLA. The pool
        slot it dirties is freed and overwritten on first real use.
        Bypasses the front door (admission control and fault ticks must
        target served traffic, not the compile probe): the scheduler is
        fed directly, the warmup's rid -1 outcome is dropped, and tick
        numbering restarts at 0 so ``slow_decode`` plans are warmup-
        invariant."""
        plen = min(2, self.serve.max_prompt_len)
        self.scheduler.submit(Request(
            rid=-1, prompt=np.zeros((plen,), np.int32), max_new_tokens=1
        ))
        faults, sink, adm = self.faults, self._event_sink, self.admission
        self.faults = None
        self._event_sink = None
        self.admission = None  # compile walltime is not drain evidence
        try:
            while not self.scheduler.idle:
                self.tick()
        finally:
            self.faults = faults
            self._event_sink = sink
            self.admission = adm
        self.outcomes.pop(-1, None)
        self._tick_no = 0

    def decode_requests(self, requests: Sequence[Request],
                        poll_every: int = 0) -> List[Completion]:
        """Closed-loop drive: submit everything, tick to idle. With
        ``poll_every`` > 0, poll for a checkpoint rollover every that
        many ticks (tests use this to pin the drain semantics)."""
        for r in requests:
            self.submit(r)
        out: List[Completion] = []
        ticks = 0
        while not self.scheduler.idle or self._pending is not None:
            out.extend(self.tick())
            ticks += 1
            if poll_every and ticks % poll_every == 0:
                self.poll_rollover()
        return sorted(out, key=lambda c: c.rid)


def checkpoint_model(raw: dict, compute_dtype) -> Tuple[TransformerConfig, Dict]:
    """Rebuild (TransformerConfig, params tree) from a train_lm raw
    checkpoint dict. Dense models only — MoE decode needs the roomy-
    capacity expert mixture and is not in the serving engine yet."""
    m = raw["model"]
    if m.get("kind", "dense") != "dense":
        raise ValueError(
            "the serving engine decodes dense LM checkpoints only "
            f"(checkpoint kind: {m.get('kind')!r})"
        )
    cfg = TransformerConfig(
        vocab_size=int(m["vocab_size"]),
        dim=int(m["dim"]),
        depth=int(m["depth"]),
        heads=int(m["heads"]),
        mlp_ratio=int(m["mlp_ratio"]),
        max_seq_len=int(m["max_seq_len"]),
        compute_dtype=compute_dtype,
    )
    params = jax.tree.map(np.asarray, listify_raw(raw["params"]))
    return cfg, params
