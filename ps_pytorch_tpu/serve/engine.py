"""Continuous-batching serving engine with hot checkpoint rollover.

The third role in the reference deployment — the evaluator that polls a
shared checkpoint directory and runs inference out-of-band — grown into
a serving loop (ROADMAP item 3): a fixed pool of KV-cache slots stepped
by ONE compiled decode program, requests admitted and evicted per step
by the host-side scheduler (serve/scheduler.py), weights hot-swapped
mid-serve when the trainer lands a new checkpoint.

Static shapes everywhere, exactly two compiled programs:

- ``prefill``: one slot's padded prompt ([max_prompt_len] int32; the
  pad tail's K/V is causally downstream of the real prompt only, never
  attended — decode overwrites each position before its first read)
  through the batched causal forward, K/V captured per block and written
  into the slot with ``lax.dynamic_update_slice``;
- ``decode``: every slot advances one token — per-slot positions,
  per-slot length masks (models/decode._attend_cached generalized to a
  length VECTOR), scatter writes at each slot's own position, greedy
  argmax. Finished/empty slots ride along masked (their writes land in
  regions the next occupant overwrites before attending), so admit/
  evict never recompiles.

Weights: the checkpoint's param tree lives on device as ONE padded flat
f32 vector in the flat-state engine's own layout
(parallel/buckets.FlatVector, the same geometry the trainer trains in),
so a checkpoint rollover is a single flat-buffer swap — the compiled
steps see an identical aval and never retrace. Rollover semantics are
PINNED as drain-then-swap: when a newer valid checkpoint appears
(checkpoint.load_latest_valid — the read-only single-read fast path),
admission pauses, in-flight sequences FINISH ON THE WEIGHTS THAT
STARTED THEM, then the buffer swaps and admission resumes. A completion
therefore always carries exactly one ``weights_step``, never a mix.

On a mesh the pool shards over the slot axis (parallel/mesh.
pool_sharding) with weights replicated: the decode step is
embarrassingly slot-parallel — ZERO collectives, a property the
``serve_decode`` pscheck contract (PSC107) pins at the jaxpr level.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..checkpoint import listify_raw, load_checkpoint_raw, load_latest_valid
from ..models.transformer import (
    TransformerConfig,
    _rms_norm,
    select_attention,
    transformer_block,
)
from ..parallel.buckets import (
    FlatVector,
    _np_tree_to_flat,
    plan_buckets,
    tree_layout,
    tree_view,
)
from ..obs import NULL_TRACER
from ..parallel.mesh import pool_sharding, replicated_sharding
from ..utils import get_logger
from .kv import attend_pool, init_kv_pool, write_slot, write_token
from .scheduler import Completion, Request, SlotScheduler

logger = get_logger()


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Pool geometry + storage policy for one serving engine."""

    slots: int = 8
    max_len: int = 256           # cache positions per slot
    max_prompt_len: int = 64     # static prefill width (pad target)
    kv_int8: bool = False        # int8 K/V payload + block scales
    donate: bool = True          # donate the pool through both steps


def make_prefill_step(cfg: TransformerConfig, serve: ServeConfig):
    """(params, pool, prompt [max_prompt_len], slot) -> pool.

    The same block math as models/decode.prefill (transformer_block +
    the config's within-chip attention), targeted at one pool slot."""

    def prefill(params_any, pool, prompt, slot):
        params = tree_view(params_any)
        cd = cfg.effective_compute_dtype
        t = prompt.shape[0]
        pos = jnp.arange(t)
        x = (params["embed"][prompt] + params["pos_embed"][pos]).astype(cd)
        x = x[None]  # [1, T, D]
        base_attend = select_attention(cfg, None)

        for i, blk in enumerate(params["blocks"]):

            def attend(q, k, v, _i=i):
                nonlocal pool
                pool = write_slot(pool, _i, slot, k[0], v[0])
                return base_attend(q, k, v)

            x = transformer_block(cfg, x, blk, attend)
        return pool

    return prefill


def make_decode_step(cfg: TransformerConfig, serve: ServeConfig):
    """(params, pool, tok [S], pos [S], active [S])
    -> (pool, next [S], next_pos [S]).

    One greedy token for every slot at once. Inactive slots hold their
    token and position (the argmax is masked away) and their cache write
    is benign: the position they scribble is re-written by the slot's
    next occupant before it is ever attended. next/next_pos are returned
    so steady-state ticks can thread them straight back in as the next
    step's device inputs — zero host->device transfers between
    admissions/evictions (see ServingEngine.tick)."""

    def step(params_any, pool, tok, pos, active):
        params = tree_view(params_any)
        cd = cfg.effective_compute_dtype
        x = (params["embed"][tok] + params["pos_embed"][pos]).astype(cd)
        x = x[:, None]  # [S, 1, D]
        scale = 1.0 / (cfg.head_dim ** 0.5)
        lengths = pos + 1

        for i, blk in enumerate(params["blocks"]):

            def attend(q, k, v, _i=i):
                nonlocal pool
                pool = write_token(pool, _i, pos, k[:, 0], v[:, 0])
                return attend_pool(pool, _i, q, lengths, scale)

            x = transformer_block(cfg, x, blk, attend)

        xf = _rms_norm(x[:, 0].astype(cd), params["out_norm"].astype(cd))
        logits = (xf @ params["embed"].T.astype(cd)).astype(jnp.float32)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, tok)
        return pool, nxt, pos + active.astype(jnp.int32)

    return step


def _flat_params(layout, plan, tree) -> np.ndarray:
    """Host-side pack of a param tree into the engine's flat geometry."""
    return _np_tree_to_flat(layout, plan, tree)


class ServingEngine:
    """One model, one slot pool, one request loop.

    Greedy decode only (the serving contract is determinism: the same
    request set replays to the same tokens regardless of batching —
    pinned by tests/test_serve.py against per-sequence models/decode)."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params: Dict,
        serve: ServeConfig,
        mesh=None,
        model_dir: Optional[str] = None,
        step: Optional[int] = None,
        clock=None,
        tracer=None,
    ):
        if not cfg.causal:
            raise ValueError("serving decode is autoregressive: cfg.causal")
        if serve.max_len > cfg.max_seq_len:
            raise ValueError(
                f"serve.max_len {serve.max_len} exceeds the model's "
                f"positional range {cfg.max_seq_len}"
            )
        if mesh is not None and serve.slots % mesh.devices.size:
            raise ValueError(
                f"slots ({serve.slots}) must divide over the mesh "
                f"({mesh.devices.size} devices) for slot sharding"
            )
        self.cfg = cfg
        self.serve = serve
        self.mesh = mesh
        self.model_dir = model_dir
        self.step = step
        # the latency clock: read at admission and again after each
        # token fetch. The open-loop driver (serve/traffic.py) rebases it
        # so arrival times and emission times share one timeline; tests
        # inject a virtual clock for determinism.
        self.clock = clock or time.perf_counter
        # span tracer (obs/trace.py): serve-tick phases + per-request
        # lifecycle spans. NULL_TRACER (the default) is inert — tick()
        # stays at exactly one host sync either way (PSL004 pins it).
        # Spans run on the tracer's REAL clock, independent of the
        # latency clock above (which tests inject/virtualize).
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.scheduler = SlotScheduler(
            serve.slots, serve.max_len, serve.max_prompt_len
        )

        # weights: ONE padded flat f32 vector in the flat-state layout
        # (single bucket — the rollover swap is one buffer either way)
        self._layout = tree_layout(params)
        self._plan = plan_buckets(self._layout.total, 0, align=1)
        flat = _flat_params(self._layout, self._plan, params)
        self._params = FlatVector(
            flat=self._place_flat(flat), layout=self._layout, plan=self._plan
        )

        pool = init_kv_pool(cfg, serve.slots, serve.max_len, int8=serve.kv_int8)
        if mesh is not None:
            sh = pool_sharding(mesh, dim=1)
            pool = {k: jax.device_put(v, sh) for k, v in pool.items()}
        self._pool = pool

        donate = (1,) if serve.donate else ()
        self._prefill = jax.jit(
            make_prefill_step(cfg, serve), donate_argnums=donate
        )
        self._decode = jax.jit(
            make_decode_step(cfg, serve), donate_argnums=donate
        )

        s = serve.slots
        self._tok = np.zeros((s,), np.int32)
        self._pos = np.zeros((s,), np.int32)
        self._active = np.zeros((s,), bool)
        # device-side (tok, pos, active) triple: rebuilt from the host
        # arrays only on ticks AFTER an admission/eviction (dirty);
        # otherwise the previous step's own outputs thread straight back
        # in — steady-state ticks pay zero host->device transfers
        self._dev: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None
        self._dirty = True
        self._pending: Optional[Tuple[int, np.ndarray]] = None
        self.rollovers: List[Dict[str, Any]] = []
        self._tick_no = 0
        # per-slot admission instant on the TRACER clock (request
        # lifecycle spans) and the open drain's start, if any
        self._admit_tr_t: Dict[int, float] = {}
        self._drain_tr_t0: Optional[float] = None

    # ------------------------------------------------------- construction
    @classmethod
    def from_checkpoint(
        cls,
        model_dir: str,
        serve: ServeConfig,
        step: Optional[int] = None,
        mesh=None,
        compute_dtype=None,
        tracer=None,
    ) -> "ServingEngine":
        """Load a cli/train_lm checkpoint (dense LMs; the evaluator's
        scheme-agnostic raw layout) into a serving engine."""
        if step is None:
            found = load_latest_valid(model_dir)
            if found is None:
                raise FileNotFoundError(f"no valid checkpoints in {model_dir}")
            step, raw = found
        else:
            raw = load_checkpoint_raw(model_dir, step)
        cfg, params = checkpoint_model(raw, compute_dtype)
        return cls(cfg, params, serve, mesh=mesh, model_dir=model_dir,
                   step=step, tracer=tracer)

    def _place_flat(self, flat: np.ndarray) -> jax.Array:
        if self.mesh is not None:
            return jax.device_put(flat, replicated_sharding(self.mesh))
        return jnp.asarray(flat)

    # ---------------------------------------------------------- rollover
    def poll_rollover(self) -> Optional[int]:
        """Stage the newest valid checkpoint newer than the serving step
        (single-read validate+load). Returns the staged step, or None.
        The swap itself waits for the drain — see tick()."""
        if self.model_dir is None:
            return None
        # while a rollover is already staged, only a STRICTLY newer step
        # re-stages — repeated polls during a drain stay one cheap listdir
        after = self._pending[0] if self._pending is not None else self.step
        found = load_latest_valid(self.model_dir, after_step=after)
        if found is None:
            return None
        new_step, raw = found
        cfg, params = checkpoint_model(raw, self.cfg.compute_dtype)
        layout = tree_layout(params)
        if layout.shapes != self._layout.shapes:
            raise ValueError(
                f"checkpoint step {new_step} has a different param "
                f"geometry than the serving model — rollover would "
                f"require a recompile, refusing"
            )
        if self._drain_tr_t0 is None:
            self._drain_tr_t0 = self.tracer.now()
        self._pending = (
            new_step, _flat_params(self._layout, self._plan, params)
        )
        logger.info(
            "rollover staged: step %s -> %d (draining %d in-flight)",
            self.step, new_step, self.scheduler.n_inflight,
        )
        return new_step

    def _swap_pending(self, now_s: float) -> None:
        new_step, flat = self._pending
        self._pending = None
        if self._drain_tr_t0 is not None:
            # the drain interval spans ticks: staged in one poll, swapped
            # when the last in-flight request finished — record it as one
            # explicit span so the timeline shows WHY admission paused
            self.tracer.add(
                "rollover_drain", self._drain_tr_t0,
                self.tracer.now() - self._drain_tr_t0, cat="serve",
                from_step=self.step, to_step=new_step,
            )
            self._drain_tr_t0 = None
        with self.tracer.span(
            "rollover_swap", cat="serve",
            from_step=self.step, to_step=new_step,
        ):
            self._params = FlatVector(
                flat=self._place_flat(flat),
                layout=self._layout,
                plan=self._plan,
            )
        self.rollovers.append(
            {"from_step": self.step, "to_step": new_step, "at_s": now_s}
        )
        logger.info("rollover complete: now serving step %d", new_step)
        self.step = new_step

    @property
    def draining(self) -> bool:
        return self._pending is not None

    # ------------------------------------------------------------ intake
    def submit(self, request: Request) -> None:
        self.scheduler.submit(request)

    # -------------------------------------------------------------- loop
    def tick(self) -> List[Completion]:
        """One scheduler round: swap-if-drained, admit, one decode step,
        record/evict. Returns the completions that finished this tick."""
        self._tick_no += 1
        tr = self.tracer
        now_s = self.clock()
        if self._pending is not None and self.scheduler.n_inflight == 0:
            self._swap_pending(now_s)
        if self._pending is None:
            for slot, req in self.scheduler.admit(now_s):
                self._admit_slot(slot, req)
        if self.scheduler.n_inflight == 0:
            return []

        with tr.span("decode_dispatch", cat="serve", tick=self._tick_no):
            if self._dirty or self._dev is None:
                self._dev = (
                    jnp.asarray(self._tok), jnp.asarray(self._pos),
                    jnp.asarray(self._active),
                )
                self._dirty = False
            tok_d, pos_d, act_d = self._dev
            self._pool, nxt, new_pos = self._decode(
                self._params, self._pool, tok_d, pos_d, act_d
            )
            self._dev = (nxt, new_pos, act_d)
        # THE per-tick host sync: the scheduler cannot admit/evict
        # without this step's tokens — one fused [slots] fetch, not a
        # per-request read
        with tr.span("token_fetch", cat="serve", tick=self._tick_no):
            tokens = np.asarray(jax.device_get(nxt))  # psl: sync-ok
        # latency is measured at emission (after the fetch retires), not
        # at tick entry — the fetch IS the serving latency's device half
        emit_s = self.clock()

        done: List[Completion] = []
        with tr.span("evict", cat="serve", tick=self._tick_no):
            for slot in list(self.scheduler.active_slots):
                token = int(tokens[slot])
                self._tok[slot] = token
                self._pos[slot] += 1
                if self.scheduler.record_token(slot, token, emit_s):
                    self._active[slot] = False
                    self._dirty = True  # next tick rebuilds the triple
                    c = self.scheduler.evict(
                        slot, emit_s, weights_step=self.step
                    )
                    t0 = self._admit_tr_t.pop(slot, None)
                    if t0 is not None:
                        # request lifecycle (admission -> finish on the
                        # tracer clock); the queue component — arrival ->
                        # admission, measured on the latency clock —
                        # rides as an attribute
                        tr.add(
                            "request", t0, tr.now() - t0, cat="request",
                            slot=slot,
                            rid=c.rid, queue_s=round(c.queue_s, 6),
                            prefill_s=round(c.prefill_s, 6),
                            decode_s=round(c.decode_s, 6),
                            new_tokens=len(c.tokens),
                            weights_step=c.weights_step,
                        )
                    done.append(c)
        if tr.enabled and self._tick_no % 256 == 0:
            # the serve loop's "log window": bounded-latency flushes off
            # the ring so a long-lived server never loses old spans
            tr.flush()
        return done

    def _admit_slot(self, slot: int, req: Request) -> None:
        with self.tracer.span(
            "admit_prefill", cat="serve", slot=slot, rid=req.rid
        ):
            self._admit_tr_t[slot] = self.tracer.now()
            plen = int(req.prompt.shape[0])
            if plen > 1:
                padded = np.zeros((self.serve.max_prompt_len,), np.int32)
                padded[:plen] = req.prompt
                self._pool = self._prefill(
                    self._params, self._pool, jnp.asarray(padded),
                    np.int32(slot),
                )
            self._tok[slot] = int(req.prompt[plen - 1])
            self._pos[slot] = plen - 1
            self._active[slot] = True
            self._dirty = True  # next tick rebuilds the device triple

    # ------------------------------------------------------- conveniences
    def compiled_decode_text(self) -> str:
        """Optimized-HLO text of the decode step (bench op-count probe).
        Lowered over the live avals — tracing only, nothing executes and
        no pool buffer is donated by a .lower()."""
        s = self.serve.slots
        return self._decode.lower(
            self._params, self._pool,
            jax.ShapeDtypeStruct((s,), jnp.int32),
            jax.ShapeDtypeStruct((s,), jnp.int32),
            jax.ShapeDtypeStruct((s,), jnp.bool_),
        ).compile().as_text()

    def warmup(self) -> None:
        """Compile both steps (one throwaway request through prefill +
        decode) so served latency measures the engine, not XLA. The pool
        slot it dirties is freed and overwritten on first real use."""
        plen = min(2, self.serve.max_prompt_len)
        self.submit(Request(
            rid=-1, prompt=np.zeros((plen,), np.int32), max_new_tokens=1
        ))
        while not self.scheduler.idle:
            self.tick()

    def decode_requests(self, requests: Sequence[Request],
                        poll_every: int = 0) -> List[Completion]:
        """Closed-loop drive: submit everything, tick to idle. With
        ``poll_every`` > 0, poll for a checkpoint rollover every that
        many ticks (tests use this to pin the drain semantics)."""
        for r in requests:
            self.submit(r)
        out: List[Completion] = []
        ticks = 0
        while not self.scheduler.idle or self._pending is not None:
            out.extend(self.tick())
            ticks += 1
            if poll_every and ticks % poll_every == 0:
                self.poll_rollover()
        return sorted(out, key=lambda c: c.rid)


def checkpoint_model(raw: dict, compute_dtype) -> Tuple[TransformerConfig, Dict]:
    """Rebuild (TransformerConfig, params tree) from a train_lm raw
    checkpoint dict. Dense models only — MoE decode needs the roomy-
    capacity expert mixture and is not in the serving engine yet."""
    m = raw["model"]
    if m.get("kind", "dense") != "dense":
        raise ValueError(
            "the serving engine decodes dense LM checkpoints only "
            f"(checkpoint kind: {m.get('kind')!r})"
        )
    cfg = TransformerConfig(
        vocab_size=int(m["vocab_size"]),
        dim=int(m["dim"]),
        depth=int(m["depth"]),
        heads=int(m["heads"]),
        mlp_ratio=int(m["mlp_ratio"]),
        max_seq_len=int(m["max_seq_len"]),
        compute_dtype=compute_dtype,
    )
    params = jax.tree.map(np.asarray, listify_raw(raw["params"]))
    return cfg, params
