"""Version compatibility gates for the pinned jax in this image.

The engine is written against the modern `jax.shard_map(..., check_vma=)`
API; the image pins jax 0.4.37, where shard_map still lives in
`jax.experimental.shard_map` and the replication-checking knob is called
`check_rep`. Installing the alias here (imported from the package
__init__, so every entry point gets it before any step factory runs)
keeps the production modules written against the current API while the
pinned interpreter still works — the same stub-don't-vendor rule the
Pallas kernels follow for interpret mode.
"""

from __future__ import annotations

import functools

import jax


def _install_shard_map_alias() -> None:
    if hasattr(jax, "shard_map"):
        return

    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @functools.wraps(_legacy_shard_map)
    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True,
                  **kwargs):
        if f is None:
            return functools.partial(
                shard_map, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=check_vma, **kwargs,
            )
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kwargs,
        )

    jax.shard_map = shard_map


def _install_axis_size_alias() -> None:
    from jax import lax

    if hasattr(lax, "axis_size"):
        return

    from jax._src import core as _core

    def axis_size(axis_name):
        if isinstance(axis_name, (tuple, list)):
            size = 1
            for a in axis_name:
                size *= _core.axis_frame(a)
            return size
        return _core.axis_frame(axis_name)

    lax.axis_size = axis_size


_install_shard_map_alias()
_install_axis_size_alias()
