"""Trace-only cost model: a modeled step time for any candidate config.

Everything here is computable on CPU in seconds with NOTHING executing —
the inputs are the same jaxpr-level measurements the contract checker
already takes (check/walker.py collective accounting, check/opcount.py
update-path ops, parallel/overlap.py schedule freedom), priced by a
DECLARED hardware profile (link bandwidths + per-collective launch cost
+ per-op update cost + a per-model compute floor).

The step-time estimate follows the analytical model of "On the Utility
of Gradient Compression in Distributed Training Systems" (PAPERS.md):
communication only costs walltime where it cannot hide behind compute,
so

    modeled_step_s = compute_s
                   + update_path_ops * op_cost_s
                   + comm_s * (1 - overlap_headroom)

where ``comm_s`` is the alpha-beta collective time (per-row: algorithm
factor x bytes / link bandwidth + count x launch cost — the same factor
table tools/predicted_scaling.py uses) and ``overlap_headroom`` is the
jaxpr schedule-freedom probe's mean independent fraction (what a
latency-hiding scheduler MAY run beside the wire). A measured probe can
substitute its span-derived dispatch fraction for the jaxpr headroom
(``modeled_step_seconds`` is the one formula both paths share).

This is a RANKING model, not a simulator: absolute seconds inherit every
caveat of runs/predicted_scaling.json's alpha-beta pricing, but the
orderings it produces are pinned against evidence the repo has already
banked (tests/test_tune.py: per-leaf vs bucketed collective counts from
runs/comm_contract.json, serial vs pipelined headroom from
runs/overlap_ab.json, and the homomorphic wire ranking <= its dequant
twin on the ResNet18 int8 leg).

The ``wire_domain`` knob (§6h) needs no special term: a homomorphic
candidate's narrowed accumulator psum (int16 vs int32), dropped round-2
scale rows, and int8 hierarchical reassembly all land in its OWN traced
byte rows, so ``comm_seconds_from_rows`` prices the compressed-domain
wire exactly the way PSC104 accounts it.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

# the committed alpha-beta model whose link numbers the default profile
# inherits (tools/predicted_scaling.py wrote it; tests pin the format)
DEFAULT_SCALING_MODEL = "runs/predicted_scaling.json"

# per-device single-step compute floor, seconds, by network — the
# measured single-chip step time divided across the mesh. Sources:
# ResNet18 b1024: runs/predicted_scaling.json model.t1_seconds (itself
# from runs/tpu_r03/bench_resnet18.json); LeNet b8192:
# runs/tpu_r03/bench_lenet.json (8192 images / 1156512.8 images/sec).
# Used only when the scaling-model file is absent or names no t1 for
# the network — the profile always records which source it used.
_T1_FALLBACK_S = {"ResNet18": 6.693e-2, "LeNet": 7.083e-3}

# collective algorithm factors over a group of size g (ring schedules;
# the same table tools/predicted_scaling.py prices HLO ops with):
# all-reduce moves 2(g-1)/g of the payload per link, one-shot
# gather/scatter/all_to_all (g-1)/g, permute 1.
_ALL_REDUCE_KINDS = ("psum", "pmax", "pmin", "pmean")
_ONE_SHOT_KINDS = ("psum_scatter", "all_gather", "all_to_all")


def _kind_factor(kind: str, g: int) -> float:
    if g <= 1:
        return 0.0
    if kind in _ALL_REDUCE_KINDS:
        return 2.0 * (g - 1) / g
    if kind in _ONE_SHOT_KINDS:
        return (g - 1) / g
    return 1.0  # ppermute and anything exotic: one payload per link


@dataclasses.dataclass(frozen=True)
class HardwareProfile:
    """The declared hardware a candidate is priced for.

    ``collective_launch_s`` is the fixed cost of ONE collective
    (dispatch + rendezvous latency) — the term that separates a
    62-collective per-leaf wire from an 11-bucket fused one even when
    both move the same bytes. ``op_cost_s`` prices one update-path
    jaxpr equation (the term the flat state layout collapses 386 -> 120
    on ResNet18). ``compute_s`` is the per-step forward+backward floor
    communication hides behind."""

    name: str = "tpu_v5e_defaults"
    ici_gbs: float = 45.0           # one-way per-link GB/s
    dcn_gbs: float = 12.5           # per-host GB/s
    collective_launch_s: float = 2e-5
    op_cost_s: float = 2e-7
    compute_s: float = 0.0
    source: str = "builtin defaults"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def load_hardware_profile(
    network: str,
    num_workers: int,
    path: Optional[str] = None,
    ici_gbs: Optional[float] = None,
    dcn_gbs: Optional[float] = None,
) -> HardwareProfile:
    """Profile with link numbers from the committed scaling model
    (runs/predicted_scaling.json "model" block) when present, builtin
    fallbacks otherwise; explicit ``ici_gbs``/``dcn_gbs`` always win.
    ``compute_s`` = the network's single-chip step time / num_workers
    (perfect compute scaling is assumed — the error is common to every
    candidate of one search, so rankings are unaffected)."""
    path = path or DEFAULT_SCALING_MODEL
    base = HardwareProfile()
    ici, dcn = base.ici_gbs, base.dcn_gbs
    t1 = _T1_FALLBACK_S.get(network)
    source = f"builtin defaults (no {path})"
    if os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                model = json.load(f).get("model", {})
            ici = float(model.get("ici_gbs_one_way", ici))
            dcn = float(model.get("dcn_gbs_per_host", dcn))
            if network == "ResNet18" and "t1_seconds" in model:
                t1 = float(model["t1_seconds"])
                source = path
            else:
                # the file priced only the links; say where t1 came from
                # instead of claiming the whole profile came from it
                source = f"{path} (links); builtin t1 for {network}"
        except (OSError, ValueError):
            source = f"builtin defaults (unreadable {path})"
    if t1 is None:
        # an unknown network still ranks: wire/schedule/op terms are the
        # candidate-dependent part, the floor just offsets them all
        t1 = 0.0
    return HardwareProfile(
        ici_gbs=ici_gbs if ici_gbs is not None else ici,
        dcn_gbs=dcn_gbs if dcn_gbs is not None else dcn,
        compute_s=t1 / max(num_workers, 1),
        source=source,
    )


def comm_seconds_from_rows(
    rows: Sequence[dict],
    axis_sizes: Dict[str, int],
    profile: HardwareProfile,
) -> float:
    """Alpha-beta collective time for accounting rows shaped like the
    pscheck artifact's (``{kind, axes, dtype, count, bytes}`` — bytes
    TOTAL across the row's count). Rows riding a DCN axis are priced on
    the per-host NIC; pure-ICI rows on the ICI link."""
    total = 0.0
    for row in rows:
        g = 1
        for ax in row.get("axes", ()):
            g *= int(axis_sizes.get(ax, 1))
        gbs = (
            profile.dcn_gbs
            if any(ax == "dcn" for ax in row.get("axes", ()))
            else profile.ici_gbs
        )
        total += _kind_factor(row["kind"], g) * row["bytes"] / (gbs * 1e9)
        total += int(row["count"]) * profile.collective_launch_s
    return total


def precision_mix_fraction(
    tags: Sequence[int],
    sizes: Sequence[int],
    hi_peak: int,
) -> float:
    """Effective-over-static wire fraction for an adaptive-precision tag
    vector: the bytes a byte-honest transport ships under ``tags``
    (resilience.precision.effective_wire_bytes — skip 0, 4-bit half,
    int8 one, hi the minimal width holding ``hi_peak``) divided by the
    static-int8 baseline of one byte per element. The controller's tag
    histogram prices to a single scalar the expected-mixed comm model
    can scale the traced wire with; > 1.0 is legal (HI tags on a wide
    payload cost more than int8)."""
    from ..resilience.precision import effective_wire_bytes

    sizes = np.asarray(sizes, np.int64)
    static = float(sizes.sum())  # static int8: 1 byte / element
    if static <= 0:
        return 1.0
    return effective_wire_bytes(tags, sizes, hi_peak) / static


def expected_mixed_comm_seconds(
    rows: Sequence[dict],
    axis_sizes: Dict[str, int],
    profile: HardwareProfile,
    fraction: float,
) -> float:
    """Alpha-beta comm time for an adaptive-precision candidate whose
    quantized gradient payload ships ``fraction`` of its traced bytes
    (``precision_mix_fraction``). Only integer-dtype rows scale — the
    quantized wire is the step's integer traffic (int8 a2a/gather
    payloads, the homomorphic accumulator psum), while float rows
    (block scales, bucket peaks, the telemetry pmean) and every launch
    cost are tag-invariant. The tiny int32 guard pmin rides the scaled
    set; at 4 bytes the mispricing is below the model's noise floor.

    PSC108's stance makes this an EXPECTED time, not a traced one: the
    traced program's physical bytes never change with the tags, so the
    artifact rows stay honest and this projection is the autotuner's
    view of what a byte-honest transport would realise."""
    if fraction < 0.0:
        raise ValueError(f"fraction must be >= 0, got {fraction}")
    scaled = []
    for row in rows:
        dt = str(row.get("dtype", ""))
        if dt.startswith(("int", "uint")):
            row = dict(row)
            row["bytes"] = row["bytes"] * fraction
        scaled.append(row)
    return comm_seconds_from_rows(scaled, axis_sizes, profile)


def modeled_step_seconds(
    comm_s: float,
    overlap_headroom: Optional[float],
    update_path_ops: int,
    profile: HardwareProfile,
) -> float:
    """THE step-time formula (module docstring). Shared by the
    trace-only path (jaxpr headroom) and the probe-calibrated path
    (measured dispatch fraction) so the two can never drift."""
    exposed = comm_s * (1.0 - (overlap_headroom or 0.0))
    return profile.compute_s + update_path_ops * profile.op_cost_s + exposed


@dataclasses.dataclass(frozen=True)
class CandidateCost:
    """One candidate's modeled cost plus every input that produced it —
    the record stores the inputs so the regression gate can re-derive
    ``modeled_step_s`` through the live formula and catch the model and
    the banked artifact drifting apart."""

    comm_rows: List[dict]           # full per-(kind,axes,dtype) accounting
    wire_bytes: int                 # gradient-path reduce bytes (PSC102 set)
    n_collectives: int              # every collective eqn in the step
    n_grad_reduces: int             # reduce-kind eqns feeding the params
    update_path_ops: int            # jaxpr eqns downstream of the reduce
    overlap_headroom: Optional[float]   # mean independent fraction
    mean_dispatch_prefix: Optional[float]
    comm_s: float
    exposed_comm_s: float
    compute_s: float
    update_s: float
    modeled_step_s: float

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        for k in ("comm_s", "exposed_comm_s", "compute_s", "update_s",
                  "modeled_step_s"):
            d[k] = round(d[k], 9)
        return d


def model_cost(
    result,
    profile: HardwareProfile,
    axis_sizes: Dict[str, int],
) -> CandidateCost:
    """Cost one traced candidate (a check/core.TraceResult carrying its
    ClosedJaxpr via ``trace_spec(keep_jaxpr=True)``)."""
    from ..check.opcount import update_path_ops_from
    from ..check.walker import REDUCE_KINDS
    from ..parallel.overlap import overlap_headroom_from

    if result.closed is None:
        raise ValueError(
            "model_cost needs the candidate's traced jaxpr — trace with "
            "trace_spec(spec, keep_jaxpr=True)"
        )
    comm_s = comm_seconds_from_rows(result.summary, axis_sizes, profile)
    wire_bytes = sum(
        c.bytes for c in result.collectives
        if c.feeds_params and c.kind in REDUCE_KINDS
    )
    n_grad = sum(
        1 for c in result.collectives
        if c.feeds_params and c.kind in REDUCE_KINDS
    )
    headrep = overlap_headroom_from(result.closed)
    headroom = headrep.get("overlap_headroom")
    ops = update_path_ops_from(result.closed)
    exposed = comm_s * (1.0 - (headroom or 0.0))
    update_s = ops * profile.op_cost_s
    return CandidateCost(
        comm_rows=list(result.summary),
        wire_bytes=wire_bytes,
        n_collectives=sum(int(r["count"]) for r in result.summary),
        n_grad_reduces=n_grad,
        update_path_ops=ops,
        overlap_headroom=headroom,
        mean_dispatch_prefix=headrep.get("mean_dispatch_prefix"),
        comm_s=comm_s,
        exposed_comm_s=exposed,
        compute_s=profile.compute_s,
        update_s=update_s,
        modeled_step_s=modeled_step_seconds(comm_s, headroom, ops, profile),
    )
