"""Self-tuning subsystem (ARCHITECTURE §7h): pick the wire/schedule/
layout knobs from evidence instead of operator folklore.

Three layers:

- ``costmodel``: a trace-only analytical cost model — for any candidate
  ``PSConfig``, wire bytes + collective counts (check/walker.py), the
  update-path op count (check/opcount.py), and schedule freedom
  (parallel/overlap.py) combine with a declared hardware profile into a
  modeled step time. CPU-only, seconds per candidate, nothing executes.
- ``search``: the knob-grid driver — candidates are validated by the
  PSC101-109 contract rules BEFORE they are costed (broken configs are
  pruned with the finding attached, never crashed on), survivors are
  ranked by modeled cost, and the top-K can optionally run short
  measured probes whose span-derived overlap fractions feed back into
  the model.
- ``tools/autotune.py``: the operator CLI; emits a ranked, schema-
  validated ``runs/autotune_<model>.json`` evidence record plus a
  ready-to-paste flag line that ``cli/train --config-json`` applies.
"""

from .costmodel import (
    CandidateCost,
    HardwareProfile,
    comm_seconds_from_rows,
    load_hardware_profile,
    model_cost,
    modeled_step_seconds,
)
from .search import build_grid, Knobs, run_search

__all__ = [
    "CandidateCost",
    "HardwareProfile",
    "Knobs",
    "build_grid",
    "comm_seconds_from_rows",
    "load_hardware_profile",
    "model_cost",
    "modeled_step_seconds",
    "run_search",
]
