"""Contract-guarded knob search: enumerate candidate configs, prune the
broken ones with the PSC101-109 rules, rank the survivors by modeled
cost, optionally calibrate the top-K with short measured probes.

The pipeline per candidate:

1. build a ``ContractSpec`` for the knob point through the SAME spec
   constructor the committed registry uses (check/contracts._ps_spec) —
   the candidate's declared invariants (grad-reduce kinds, wire dtype
   policy, fusion budget, overlap twin) are derived from its knobs
   exactly like a registry entry's would be;
2. trace the REAL train step (check/core.trace_spec, CPU-only, nothing
   executes) and run the contract rules on it. A config the engine
   refuses to construct (e.g. a pipelined per-leaf wire) or whose trace
   violates a rule (e.g. block-scale rows overflowing the declared
   PSC103 scale allowance on a fused 2-round wire) is PRUNED with the
   reason attached — contracts are search constraints, not crashes;
3. cost the survivors with the trace-only model (tune/costmodel.py) and
   rank ascending by modeled step time;
4. optionally run short measured probes on the top-K (real steps on the
   live backend, bench.py's warmup/sync discipline, an in-memory obs
   tracer splitting dispatch vs sync) — the span-derived overlap
   fraction feeds back into the SAME step-time formula as a calibrated
   estimate, and every probe stamps its backend so mixed-backend
   comparisons are refused, never averaged.

The emitted record (runs/autotune_<model>.json) is schema-validated
(obs/schema.py kind "autotune", run_header included) and carries, for
the best candidate, a ready-to-paste flag line that
``cli/train --config-json`` applies directly.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .costmodel import (
    HardwareProfile,
    load_hardware_profile,
    model_cost,
    modeled_step_seconds,
)

# knob-space presets per tuned model. ``buckets`` carries the model's
# bucket-granularity ladder (None = legacy per-leaf, 0 = one fused
# buffer, N = ~N-byte buckets — 64 KiB suits LeNet's ~1.7 MB payload,
# 4 MiB the ResNet18 ~44.7 MB one, mirroring the registry's entries).
MODELS: Dict[str, Dict[str, Any]] = {
    "lenet": {
        "network": "LeNet",
        "dataset": "MNIST",
        "buckets": (None, 0, 64 << 10),
        "probe_batch": 64,
    },
    "resnet18": {
        "network": "ResNet18",
        "dataset": "Cifar10",
        "buckets": (None, 0, 4 << 20),
        "probe_batch": 64,
    },
}

# the banked regression-gate margin: the tuned config's MODELED step
# time must beat the CLI-default config's by at least this factor
# (tests/test_tune.py pins the committed runs/autotune_resnet18.json
# against it). A conservative floor well under the observed margin
# (1.077x at the committed profile), so legitimate model refinements
# don't trip the gate while a regression that ranks the default near
# the top does. LeNet has no gate: at a ~1.7 MB payload the model
# honestly ranks the default per-leaf f32 wire near-optimal (collective
# launch cost dominates, quantization overhead doesn't pay).
GATE_MIN_SPEEDUP = {"resnet18": 1.03}


@dataclasses.dataclass(frozen=True)
class Knobs:
    """One point of the declared knob space (the searchable subset of
    PSConfig — mesh-geometry and serving knobs are future axes)."""

    compress: Optional[str] = None      # None | "int8" | "int8_2round"
    bucket_bytes: Optional[int] = None  # None = per-leaf, 0 = fused, N
    overlap: str = "serial"             # "serial" | "pipelined"
    opt_placement: str = "replicated"   # "replicated" | "sharded"
    quant_block_size: int = 0
    state_layout: str = "flat"
    wire_domain: str = "dequant"        # "dequant" | "homomorphic"

    def bucket_tag(self) -> str:
        bb = self.bucket_bytes
        if not bb:
            return ""  # per-leaf has no _bucketed suffix; fused no tag
        return f"{bb >> 10}k" if bb % 1024 == 0 else str(bb)

    def flags(self, network: str, dataset: str) -> Dict[str, Any]:
        """The exact cli/train flag assignment reproducing this point
        (the --config-json round-trip surface)."""
        return {
            "--network": network,
            "--dataset": dataset,
            "--compress-grad": {
                None: "none", "int8": "compress", "int8_2round": "2round",
            }[self.compress],
            "--bucket-bytes": (
                -1 if self.bucket_bytes is None else self.bucket_bytes
            ),
            "--overlap": "on" if self.overlap == "pipelined" else "off",
            "--opt-placement": self.opt_placement,
            "--quant-block-size": self.quant_block_size,
            "--state-layout": self.state_layout,
            "--wire-domain": self.wire_domain,
        }

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def flag_line(flags: Dict[str, Any]) -> str:
    return " ".join(f"{k} {v}" for k, v in flags.items())


DEFAULT_KNOBS = Knobs()  # == cli/train defaults: per-leaf f32 serial


def build_grid(model: str, grid: str = "default") -> List[Knobs]:
    """The declared knob grid for one model.

    - ``default``: the full compress x bucket x overlap x placement
      product (sharded skips the per-leaf rung — its wire is flat by
      construction, so None would duplicate the fused point), plus two
      showcase points: the fused 2-round wire with block-32 scales
      (PSC103 prunes it — scale rows overflow the declared allowance)
      and the flagship quantized bucketed config in the legacy tree
      state layout (the update-path op term separates the twins).
    - ``smoke``: a trimmed replicated-only LeNet-scale grid for
      tools/smoke.sh — still contains config-invalid AND
      contract-pruned points.
    - ``tiny``: the test grid (tests/test_tune.py) — one of everything.
    """
    preset = MODELS[model]
    per_leaf, fused, bucketed = preset["buckets"]
    out: List[Knobs] = []
    if grid == "default":
        for compress in (None, "int8", "int8_2round"):
            for bb in preset["buckets"]:
                for overlap in ("serial", "pipelined"):
                    for placement in ("replicated", "sharded"):
                        if placement == "sharded" and bb is None:
                            continue
                        out.append(Knobs(
                            compress=compress, bucket_bytes=bb,
                            overlap=overlap, opt_placement=placement,
                        ))
        out.append(Knobs(compress="int8_2round", bucket_bytes=fused,
                         quant_block_size=32))
        out.append(Knobs(compress="int8", bucket_bytes=bucketed,
                         state_layout="tree"))
        # the wire_domain axis (§6h): the compressed-domain twins of the
        # quantized points — the model prices the narrowed psum / the
        # dropped f32 rows straight from the candidates' own traced
        # accounting
        out.append(Knobs(compress="int8", bucket_bytes=bucketed,
                         wire_domain="homomorphic"))
        out.append(Knobs(compress="int8", bucket_bytes=bucketed,
                         overlap="pipelined", wire_domain="homomorphic"))
        out.append(Knobs(compress="int8_2round", bucket_bytes=fused,
                         wire_domain="homomorphic"))
        return out
    if grid == "smoke":
        for compress in (None, "int8"):
            for bb in preset["buckets"]:
                for overlap in ("serial", "pipelined"):
                    out.append(Knobs(compress=compress, bucket_bytes=bb,
                                     overlap=overlap))
        out.append(Knobs(compress="int8_2round", bucket_bytes=fused,
                         quant_block_size=32))
        out.append(Knobs(compress="int8_2round", bucket_bytes=bucketed))
        out.append(Knobs(compress="int8", bucket_bytes=fused,
                         wire_domain="homomorphic"))
        return out
    if grid == "tiny":
        return [
            Knobs(),                                        # the default
            Knobs(compress=None, bucket_bytes=fused),
            Knobs(compress="int8", bucket_bytes=fused),
            Knobs(compress="int8", bucket_bytes=bucketed),
            Knobs(compress="int8", bucket_bytes=bucketed,
                  overlap="pipelined"),
            Knobs(compress="int8", bucket_bytes=bucketed,
                  wire_domain="homomorphic"),
            Knobs(compress="int8", overlap="pipelined"),    # config-invalid
            Knobs(compress=None,
                  wire_domain="homomorphic"),               # config-invalid
            Knobs(compress="int8_2round", bucket_bytes=fused,
                  quant_block_size=32),                     # PSC103-pruned
        ]
    raise ValueError(f"unknown grid {grid!r} (default, smoke, tiny)")


def spec_for(knobs: Knobs, network: str):
    """The candidate's ContractSpec, built by the registry's own spec
    constructor so declared invariants can't drift from the committed
    entries' derivation."""
    from ..check.contracts import _ps_spec

    return _ps_spec(
        knobs.compress,
        knobs.opt_placement,
        bucket_bytes=knobs.bucket_bytes,
        network=network,
        state_layout=knobs.state_layout,
        overlap=knobs.overlap,
        bucket_tag=knobs.bucket_tag(),
        quant_block_size=knobs.quant_block_size,
        wire_domain=knobs.wire_domain,
    )


def backend_info() -> Dict[str, Optional[str]]:
    """The live jax backend identity every probe (and bench record)
    stamps: platform + device kind. CPU-fallback evidence must never be
    indistinguishable from TPU evidence again (BENCH_r05)."""
    import jax

    devs = jax.devices()
    return {
        "platform": jax.default_backend(),
        "device_kind": (
            str(getattr(devs[0], "device_kind", "unknown")) if devs else None
        ),
    }


def require_same_backend(records: Sequence[Dict[str, Any]]) -> None:
    """Refuse to compare measurements taken on different backends."""
    seen = {
        (r.get("platform"), r.get("device_kind"))
        for r in records if r is not None
    }
    if len(seen) > 1:
        # str-keyed sort: a missing stamp is (None, None) and None does
        # not order against str
        raise SystemExit(
            f"refusing to compare measurements across backends: "
            f"{sorted(seen, key=str)} — re-run the probes on one backend"
        )


def measure_probe(
    knobs: Knobs,
    network: str,
    dataset: str,
    steps: int = 4,
    batch: int = 64,
) -> Dict[str, Any]:
    """One short measured probe: real steps on the live backend with
    bench.py's sync discipline (host reads, not block_until_ready) and
    an in-memory span tracer splitting dispatch from sync. Returns the
    measured step time, the span-derived overlap fraction, and the
    backend stamp."""
    import jax

    from ..data import IMAGE_SHAPES, make_preprocessor, make_synthetic
    from ..models import build_model
    from ..obs import Tracer, summarize_spans
    from ..optim import build_optimizer
    from ..parallel import (
        init_ps_state,
        make_mesh,
        make_ps_train_step,
        shard_batch,
        shard_state,
    )
    from ..parallel.ps import PSConfig
    from ..utils import host_sync

    n_dev = len(jax.devices())
    mesh = make_mesh(num_workers=n_dev)
    cfg = PSConfig(
        num_workers=n_dev,
        compress=knobs.compress,
        bucket_bytes=knobs.bucket_bytes,
        overlap=knobs.overlap,
        opt_placement=knobs.opt_placement,
        quant_block_size=knobs.quant_block_size,
        state_layout=knobs.state_layout,
        wire_domain=knobs.wire_domain,
    )
    tx = build_optimizer(
        "sgd", 0.01, momentum=0.9, flat=(knobs.state_layout == "flat")
    )
    model = build_model(network)
    ds = make_synthetic(dataset, train_size=batch, test_size=8, seed=0)
    data = {"image": ds.train_images, "label": ds.train_labels}
    pre = make_preprocessor(dataset, train=True)
    state = init_ps_state(
        model, tx, cfg, jax.random.key(0), IMAGE_SHAPES[dataset]
    )
    state = shard_state(state, mesh, cfg)
    step = make_ps_train_step(model, tx, cfg, mesh, preprocess=pre)
    sharded = shard_batch(data, mesh, cfg)
    key = jax.random.key(1)
    # warmup: compile + one steady-state step, then a full host sync so
    # the timed window starts with an idle device
    for _ in range(2):
        state, metrics = step(state, sharded, key)
    host_sync(state.params, metrics)
    tracer = Tracer("autotune_probe", path=None)
    t0 = time.perf_counter()
    for _ in range(steps):
        with tracer.span("dispatch"):
            state, metrics = step(state, sharded, key)
        with tracer.span("sync"):
            host_sync(state.params, metrics)
    elapsed = time.perf_counter() - t0
    spans = summarize_spans(tracer.drain())
    d = spans.get("dispatch", {}).get("total_s", 0.0)
    y = spans.get("sync", {}).get("total_s", 0.0)
    return {
        "measured_step_s": round(elapsed / steps, 6),
        "overlap_fraction_spans": (
            round(d / (d + y), 4) if (d + y) > 0 else None
        ),
        "steps": steps,
        "batch": batch,
        **backend_info(),
    }


def _prune_entry(knobs: Knobs, name: Optional[str], stage: str,
                 reason: str, rules: Sequence[str] = ()) -> dict:
    return {
        "name": name,
        "knobs": knobs.to_json(),
        "stage": stage,          # "config" | "contract" | "trace"
        "rules": sorted(set(rules)),
        "reason": reason,
    }


def run_search(
    model: str,
    grid: str = "default",
    profile: Optional[HardwareProfile] = None,
    probe_top: int = 0,
    probe_steps: int = 4,
    progress=None,
) -> dict:
    """The full search: enumerate -> prune-by-contract -> cost -> rank
    [-> probe top-K]. Returns the evidence record (schema-validated,
    run_header included); the caller owns writing it to disk."""
    from ..check.contracts import MESH_DEVICES
    from ..check.core import trace_spec
    from ..check.rules import check_result, psc109_schedule
    from ..obs.schema import run_header, validate_event

    say = progress or (lambda *_: None)
    preset = MODELS[model]
    network, dataset = preset["network"], preset["dataset"]
    # candidates trace on the contract registry's virtual mesh, so the
    # model prices THAT geometry (probes run on the live devices and
    # stamp their backend separately)
    n_dev = MESH_DEVICES
    axis_sizes = {"workers": n_dev}
    if profile is None:
        profile = load_hardware_profile(network, n_dev)

    t_start = time.perf_counter()
    points = build_grid(model, grid)
    pruned: List[dict] = []
    traced: List[Tuple[Knobs, Any]] = []  # (knobs, TraceResult)
    for kn in points:
        try:
            spec = spec_for(kn, network)
            result = trace_spec(spec, keep_jaxpr=True)
        except ValueError as e:
            # the engine itself refuses the combination (e.g. a
            # pipelined per-leaf wire) — pruned at construction
            pruned.append(_prune_entry(kn, None, "config", str(e)))
            say(f"prune [config] {kn.to_json()}: {e}")
            continue
        except Exception as e:  # noqa: BLE001 - a candidate must never
            # crash the search; an unbuildable point is a pruned point
            pruned.append(_prune_entry(kn, None, "trace",
                                       f"{type(e).__name__}: {e}"))
            say(f"prune [trace] {kn.to_json()}: {e}")
            continue
        traced.append((kn, result))

    # contract rules as search constraints: per-result rules plus the
    # cross-result PSC109 schedule pins (serial twins are in the grid).
    # PSC104 is out of scope — candidates are not pinned in the
    # committed artifact; the registry gate owns that.
    findings_by_name: Dict[str, List] = {}
    for kn, r in traced:
        for f in check_result(r):
            findings_by_name.setdefault(f.config, []).append(f)
    for f in psc109_schedule([r for _, r in traced]):
        findings_by_name.setdefault(f.config, []).append(f)

    survivors: List[Tuple[Knobs, Any]] = []
    for kn, r in traced:
        hits = findings_by_name.get(r.spec.name, [])
        if hits:
            pruned.append(_prune_entry(
                kn, r.spec.name, "contract",
                "; ".join(f.message for f in hits),
                rules=[f.rule for f in hits],
            ))
            say(f"prune [contract] {r.spec.name}: "
                f"{sorted({f.rule for f in hits})}")
        else:
            survivors.append((kn, r))

    candidates: List[dict] = []
    for kn, r in survivors:
        cost = model_cost(r, profile, axis_sizes)
        candidates.append({
            "name": r.spec.name,
            "knobs": kn.to_json(),
            "flags": kn.flags(network, dataset),
            "cost": cost.to_json(),
        })
    candidates.sort(key=lambda c: c["cost"]["modeled_step_s"])
    for rank, c in enumerate(candidates):
        c["rank"] = rank
    say(f"{len(candidates)} candidate(s) ranked, {len(pruned)} pruned")

    if probe_top > 0 and candidates:
        probes = []
        for c in candidates[:probe_top]:
            kn = Knobs(**c["knobs"])
            say(f"probe {c['name']} ({probe_steps} steps)")
            probe = measure_probe(
                kn, network, dataset,
                steps=probe_steps, batch=preset["probe_batch"],
            )
            c["probe"] = probe
            # feed the MEASURED dispatch fraction back through the same
            # step-time formula the trace-only estimate used
            c["cost"]["modeled_step_probe_s"] = round(modeled_step_seconds(
                c["cost"]["comm_s"],
                probe["overlap_fraction_spans"],
                c["cost"]["update_path_ops"],
                profile,
            ), 9)
            probes.append(probe)
        require_same_backend(probes)

    default_name = spec_for(DEFAULT_KNOBS, network).name
    default = next(
        (c for c in candidates if c["name"] == default_name), None
    )
    best = candidates[0] if candidates else None
    gate: Dict[str, Any] = {
        "min_modeled_speedup": GATE_MIN_SPEEDUP.get(model),
        "modeled_speedup": None,
    }
    if best and default:
        gate["modeled_speedup"] = round(
            default["cost"]["modeled_step_s"]
            / max(best["cost"]["modeled_step_s"], 1e-12), 4,
        )

    header = validate_event(run_header(
        "autotune",
        geometry={
            "workload": "autotune", "model": model, "devices": n_dev,
            "device_kind": backend_info()["device_kind"],
        },
    ))
    rec = {
        "kind": "autotune",
        "run": header,
        "model": model,
        "network": network,
        "grid": grid,
        "backend": backend_info(),
        "trace_only": probe_top == 0,
        "hardware_profile": profile.to_json(),
        "n_points": len(points),
        "n_candidates": len(candidates),
        "n_pruned": len(pruned),
        "elapsed_s": round(time.perf_counter() - t_start, 3),
        "gate": gate,
        "default": default,
        "best": (
            dict(best, flag_line=flag_line(best["flags"])) if best else None
        ),
        "candidates": candidates,
        "pruned": pruned,
    }
    return validate_event(rec)
