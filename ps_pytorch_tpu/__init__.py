"""ps_pytorch_tpu — a TPU-native synchronous parameter-server training framework.

A brand-new JAX/XLA/Pallas re-design (not a port) with the capabilities of the
reference mpi4py/PyTorch parameter-server implementation (see SURVEY.md):

- Models: LeNet, ResNet-18/34/50/101/152, VGG-11/13/16/19 (+/- BN)
  (reference: src/model_ops/*, src/util.py:8-19)
- Optimizers: SGD (momentum/nesterov/dampening/weight-decay) and Adam (AMSGrad)
  with PyTorch update semantics (reference: src/optim/sgd.py, src/optim/adam.py)
- Datasets: MNIST, CIFAR-10/100, SVHN with the reference's normalization and
  augmentation (reference: src/util.py:21-106) — augmentation runs on-device.
- Parameter-server data parallelism over a `jax.sharding.Mesh`: replicated
  params, per-worker gradients, `lax.psum` aggregation with partial
  ("backup-worker") num-aggregate masking, optional int8-quantized collectives
  (Pallas kernel) replacing Blosc compression, and a ZeRO-1 style sharded
  optimizer-state mode (the "PS chip" generalized to a sharded PS).
  (reference: src/sync_replicas_master_nn.py, src/distributed_worker.py,
   src/compression.py)
- Checkpointing with step-tagged single-writer checkpoints + actual resume,
  and an out-of-band polling evaluator (reference: src/distributed_evaluator.py).
"""

from . import _compat  # noqa: F401  (installs the jax.shard_map alias)

__version__ = "0.1.0"
