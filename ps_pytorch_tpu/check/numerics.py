"""psnumerics — precision-flow analysis over traced jaxprs (PSC111-114).

The walker (walker.py) measures WHERE the collectives are; this module
proves WHAT the quantized wire's numbers can be. A forward abstract
interpretation over the same traced jaxpr tracks, per variable,

  * an interval bound (``lo``/``hi``) — the worst-case value range on
    the integer lattice (int8 payloads enter at +-127 via the traced
    clamp; collectives and reductions multiply it by their traced
    summand counts),
  * scale provenance (``roots``) — the set of max-abs reductions
    (an ``abs`` feeding a ``reduce_max``) this value's scale chain
    descends from,
  * payload provenance (``sites``) — the set of quantization sites
    (bounded float->int converts) this value descends from, and
  * residual provenance (``deqs``) — the dequantization events it
    descends from (the error-feedback closure check, PSC112).

Call-likes (pjit / shard_map / remat / custom_{jvp,vjp}) are entered
exactly, mirroring the walker's 1:1 invar/outvar mapping. ``cond``
branches are joined exactly (one branch runs). ``scan``/``while`` carry
state is ITERATED to a provenance fixpoint with bounds dropped to
unknown — a value routed through a loop carry can never prove a bound,
so a numerics rule over it degrades to "cannot prove", never to a
vacuous pass; chains confined to a single iteration stay exact.

Quantization sites are keyed by their cumulative element offset on the
gradient path (``start_offset``) — the same flat-buffer coordinates the
bucketed wire uses — so per-bucket format decisions (ROADMAP item 1)
land on lattice state the analyzer already tracks per bucket.

Everything here is pure data over ``jax.core`` jaxprs: nothing
executes, no device is touched.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from .walker import _is_var, _open

# reduce-kind collectives (the walker's REDUCE_KINDS, by primitive name):
# outputs are "downstream of the gradient reduce" for PSC114
_REDUCE_PRIMS = {"psum", "psum_scatter", "reduce_scatter", "all_to_all"}

# call-like primitives entered with the exact 1:1 invar/outvar mapping
_EXACT_CALLS = {
    "pjit", "closed_call", "core_call", "xla_call", "remat", "remat2",
    "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "shard_map",
    "custom_lin",
}

_EMPTY: FrozenSet[int] = frozenset()


def _finfo_mant(dtype) -> Optional[int]:
    try:
        return int(np.finfo(np.dtype(dtype)).nmant) + 1  # + implicit bit
    except Exception:
        pass
    # np.finfo refuses extension floats (bfloat16, fp8) — those live in
    # ml_dtypes, which ships with jax and has its own finfo
    try:
        import ml_dtypes

        return int(ml_dtypes.finfo(np.dtype(dtype)).nmant) + 1
    except Exception:
        return None


def _int_cap(dtype) -> Optional[int]:
    try:
        if np.issubdtype(dtype, np.integer):
            return int(np.iinfo(dtype).max)
    except Exception:
        pass
    return None


def _is_int(dtype) -> bool:
    return bool(np.issubdtype(dtype, np.integer))


def _is_float(dtype) -> bool:
    return bool(np.issubdtype(dtype, np.inexact)) or (
        _finfo_mant(dtype) is not None)


def _narrows(src, dst) -> bool:
    """True when a convert src->dst can silently lose precision."""
    if np.issubdtype(dst, np.bool_) or np.issubdtype(src, np.bool_):
        return False
    if _is_int(dst) and _is_float(src):
        return True  # drops fractions; only a quantize site may do this
    if _is_int(src) and _is_int(dst):
        si, di = np.iinfo(src), np.iinfo(dst)
        return di.max < si.max or di.min > si.min
    if _is_float(src) and _is_float(dst):
        ms, md = _finfo_mant(src), _finfo_mant(dst)
        return md is not None and ms is not None and md < ms
    return False  # int -> float: lattice-aware check handled separately


# ------------------------------------------------------------------ events


@dataclasses.dataclass(frozen=True)
class QuantSite:
    """A bounded float->int (or narrowing int->int) convert: the traced
    truth of one quantization point on the wire lattice."""

    sid: int
    dtype: str                     # target integer dtype
    shape: Tuple[int, ...]
    size: int
    start_offset: int              # cumulative grad-path element offset
                                   # (the bucketed wire's flat coords)
    peak: Optional[float]          # clamp bound carried into the convert
    pre_peak: Optional[float]      # worst-case |value| BEFORE the clamp
                                   # (None: unbounded / unknown)
    roots: FrozenSet[int]          # max-abs reductions its scale chain saw
    primary: bool                  # quantizes fresh float (not a requant
                                   # of lattice payload: EF tracks these)
    conservative: bool             # inside a loop body
    feeds_params: bool = False


@dataclasses.dataclass(frozen=True)
class DequantEvent:
    """A multiply (or divide) of lattice payload by a scale, leaving the
    integer lattice: the point PSC111 audits for scale provenance."""

    did: int
    payload_sites: FrozenSet[int]
    scale_roots: FrozenSet[int]
    scale_literal: bool            # scale is a static constant
    conservative: bool
    feeds_params: bool = False


@dataclasses.dataclass(frozen=True)
class AccumEvent:
    """One integer accumulation (psum / psum_scatter / reduce_sum /
    narrowing convert / int->float mantissa exit) with its traced
    worst-case |sum| against the dtype's capacity."""

    kind: str                      # psum|psum_scatter|reduce_sum|convert
                                   # |mantissa
    dtype: str                     # accumulator / target dtype
    axes: Tuple[str, ...]          # collective axes ('' ops: empty)
    multiplier: Optional[int]      # summand count (None: unknown axis)
    peak_in: Optional[float]
    peak_out: Optional[float]
    capacity: Optional[int]
    lattice: bool                  # payload descends from a quant site
    conservative: bool
    feeds_params: bool = False


@dataclasses.dataclass(frozen=True)
class NarrowEvent:
    """A precision-narrowing convert_element_type (PSC114 raw material:
    the rule flags the ones downstream of the gradient reduce, on the
    update path, that are not declared quantize sites or allowances)."""

    src: str
    dst: str
    is_quant_site: bool
    downstream_of_reduce: bool
    conservative: bool
    feeds_params: bool = False


@dataclasses.dataclass(frozen=True)
class ResidualEvent:
    """A subtract whose subtrahend descends from a dequantization —
    the grad - dequant(quant(grad)) error-feedback residual shape."""

    rid: int
    covered_sites: FrozenSet[int]  # primary quant sites this closes
                                   # (minuend proven an ancestor-sharer)
    feeds_carry: bool              # reaches a non-param step output
    feeds_params: bool             # double-count hazard when True
    conservative: bool


@dataclasses.dataclass
class NumericsReport:
    """The full precision-flow record for one traced step."""

    sites: Tuple[QuantSite, ...]
    dequants: Tuple[DequantEvent, ...]
    accums: Tuple[AccumEvent, ...]
    narrows: Tuple[NarrowEvent, ...]
    residuals: Tuple[ResidualEvent, ...]
    axis_sizes: Dict[str, int]

    def grad_sites(self) -> List[QuantSite]:
        return [s for s in self.sites if s.feeds_params]


# ------------------------------------------------------------------- state


class _St:
    """Abstract value: interval + provenance. Mutated never; copied via
    ``_evolve``."""

    __slots__ = ("lo", "hi", "roots", "sites", "deqs", "is_abs", "pre",
                 "post", "tainted")

    def __init__(self, lo=None, hi=None, roots=_EMPTY, sites=_EMPTY,
                 deqs=_EMPTY, is_abs=False, pre=None, post=False,
                 tainted=False):
        self.lo = lo
        self.hi = hi
        self.roots = roots
        self.sites = sites
        self.deqs = deqs
        self.is_abs = is_abs
        self.pre = pre
        self.post = post
        self.tainted = tainted

    def peak(self) -> Optional[float]:
        if self.lo is None or self.hi is None:
            return None
        return max(abs(self.lo), abs(self.hi))


def _union(ins: Sequence[_St], lo=None, hi=None, is_abs=False,
           pre=None) -> _St:
    roots = _EMPTY
    sites = _EMPTY
    deqs = _EMPTY
    post = False
    tainted = False
    for s in ins:
        roots |= s.roots
        sites |= s.sites
        deqs |= s.deqs
        post = post or s.post
        tainted = tainted or s.tainted
    return _St(lo=lo, hi=hi, roots=roots, sites=sites, deqs=deqs,
               is_abs=is_abs, pre=pre, post=post, tainted=tainted)


def _join(a: _St, b: _St) -> _St:
    """Least upper bound: interval hull + provenance union."""
    lo = None if (a.lo is None or b.lo is None) else min(a.lo, b.lo)
    hi = None if (a.hi is None or b.hi is None) else max(a.hi, b.hi)
    pre = None if (a.pre is None or b.pre is None) else max(a.pre, b.pre)
    return _St(lo=lo, hi=hi, roots=a.roots | b.roots,
               sites=a.sites | b.sites, deqs=a.deqs | b.deqs,
               is_abs=a.is_abs and b.is_abs, pre=pre,
               post=a.post or b.post, tainted=a.tainted or b.tainted)


def _taint(s: _St) -> _St:
    """Loop-carry widening: keep provenance, drop every proven bound."""
    return _St(lo=None, hi=None, roots=s.roots, sites=s.sites,
               deqs=s.deqs, is_abs=False, pre=None, post=s.post,
               tainted=True)


def _prov_eq(a: _St, b: _St) -> bool:
    return (a.roots == b.roots and a.sites == b.sites and a.deqs == b.deqs
            and a.post == b.post)


def _scalar_of(s: _St) -> Optional[float]:
    """The statically-known scalar value, when the interval is a point."""
    if s.lo is not None and s.lo == s.hi:
        return s.lo
    return None


# ---------------------------------------------------------------- analyzer


class _Analyzer:
    def __init__(self, axis_sizes: Optional[Dict[str, int]] = None):
        self.axis_sizes: Dict[str, int] = dict(axis_sizes or {})
        self._forced_axes = frozenset(self.axis_sizes)
        self._preds: List[List[int]] = [[]]  # node 0: external constants
        self._sid = itertools.count()
        self._did = itertools.count()
        self._rid = itertools.count()
        self.sites: List[QuantSite] = []
        self._site_node: Dict[int, int] = {}
        self.dequants: List[DequantEvent] = []
        self._deq_node: Dict[int, int] = {}
        self._deq_payload: Dict[int, FrozenSet[int]] = {}
        self.accums: List[AccumEvent] = []
        self._accum_node: List[int] = []
        self.narrows: List[NarrowEvent] = []
        self._narrow_node: List[int] = []
        self.residuals: List[dict] = []   # resolved in finalize()
        self._loop_depth = 0
        self._anc_cache: Dict[int, FrozenSet[int]] = {}

    # -- graph ----------------------------------------------------------

    def _new_node(self, preds: Sequence[int]) -> int:
        self._preds.append(list(dict.fromkeys(preds)))
        return len(self._preds) - 1

    def _ancestors(self, starts: Sequence[int]) -> FrozenSet[int]:
        seen: set = set()
        stack = list(starts)
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._preds[n])
        return frozenset(seen)

    def _anc_of(self, node: int) -> FrozenSet[int]:
        got = self._anc_cache.get(node)
        if got is None:
            got = self._ancestors([node])
            self._anc_cache[node] = got
        return got

    # -- literal / const states ----------------------------------------

    def _const_state(self, val) -> _St:
        try:
            arr = np.asarray(val)
            if arr.size and arr.size <= 4096 and (
                np.issubdtype(arr.dtype, np.number)
                or np.issubdtype(arr.dtype, np.bool_)
            ):
                a = arr.astype(np.float64)
                if np.all(np.isfinite(a)):
                    return _St(lo=float(a.min()), hi=float(a.max()))
        except Exception:
            pass
        return _St()

    def _get(self, env, v) -> Tuple[_St, int]:
        if _is_var(v):
            got = env.get(v)
            if got is None:
                return _St(), 0  # untracked (e.g. dropvar reuse): unknown
            return got
        return self._const_state(v.val), 0

    # -- main recursion -------------------------------------------------

    def run_closed(self, closed) -> List[Tuple[_St, int]]:
        jaxpr = _open(closed)
        env: Dict[Any, Tuple[_St, int]] = {}
        for cv, cval in zip(jaxpr.constvars,
                            getattr(closed, "consts", ()) or ()):
            env[cv] = (self._const_state(cval), self._new_node([]))
        for cv in jaxpr.constvars:
            if cv not in env:
                env[cv] = (_St(), self._new_node([]))
        for iv in jaxpr.invars:
            env[iv] = (_St(), self._new_node([]))
        self._run(jaxpr, env, record=True)
        return [self._get(env, ov) for ov in jaxpr.outvars]

    def _bind_closed(self, sub, env: Dict[Any, Tuple[_St, int]]) -> Any:
        """Bind a ClosedJaxpr's constvars into env; return the open
        jaxpr."""
        inner = _open(sub)
        for cv, cval in zip(inner.constvars,
                            getattr(sub, "consts", ()) or ()):
            env[cv] = (self._const_state(cval), 0)
        for cv in inner.constvars:
            if cv not in env:
                env[cv] = (_St(), 0)
        return inner

    def _run(self, jaxpr, env: Dict[Any, Tuple[_St, int]],
             record: bool) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _EXACT_CALLS:
                self._exact_call(eqn, env, record)
            elif name == "scan":
                self._scan(eqn, env, record)
            elif name == "while":
                self._while(eqn, env, record)
            elif name == "cond":
                self._cond(eqn, env, record)
            else:
                self._eqn(eqn, env, record)

    def _exact_call(self, eqn, env, record: bool) -> None:
        name = eqn.primitive.name
        sub = None
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            sub = eqn.params.get(key)
            if sub is not None:
                break
        if sub is None:
            self._eqn(eqn, env, record)
            return
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            shape = getattr(mesh, "shape", None)
            if shape:
                for ax, size in dict(shape).items():
                    if str(ax) not in self._forced_axes:
                        self.axis_sizes[str(ax)] = int(size)
        inner_env: Dict[Any, Tuple[_St, int]] = {}
        inner = self._bind_closed(sub, inner_env)
        # walker convention: invars map 1:1, zipped from the END so
        # leading const-style invars of open jaxprs stay aligned
        n = min(len(eqn.invars), len(inner.invars))
        if n:
            for iv in inner.invars[:-n]:
                inner_env[iv] = (_St(), 0)
            for ov, iv in zip(eqn.invars[-n:], inner.invars[-n:]):
                inner_env[iv] = self._get(env, ov)
        else:
            for iv in inner.invars:
                inner_env[iv] = (_St(), 0)
        self._run(inner, inner_env, record)
        for ov, sv in zip(eqn.outvars, inner.outvars):
            if _is_var(ov):
                env[ov] = self._get(inner_env, sv)

    def _loop_body(self, body_closed, const_in, carry_in, xs_in, record):
        """Fixpoint a loop body: provenance grows to a fixed point with
        carry bounds dropped; events are recorded on the final pass."""
        inner_env: Dict[Any, Tuple[_St, int]] = {}
        body = self._bind_closed(body_closed, inner_env)
        carry = [_taint(s) for s, _ in carry_in]
        region = self._new_node(
            [n for _, n in list(const_in) + list(carry_in) + list(xs_in)]
        )
        ncarry = len(carry_in)
        for _ in range(4):
            env_i = dict(inner_env)
            vals = (list(const_in)
                    + [(c, region) for c in carry]
                    + [(s, n) for s, n in xs_in])
            for iv, v in zip(body.invars, vals):
                env_i[iv] = v
            self._run(body, env_i, record=False)
            outs = [self._get(env_i, ov) for ov in body.outvars]
            new_carry = [_join(c, _taint(o)) for c, (o, _) in
                         zip(carry, outs[:ncarry])]
            if all(_prov_eq(c, n2) for c, n2 in zip(carry, new_carry)):
                carry = new_carry
                break
            carry = new_carry
        # final recording pass
        self._loop_depth += 1
        env_f = dict(inner_env)
        vals = (list(const_in)
                + [(c, region) for c in carry]
                + [(s, n) for s, n in xs_in])
        for iv, v in zip(body.invars, vals):
            env_f[iv] = v
        self._run(body, env_f, record=record)
        self._loop_depth -= 1
        outs = [self._get(env_f, ov) for ov in body.outvars]
        # close the cycle: carry outputs feed the region node
        self._preds[region].extend(n for _, n in outs[:ncarry])
        return outs, region

    def _scan(self, eqn, env, record: bool) -> None:
        nconsts = eqn.params.get("num_consts", 0)
        ncarry = eqn.params.get("num_carry", 0)
        ins = [self._get(env, v) for v in eqn.invars]
        const_in = ins[:nconsts]
        carry_in = ins[nconsts:nconsts + ncarry]
        xs_in = ins[nconsts + ncarry:]
        outs, region = self._loop_body(
            eqn.params["jaxpr"], const_in, carry_in, xs_in, record
        )
        for i, ov in enumerate(eqn.outvars):
            if not _is_var(ov):
                continue
            if i < len(outs):
                st, node = outs[i]
                if i < ncarry:
                    st = _taint(st)  # the carried-out iterate
                env[ov] = (st, node)
            else:
                env[ov] = (_St(tainted=True), region)

    def _while(self, eqn, env, record: bool) -> None:
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        ins = [self._get(env, v) for v in eqn.invars]
        cond_consts = ins[:cn]
        body_consts = ins[cn:cn + bn]
        carry_in = ins[cn + bn:]
        outs, region = self._loop_body(
            eqn.params["body_jaxpr"], body_consts, carry_in, [], record
        )
        # run the cond once for event coverage (tainted carry)
        cond_env: Dict[Any, Tuple[_St, int]] = {}
        cond = self._bind_closed(eqn.params["cond_jaxpr"], cond_env)
        vals = list(cond_consts) + [(_taint(s), region)
                                    for s, _ in carry_in]
        self._loop_depth += 1
        for iv, v in zip(cond.invars, vals):
            cond_env[iv] = v
        self._run(cond, cond_env, record=record)
        self._loop_depth -= 1
        for i, ov in enumerate(eqn.outvars):
            if not _is_var(ov):
                continue
            if i < len(outs):
                st, node = outs[i]
                env[ov] = (_taint(st), node)
            else:
                env[ov] = (_St(tainted=True), region)

    def _cond(self, eqn, env, record: bool) -> None:
        branches = eqn.params.get("branches", ()) or ()
        operands = [self._get(env, v) for v in eqn.invars[1:]]
        joined: List[Optional[Tuple[_St, List[int]]]] = None
        for br in branches:
            br_env: Dict[Any, Tuple[_St, int]] = {}
            inner = self._bind_closed(br, br_env)
            for iv, v in zip(inner.invars, operands):
                br_env[iv] = v
            self._run(inner, br_env, record)
            outs = [self._get(br_env, ov) for ov in inner.outvars]
            if joined is None:
                joined = [(st, [node]) for st, node in outs]
            else:
                joined = [
                    (_join(a, st), nodes + [node])
                    for (a, nodes), (st, node) in zip(joined, outs)
                ]
        for i, ov in enumerate(eqn.outvars):
            if not _is_var(ov):
                continue
            if joined is not None and i < len(joined):
                st, nodes = joined[i]
                env[ov] = (st, self._new_node(nodes))
            else:
                env[ov] = (_St(), 0)

    # -- per-primitive transfer ----------------------------------------

    def _axis_mult(self, eqn) -> Optional[int]:
        ax = eqn.params.get("axes", None)
        if ax is None:
            ax = eqn.params.get("axis_name", None)
        if ax is None:
            return None
        if not isinstance(ax, (tuple, list)):
            ax = (ax,)
        mult = 1
        for a in ax:
            size = self.axis_sizes.get(str(a))
            if size is None:
                return None
            mult *= size
        return mult

    def _eqn_axes(self, eqn) -> Tuple[str, ...]:
        ax = eqn.params.get("axes", None)
        if ax is None:
            ax = eqn.params.get("axis_name", None)
        if ax is None:
            return ()
        if not isinstance(ax, (tuple, list)):
            ax = (ax,)
        return tuple(str(a) for a in ax)

    def _eqn(self, eqn, env, record: bool) -> None:
        name = eqn.primitive.name
        ins = [self._get(env, v) for v in eqn.invars]
        sts = [s for s, _ in ins]
        node = self._new_node([n for _, n in ins])
        self._in_nodes = [n for _, n in ins]
        conservative = self._loop_depth > 0
        out_dtype = None
        if eqn.outvars and hasattr(eqn.outvars[0], "aval"):
            aval = eqn.outvars[0].aval
            out_dtype = getattr(aval, "dtype", None)

        st = self._transfer(name, eqn, sts, out_dtype, node, record,
                            conservative)

        outs = eqn.outvars
        if name == "optimization_barrier" and len(outs) == len(sts):
            for ov, s in zip(outs, sts):
                if _is_var(ov):
                    env[ov] = (s, node)
            return
        for ov in outs:
            if _is_var(ov):
                env[ov] = (st, node)

    def _transfer(self, name, eqn, sts, out_dtype, node, record,
                  conservative) -> _St:
        s0 = sts[0] if sts else _St()

        if name == "convert_element_type":
            return self._convert(eqn, s0, out_dtype, node, record,
                                 conservative)

        if name in ("add", "add_any"):
            a, b = sts[0], sts[1]
            lo = None if (a.lo is None or b.lo is None) else a.lo + b.lo
            hi = None if (a.hi is None or b.hi is None) else a.hi + b.hi
            out = _union(sts, lo=lo, hi=hi)
            if (record and out_dtype is not None and _is_int(out_dtype)
                    and out.sites):
                cap = _int_cap(out_dtype)
                self.accums.append(AccumEvent(
                    kind="add", dtype=str(out_dtype), axes=(),
                    multiplier=2,
                    peak_in=max(p for p in (a.peak(), b.peak())
                                if p is not None)
                    if (a.peak() is not None or b.peak() is not None)
                    else None,
                    peak_out=out.peak(), capacity=cap,
                    lattice=True, conservative=conservative))
                self._accum_node.append(node)
            return out

        if name == "sub":
            a, b = sts[0], sts[1]
            lo = None if (a.lo is None or b.hi is None) else a.lo - b.hi
            hi = None if (a.hi is None or b.lo is None) else a.hi - b.lo
            out = _union(sts, lo=lo, hi=hi)
            if record and b.deqs:
                # the error-feedback residual shape: minuend - dequant(...)
                cand = _EMPTY
                for d in b.deqs:
                    cand |= self._deq_payload.get(d, _EMPTY)
                self.residuals.append({
                    "rid": next(self._rid),
                    "cand": cand,
                    "minuend_node": self._in_nodes[0],
                    "node": node,
                    "conservative": conservative,
                })
            return out

        if name == "mul":
            return self._mul(sts, out_dtype, node, record, conservative)

        if name == "div":
            return self._div(sts, out_dtype, node, record, conservative)

        if name == "neg":
            lo = None if s0.hi is None else -s0.hi
            hi = None if s0.lo is None else -s0.lo
            return _union(sts, lo=lo, hi=hi)

        if name in ("abs", "sign"):
            if name == "sign":
                return _union(sts, lo=-1.0, hi=1.0)
            p = s0.peak()
            return _union(sts, lo=0.0, hi=p, is_abs=True)

        if name in ("max", "min"):
            a, b = sts[0], sts[1]
            ka, kb = _scalar_of(a), _scalar_of(b)
            if name == "max":
                lo = (max(x for x in (a.lo, b.lo) if x is not None)
                      if (a.lo is not None or b.lo is not None) else None)
                hi = (None if (a.hi is None or b.hi is None)
                      else max(a.hi, b.hi))
            else:
                lo = (None if (a.lo is None or b.lo is None)
                      else min(a.lo, b.lo))
                hi = (min(x for x in (a.hi, b.hi) if x is not None)
                      if (a.hi is not None or b.hi is not None) else None)
            # clamp: remember the unclamped operand's peak for the
            # saturation check at the eventual requant convert
            pre = None
            if ka is not None and kb is None:
                pre = b.pre if b.pre is not None else b.peak()
            elif kb is not None and ka is None:
                pre = a.pre if a.pre is not None else a.peak()
            out = _union(sts, lo=lo, hi=hi, pre=pre)
            out.is_abs = any(s.is_abs for s in sts)
            return out

        if name == "clamp":
            lo_b, x, hi_b = sts[0], sts[1], sts[2]
            klo, khi = _scalar_of(lo_b), _scalar_of(hi_b)
            pre = x.pre if x.pre is not None else x.peak()
            return _union([x], lo=klo, hi=khi, pre=pre)

        if name in ("round", "floor", "ceil", "nearbyint"):
            out = _union(sts, lo=s0.lo, hi=s0.hi, pre=s0.pre)
            out.is_abs = s0.is_abs
            return out

        if name in ("reduce_max", "pmax"):
            out = _union(sts, lo=s0.lo, hi=s0.hi)
            out.is_abs = s0.is_abs
            if name == "reduce_max" and s0.is_abs:
                # a max-abs reduction: mint a scale-provenance root
                # (-1 on fixpoint passes keeps the iterate stable)
                out.roots = out.roots | {node if record else -1}
            return out

        if name in ("reduce_min", "pmin"):
            out = _union(sts, lo=s0.lo, hi=s0.hi)
            out.is_abs = s0.is_abs
            return out

        if name in ("reduce_sum", "cumsum"):
            axes = eqn.params.get("axes", ())
            in_aval = getattr(eqn.invars[0], "aval", None)
            mult = 1
            if name == "cumsum":
                ax = eqn.params.get("axis", 0)
                axes = (ax,)
            if in_aval is not None and hasattr(in_aval, "shape"):
                for a in axes:
                    mult *= int(in_aval.shape[a])
            else:
                mult = None
            return self._summed(sts, s0, mult, (), "reduce_sum",
                                out_dtype, node, record, conservative)

        if name in ("psum", "psum_scatter", "reduce_scatter"):
            mult = self._axis_mult(eqn)
            out = self._summed(
                sts, s0, mult, self._eqn_axes(eqn),
                "psum" if name == "psum" else "psum_scatter",
                out_dtype, node, record, conservative)
            out.post = True
            return out

        if name in ("all_gather", "all_to_all", "ppermute", "pshuffle"):
            out = _union(sts, lo=s0.lo, hi=s0.hi)
            if name == "all_to_all":
                out.post = True
            return out

        if name in ("reshape", "squeeze", "expand_dims",
                    "broadcast_in_dim", "transpose", "rev", "slice",
                    "dynamic_slice", "gather", "copy", "stop_gradient"):
            out = _union(sts[:1], lo=s0.lo, hi=s0.hi, pre=s0.pre)
            out.is_abs = s0.is_abs
            return out

        if name == "concatenate":
            out = sts[0]
            for s in sts[1:]:
                out = _join(out, s)
            return out

        if name == "pad":
            return _join(sts[0], sts[1])

        if name == "dynamic_update_slice":
            return _join(sts[0], sts[1])

        if name == "select_n":
            cases = sts[1:] if len(sts) > 1 else sts
            out = cases[0]
            for s in cases[1:]:
                out = _join(out, s)
            return out

        if name in ("gt", "lt", "ge", "le", "eq", "ne", "and", "or",
                    "not", "xor", "is_finite", "reduce_and", "reduce_or"):
            return _union(sts, lo=0.0, hi=1.0)

        if name == "integer_pow":
            y = eqn.params.get("y", None)
            p = s0.peak()
            if y is not None and p is not None and y >= 0:
                hi = float(p) ** int(y)
                lo = 0.0 if int(y) % 2 == 0 else -hi
                return _union(sts, lo=lo, hi=hi)
            return _union(sts)

        if name in ("iota", "rng_bit_generator", "random_bits",
                    "random_seed", "random_wrap", "random_fold_in"):
            return _St()

        if name in ("dot_general", "conv_general_dilated"):
            # fold-style dequantization (serve attention): a float
            # contraction of int-lattice payload against an operand that
            # already carries the scale row (root provenance) IS the
            # point where the payload leaves the lattice — audit it as a
            # dequant; with no scale in sight the payload flows on and a
            # later elementwise scale multiply is the dequant
            a, b = sts[0], sts[1]
            payload = other = None
            if a.sites and not b.sites:
                payload, other = a, b
            elif b.sites and not a.sites:
                payload, other = b, a
            if (payload is not None and other.roots
                    and out_dtype is not None and _is_float(out_dtype)):
                did = next(self._did) if record else -1
                if record:
                    self.dequants.append(DequantEvent(
                        did=did, payload_sites=payload.sites,
                        scale_roots=other.roots, scale_literal=False,
                        conservative=conservative))
                    self._deq_node[did] = node
                    self._deq_payload[did] = payload.sites
                out = _union(sts)
                out.sites = _EMPTY
                out.deqs = out.deqs | {did}
                out.lo = out.hi = None
                return out
            return _union(sts)

        # default: provenance union, bounds unknown
        return _union(sts)

    def _summed(self, sts, s0, mult, axes, kind, out_dtype, node, record,
                conservative) -> _St:
        if mult is not None and s0.lo is not None and s0.hi is not None:
            lo = min(s0.lo * mult, s0.hi * mult)
            hi = max(s0.lo * mult, s0.hi * mult)
        else:
            lo = hi = None
        out = _union(sts, lo=lo, hi=hi)
        if record and out_dtype is not None and _is_int(out_dtype):
            self.accums.append(AccumEvent(
                kind=kind, dtype=str(out_dtype), axes=tuple(axes),
                multiplier=mult, peak_in=s0.peak(),
                peak_out=(None if hi is None else max(abs(lo), abs(hi))),
                capacity=_int_cap(out_dtype),
                lattice=bool(s0.sites), conservative=conservative))
            self._accum_node.append(node)
        elif (record and out_dtype is not None and _is_float(out_dtype)
              and s0.sites):
            # float psum of lattice payload: mantissa capacity applies
            mant = _finfo_mant(out_dtype)
            cap = (1 << mant) if mant else None
            self.accums.append(AccumEvent(
                kind=kind, dtype=str(out_dtype), axes=tuple(axes),
                multiplier=mult, peak_in=s0.peak(),
                peak_out=(None if hi is None else max(abs(lo), abs(hi))),
                capacity=cap, lattice=True, conservative=conservative))
            self._accum_node.append(node)
        return out

    def _mul(self, sts, out_dtype, node, record, conservative) -> _St:
        a, b = sts[0], sts[1]
        # dequantization: lattice payload x scale, leaving the lattice
        payload = None
        other = None
        if a.sites and not b.sites:
            payload, other = a, b
        elif b.sites and not a.sites:
            payload, other = b, a
        if payload is not None and _scalar_of(other) is not None:
            # multiply by a STATIC scalar: an exact rescale (softmax
            # temperature, gain) — the payload stays on the lattice;
            # only a traced (data-dependent) scale can dequantize
            k = _scalar_of(other)
            lo = hi = None
            if payload.lo is not None and payload.hi is not None:
                lo, hi = sorted((payload.lo * k, payload.hi * k))
            out = _union(sts, lo=lo, hi=hi,
                         pre=(None if payload.pre is None
                              else payload.pre * abs(k)))
            out.is_abs = payload.is_abs and k > 0
            return out
        if (payload is not None and out_dtype is not None
                and _is_float(out_dtype)
                and _scalar_of(other) is None):
            did = next(self._did) if record else -1
            if record:
                self.dequants.append(DequantEvent(
                    did=did, payload_sites=payload.sites,
                    scale_roots=other.roots,
                    scale_literal=(_scalar_of(other) is not None
                                   and not other.roots),
                    conservative=conservative))
                self._deq_node[did] = node
                self._deq_payload[did] = payload.sites
            out = _union(sts)
            out.sites = _EMPTY
            out.deqs = out.deqs | {did}
            out.lo = out.hi = None
            return out
        # interval product
        lo = hi = None
        if (a.lo is not None and a.hi is not None and b.lo is not None
                and b.hi is not None):
            prods = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
            lo, hi = min(prods), max(prods)
        out = _union(sts, lo=lo, hi=hi)
        if (record and out_dtype is not None and _is_int(out_dtype)
                and out.sites and hi is None):
            # integer lattice product with unknown bound: capacity
            # becomes unprovable downstream; surface it here
            self.accums.append(AccumEvent(
                kind="mul", dtype=str(out_dtype), axes=(),
                multiplier=None, peak_in=None, peak_out=None,
                capacity=_int_cap(out_dtype), lattice=True,
                conservative=conservative))
            self._accum_node.append(node)
        return out

    def _div(self, sts, out_dtype, node, record, conservative) -> _St:
        a, b = sts[0], sts[1]
        k = _scalar_of(b)
        if k is not None and k != 0.0:
            lo = hi = None
            if a.lo is not None and a.hi is not None:
                q = sorted((a.lo / k, a.hi / k))
                lo, hi = q
            out = _union([a], lo=lo, hi=hi)
            out.is_abs = a.is_abs
            out.roots = a.roots | b.roots
            return out
        if (a.sites and not b.sites and out_dtype is not None
                and _is_float(out_dtype)):
            # dequant spelled as payload / inv_scale
            did = next(self._did) if record else -1
            if record:
                self.dequants.append(DequantEvent(
                    did=did, payload_sites=a.sites, scale_roots=b.roots,
                    scale_literal=False, conservative=conservative))
                self._deq_node[did] = node
                self._deq_payload[did] = a.sites
            out = _union(sts)
            out.sites = _EMPTY
            out.deqs = out.deqs | {did}
            out.lo = out.hi = None
            return out
        return _union(sts)

    def _convert(self, eqn, s0, out_dtype, node, record,
                 conservative) -> _St:
        in_aval = getattr(eqn.invars[0], "aval", None)
        src = getattr(in_aval, "dtype", None)
        if src is None or out_dtype is None:
            return _union([s0])
        out = _union([s0], lo=s0.lo, hi=s0.hi, pre=s0.pre)
        out.is_abs = s0.is_abs
        narrowing = _narrows(src, out_dtype)
        peak = s0.peak()
        if peak is None and _is_int(src):
            # an integer source has intrinsic dtype bounds even when the
            # dataflow bound is unknown (external int8 pool args)
            ii = np.iinfo(np.dtype(src))
            out.lo, out.hi = float(ii.min), float(ii.max)
            peak = float(max(abs(ii.min), ii.max))

        if _is_int(out_dtype) and (_is_float(src) or
                                   (_is_int(src) and narrowing)):
            cap = _int_cap(out_dtype)
            lattice_dtype = np.dtype(out_dtype).itemsize <= 2
            if peak is not None and cap is not None and peak <= cap:
                if not lattice_dtype:
                    # bounded cast into a wide int (index math, counters)
                    # — provably exact, not a quantization event
                    return out
                if (_scalar_of(s0) is not None and not s0.roots
                        and not s0.sites):
                    # a STATIC constant cast onto the lattice (zero
                    # init, padding) — provably exact, not a site
                    return out
                # a bounded narrowing convert onto the wire lattice:
                # a quantization site
                if record:
                    sid = next(self._sid)
                    shape = tuple(
                        int(d) for d in getattr(in_aval, "shape", ())
                    )
                    size = 1
                    for d in shape:
                        size *= d
                    self.sites.append(QuantSite(
                        sid=sid, dtype=str(out_dtype), shape=shape,
                        size=size, start_offset=0,  # set in finalize
                        peak=peak,
                        pre_peak=s0.pre,
                        roots=s0.roots,
                        primary=not s0.sites,
                        conservative=conservative))
                    self._site_node[sid] = node
                    out.sites = out.sites | {sid}
                else:
                    out.sites = out.sites | {-1}
            else:
                if record:
                    self.narrows.append(NarrowEvent(
                        src=str(src), dst=str(out_dtype),
                        is_quant_site=False,
                        downstream_of_reduce=s0.post,
                        conservative=conservative))
                    self._narrow_node.append(node)
                if (record and peak is not None and cap is not None
                        and peak > cap):
                    self.accums.append(AccumEvent(
                        kind="convert", dtype=str(out_dtype),
                        axes=(), multiplier=1, peak_in=peak,
                        peak_out=peak, capacity=cap,
                        lattice=bool(s0.sites),
                        conservative=conservative))
                    self._accum_node.append(node)
                out.lo = out.hi = None
            return out

        if _is_int(src) and _is_float(out_dtype) and s0.sites:
            # lattice value entering float: exactness needs the mantissa
            mant = _finfo_mant(out_dtype)
            cap = (1 << mant) if mant else None
            if record and (peak is None or (cap is not None
                                            and peak > cap)):
                self.accums.append(AccumEvent(
                    kind="mantissa", dtype=str(out_dtype), axes=(),
                    multiplier=1, peak_in=peak, peak_out=peak,
                    capacity=cap, lattice=True,
                    conservative=conservative))
                self._accum_node.append(node)
            return out

        if narrowing:
            if record:
                self.narrows.append(NarrowEvent(
                    src=str(src), dst=str(out_dtype),
                    is_quant_site=False,
                    downstream_of_reduce=s0.post,
                    conservative=conservative))
                self._narrow_node.append(node)
        return out

    # -- finalize -------------------------------------------------------

    def finalize(self, out_states: List[Tuple[_St, int]],
                 param_out_indices: Optional[Sequence[int]]
                 ) -> NumericsReport:
        n_out = len(out_states)
        param_set = set(param_out_indices or range(n_out))
        param_nodes = [node for i, (_, node) in enumerate(out_states)
                       if i in param_set]
        nonparam_nodes = [node for i, (_, node) in enumerate(out_states)
                          if i not in param_set]
        anc_params = self._ancestors(param_nodes)
        anc_nonparams = self._ancestors(nonparam_nodes)

        sites: List[QuantSite] = []
        offset = 0
        for s in self.sites:
            feeds = self._site_node[s.sid] in anc_params
            s = dataclasses.replace(s, feeds_params=feeds,
                                    start_offset=offset)
            if feeds and s.primary:
                offset += s.size
            sites.append(s)
        dequants = [
            dataclasses.replace(
                d, feeds_params=self._deq_node[d.did] in anc_params)
            for d in self.dequants
        ]
        accums = [
            dataclasses.replace(a, feeds_params=node in anc_params)
            for a, node in zip(self.accums, self._accum_node)
        ]
        narrows = [
            dataclasses.replace(nv, feeds_params=node in anc_params)
            for nv, node in zip(self.narrows, self._narrow_node)
        ]
        residuals: List[ResidualEvent] = []
        for r in self.residuals:
            covered = {
                sid for sid in r["cand"]
                if sid in self._site_node
                and r["minuend_node"] in self._anc_of(
                    self._site_node[sid])
            }
            if covered:
                # recomputed-transform EF (collectives.
                # local_quantized_contribution): the residual round-trips
                # a RE-quantization of the value the wire quantized —
                # bit-identical by construction but a separate set of
                # eqns, so the wire's own site is not in the subtrahend.
                # Extend coverage to sites quantizing the SAME minuend
                # with the SAME geometry: the minuend-ancestry check ties
                # both to one source value, the (dtype, shape) match ties
                # them to one transform.
                geom = {(self.sites[sid].dtype, self.sites[sid].shape)
                        for sid in covered}
                covered |= {
                    s.sid for s in self.sites
                    if s.sid not in covered
                    and (s.dtype, s.shape) in geom
                    and r["minuend_node"] in self._anc_of(
                        self._site_node[s.sid])
                }
            residuals.append(ResidualEvent(
                rid=r["rid"], covered_sites=frozenset(covered),
                feeds_carry=r["node"] in anc_nonparams,
                feeds_params=r["node"] in anc_params,
                conservative=r["conservative"]))
        return NumericsReport(
            sites=tuple(sites), dequants=tuple(dequants),
            accums=tuple(accums), narrows=tuple(narrows),
            residuals=tuple(residuals),
            axis_sizes=dict(self.axis_sizes))


def analyze_numerics(
    closed_jaxpr,
    param_out_indices: Optional[Sequence[int]] = None,
    axis_sizes: Optional[Dict[str, int]] = None,
) -> NumericsReport:
    """Run the precision-flow analysis over a traced ClosedJaxpr.

    ``param_out_indices``: flat output positions of the updated params
    (None: every output counts as params — fully conservative).
    ``axis_sizes``: mesh-axis sizes for collectives traced OUTSIDE a
    shard_map (e.g. a ``jax.make_jaxpr(..., axis_env=...)`` trace);
    sizes discovered from shard_map eqns are merged in automatically,
    with the explicit entries winning.
    """
    an = _Analyzer(axis_sizes=axis_sizes)
    outs = an.run_closed(closed_jaxpr)
    return an.finalize(outs, param_out_indices)
