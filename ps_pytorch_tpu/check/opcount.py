"""Op-count probes: how big is the compiled step, and how much of it is
UPDATE path (everything downstream of the gradient reduce)?

Two measurements, two tools:

- ``update_path_op_count`` walks the traced jaxpr FORWARD from the
  outputs of every reduce-kind collective (walker.REDUCE_KINDS — the
  gradient psum / psum_scatter / all_to_all family) and counts the
  equations that consume them, directly or transitively. This is the
  number that collapses when the state goes flat (PSConfig.state_layout
  = "flat"): the per-leaf scatter -> per-leaf optimizer -> per-leaf
  apply chain becomes one fused vector update, while the forward/
  backward half of the program is untouched. Deterministic, CPU-only,
  nothing executes. The few post-reduce metrics ops (loss pmean
  consumers) are counted too — identical in both layouts, so they only
  dilute the ratio, never flip it.

- ``hlo_op_count`` counts instructions in the OPTIMIZED HLO of the
  compiled step — the whole-program size after XLA fusion, recorded by
  bench.py on every benchmark record so the trajectory JSONs capture
  the update-path collapse on real configs.

Sub-jaxpr handling mirrors walker.py: exact through the call-like
primitives (pjit / shard_map / remat / custom_*), conservative inside
scan / while / cond (a tainted input taints every output and the WHOLE
body counts, nested sub-jaxprs included) — an over-approximation that
can only raise the count, never hide de-fusion.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Set, Tuple

from .walker import COLLECTIVE_PRIMS, REDUCE_KINDS, _is_var, _open, _subjaxprs

# one optimized-HLO instruction per line: "  %name = type op(...)" (the
# ROOT marker is optional); parameters count too — they appear in both
# layouts and wash out of any ratio
_HLO_INSTR = re.compile(r"^\s+(?:ROOT\s+)?[%\w.-]+\s*=\s")


def hlo_op_count(hlo_text: str) -> int:
    """Instruction count of an (optimized) HLO module's text dump."""
    return sum(1 for line in hlo_text.splitlines() if _HLO_INSTR.match(line))


def compiled_op_count(fn, *args) -> Optional[int]:
    """hlo_op_count of ``fn.lower(*args).compile()``; None when the
    function cannot be lowered/compiled here (e.g. a backend mismatch) —
    callers record the absence rather than a wrong number."""
    try:
        return hlo_op_count(fn.lower(*args).compile().as_text())
    except Exception:
        return None


def _total_eqns(jaxpr) -> int:
    """Every equation under a jaxpr, nested sub-jaxprs included — the
    conservative 'all of it is update path' count for a tainted loop or
    branch body."""
    n = 0
    for eqn in jaxpr.eqns:
        n += 1
        for sub, _ in _subjaxprs(eqn):
            n += _total_eqns(_open(sub))
    return n


def _forward_count(jaxpr, tainted: Set[Any]) -> Tuple[int, Set[Any]]:
    """One forward pass over an open jaxpr: seed taint at reduce-kind
    collective outputs, propagate through dataflow, count tainted eqns.
    Returns (count, tainted outvars of this jaxpr)."""
    count = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        in_tainted = any(v in tainted for v in eqn.invars if _is_var(v))
        subs = _subjaxprs(eqn)
        if subs:
            for sub, exact in subs:
                inner = _open(sub)
                if exact:
                    n = min(len(eqn.invars), len(inner.invars))
                    sub_taint = {
                        iv
                        for ov, iv in zip(eqn.invars[-n:], inner.invars[-n:])
                        if _is_var(ov) and ov in tainted and _is_var(iv)
                    }
                    c, sub_out = _forward_count(inner, sub_taint)
                    count += c
                    for ov, iv in zip(eqn.outvars, inner.outvars):
                        if _is_var(ov) and _is_var(iv) and iv in sub_out:
                            tainted.add(ov)
                else:
                    if in_tainted:
                        # loop/branch fed by the reduce: the WHOLE body
                        # is conservatively update path (a de-fused
                        # per-leaf update hidden inside a scan must
                        # raise the count, never collapse to 1)
                        count += _total_eqns(inner)
                        for v in eqn.outvars:
                            if _is_var(v):
                                tainted.add(v)
                    else:
                        # not fed by an outer reduce: count only its own
                        # internal post-reduce ops — and if the body
                        # CONTAINS a reduce, its outputs carry taint out
                        # of the loop (conservatively all of them; the
                        # in/out mapping is not exact here)
                        c, sub_out = _forward_count(inner, set())
                        count += c
                        if sub_out:
                            for v in eqn.outvars:
                                if _is_var(v):
                                    tainted.add(v)
            continue
        is_reduce = (
            name in COLLECTIVE_PRIMS
            and COLLECTIVE_PRIMS[name] in REDUCE_KINDS
        )
        if in_tainted:
            count += 1
        if in_tainted or is_reduce:
            # the reduce itself seeds taint but is not a post-reduce op
            for v in eqn.outvars:
                if _is_var(v):
                    tainted.add(v)
    return count, {v for v in jaxpr.outvars if _is_var(v) and v in tainted}


def update_path_op_count(fn, *args) -> int:
    """Number of jaxpr equations downstream of the gradient reduce in
    ``fn(*args)`` — the update-path size the flat state layout collapses.
    Traces only (ShapeDtypeStruct args are fine); nothing executes."""
    import jax

    return update_path_ops_from(jax.make_jaxpr(fn)(*args))


def update_path_ops_from(closed) -> int:
    """``update_path_op_count`` over an already-traced ClosedJaxpr (the
    tune/ cost model reuses the trace pscheck's rules ran on)."""
    count, _ = _forward_count(_open(closed), set())
    return count
