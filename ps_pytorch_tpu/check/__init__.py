"""pscheck — jaxpr-level contract checking for the parallel schemes.

pslint (ps_pytorch_tpu/lint) guards the SOURCE TEXT; pscheck guards what
XLA is actually asked to do: it traces each scheme's real step function
(CPU-only, abstract inputs, nothing executes) and walks the jaxpr to
verify the communication contracts ARCHITECTURE §1-§6b claim — every
axis carries its collective (PSC101), gradient reductions feed the
optimizer (PSC102), compressed wires stay int8 (PSC103), per-collective
wire bytes round-trip against runs/comm_contract.json (PSC104),
donation survives lowering (PSC105), bucketed wires stay fused — no
more gradient-path collectives than the declared bucket plan allows
(PSC106) — the serving hot path stays collective-free with an
honest KV storage dtype (PSC107), and adaptive-mask configs keep their
grad-reduce declaration and byte envelope (PSC108), pipelined
configs move exactly their serial twin's bytes with a real per-bucket
dispatch (PSC109), and adaptive configs name a real host-consensus
point for their traced count — checked against pslint's consensus
inventory (PSC110, the static half of PSL007's divergence guarantee).

PSC111-114 are the psnumerics rules (check/numerics.py): a precision-
flow analysis over the same traced jaxpr proves the quantized wire's
numerics — scale provenance (PSC111), error-feedback closure (PSC112),
integer-accumulation capacity from the traced axis sizes (PSC113), and
no silent downcast on the update path (PSC114). They run for every spec
declaring a NumericsPolicy; rule subsets via ``--select PSC1xx,...``.

Entry points: ``python -m ps_pytorch_tpu.check``, ``tools/check.sh``,
and the tier-1 gate in tests/test_check.py.
"""

from .contracts import (
    AdaptivePolicy,
    Built,
    ContractSpec,
    DonationSpec,
    FusionSpec,
    GradReduce,
    NarrowingAllowance,
    NumericsPolicy,
    OverlapPolicy,
    ServePolicy,
    WireAllowance,
    WirePolicy,
    get_contracts,
)
from .numerics import NumericsReport, analyze_numerics
from .core import (
    CheckFinding,
    TraceResult,
    load_contract,
    run_checks,
    to_contract_json,
    trace_registry,
    trace_spec,
    write_contract,
)
from .opcount import compiled_op_count, hlo_op_count, update_path_op_count
from .rules import RULE_IDS
from .walker import Collective, collect_collectives, summarize

__all__ = [
    "AdaptivePolicy",
    "Built",
    "CheckFinding",
    "Collective",
    "ContractSpec",
    "DonationSpec",
    "FusionSpec",
    "GradReduce",
    "NarrowingAllowance",
    "NumericsPolicy",
    "NumericsReport",
    "OverlapPolicy",
    "RULE_IDS",
    "ServePolicy",
    "TraceResult",
    "WireAllowance",
    "WirePolicy",
    "analyze_numerics",
    "collect_collectives",
    "compiled_op_count",
    "get_contracts",
    "hlo_op_count",
    "load_contract",
    "run_checks",
    "summarize",
    "to_contract_json",
    "trace_registry",
    "trace_spec",
    "update_path_op_count",
    "write_contract",
]
