"""pscheck contract registry: each scheme's step function + its declared
communication invariants.

A ContractSpec bundles a builder that constructs the REAL production step
(the same factory the trainer calls — nothing re-implemented here) with
the invariants ARCHITECTURE.md claims for it, as data the rules
(rules.py) can verify against the traced jaxpr:

- ``axes``: every declared mesh axis must be consumed by a collective,
  and no collective may ride any other axis (PSC101);
- ``grad_reduce``: for each axis across which gradient leaves are
  replicated, the reducing collective kinds that must feed the updated
  params (PSC102) — ``psum`` for the plain/int8 paths, ``psum_scatter``
  for the ZeRO-1 wire, ``all_to_all`` for the bandwidth-honest 2-round
  schemes (where the all_to_all + local sum IS the reduction);
- ``wire``: for configs that claim an int8 wire (§6b ladder rung 3), the
  payload dtype every collective on those axes must carry, plus the
  explicitly-allowed exceptions — scale rows, the f32 metrics pmean, the
  ZeRO-1 update all_gather (the weight bcast analogue) (PSC103);
- ``donation``: which args the compiled step donates and which outputs
  they must alias (PSC105).

Builders run CPU-only and deterministic: states are jax.eval_shape
abstractions, inputs are ShapeDtypeStructs — tracing never allocates or
executes a step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

MESH_DEVICES = 8  # the virtual CPU mesh every contract traces on


@dataclasses.dataclass(frozen=True)
class GradReduce:
    """PSC102: a reduce over `axis` with one of `kinds` must feed params."""

    axis: str
    kinds: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class WireAllowance:
    """A declared non-payload-dtype collective on a compressed wire."""

    kind: str
    dtype: str
    reason: str
    max_bytes: Optional[int] = None   # None = unlimited (document why!)
    axes: Optional[Tuple[str, ...]] = None  # None = any axes


@dataclasses.dataclass(frozen=True)
class WirePolicy:
    """PSC103: collectives riding `axes` must carry `payload_dtype`
    unless a WireAllowance explicitly covers them."""

    axes: Tuple[str, ...]
    payload_dtype: str = "int8"
    allow: Tuple[WireAllowance, ...] = ()


@dataclasses.dataclass(frozen=True)
class DonationSpec:
    """PSC105: arg `argnums[i]` is donated and must alias output
    position `out_positions[i]` of the step's output tuple."""

    argnums: Tuple[int, ...]
    out_positions: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class FusionSpec:
    """PSC106: gradient-path collective budget for a fused/bucketed wire.

    A scheme whose jaxpr emits more than
    ``per_bucket * n_buckets + slack`` (n_buckets from the engine's own
    ``plan_buckets``; ≈ ``ceil(payload_bytes / bucket_bytes)``)
    reduce-kind collectives feeding the updated params fails the gate —
    the canary for silent de-fusion (a refactor quietly going back to
    one collective per pytree leaf).

    ``payload_bytes``: f32 bytes of the gradient pytree;
    ``bucket_bytes``: PSConfig.bucket_bytes (0/None = one fused bucket);
    ``align``: the engine's bucket-boundary alignment in f32 elements
    (quant block size; × num_workers for the ZeRO-1 scatter) — the
    budget is computed by the SAME plan_buckets the wire uses, so the
    checker can never desync from the engine's round-down carving;
    ``per_bucket``: reduce collectives a healthy bucket legitimately
    costs (1 for psum/psum_scatter/all_to_all schemes, 2 for the
    hierarchical scheme's ICI + DCN all_to_all pair);
    ``slack``: extra allowed beyond the formula (document why)."""

    payload_bytes: int
    bucket_bytes: Optional[int] = 0
    align: int = 1
    per_bucket: int = 1
    slack: int = 0

    @property
    def n_buckets(self) -> int:
        from ..parallel.buckets import plan_buckets

        return plan_buckets(
            self.payload_bytes // 4, self.bucket_bytes or 0,
            align=self.align,
        ).n_buckets

    @property
    def max_collectives(self) -> int:
        return self.per_bucket * self.n_buckets + self.slack


@dataclasses.dataclass(frozen=True)
class AdaptivePolicy:
    """PSC108: the adaptive-partial-aggregation contract.

    A config taking a traced aggregation count (PSConfig.
    num_aggregate_min/max) must still declare a ``grad_reduce``
    requirement — the mask is a pre-reduce multiply, so PSC102's
    dataflow rule (the masked reduce feeds the updated params) applies
    unchanged, and PSC108 fails a spec that opted out of declaring it.
    It must also keep its gradient-path reduce collectives inside
    ``envelope_bytes``: adaptation reshapes VALUES (which workers'
    gradients are non-zero, what the denominator is), never bytes — a
    traced count that started moving per-count payloads (e.g. a gather
    of the mask, or a resize of the wire) is a regression this pin
    catches.

    PSC110: ``consensus`` names the host-consensus point that agrees the
    traced count across processes before it is fed to the step — a
    package-relative dotted path (``trainer.Trainer._count_consensus``)
    that must exist in pslint's consensus inventory (a function whose
    returned value passes through broadcast_one_to_all/process_allgather,
    see lint/diverge.py). An adaptive config with no declared consensus
    point is PR 7's per-host agg_count bug waiting to recur: each host
    adapts on its own timing and the traced counts tear."""

    min_aggregate: int
    max_aggregate: int
    envelope_bytes: int
    consensus: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """PSC108/PSC110 extension: adaptive per-bucket precision.

    A config taking a traced per-bucket precision tag vector
    (PSConfig.precision_adapt — skip / 4-bit / int8 / hi per wire
    bucket) inherits the adaptive mask's discipline:

    - PSC108: the gradient-path reduce collectives must stay inside
      ``envelope_bytes`` — a tag selects which LATTICE a bucket's
      values occupy (the traced clipping peak), never how many bytes
      the trace moves; a tag that started resizing payloads or
      gathering per-tag side channels is the same regression the mask
      envelope catches. ``n_buckets`` documents the traced tag
      vector's length (the wire's own state_plan carving).
    - PSC110: ``consensus`` names the host function that agrees the
      adopted tag vector across processes (elementwise min) before it
      is fed to the step — it must resolve in pslint's consensus
      inventory, exactly like AdaptivePolicy.consensus. Torn tags are
      torn traced values: each host would quantize the SAME psum
      payload onto a different lattice and the replicas shear.
    """

    n_buckets: int
    envelope_bytes: int
    consensus: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class OverlapPolicy:
    """PSC109: schedule invariance for the pipelined bucket wire.

    A config running ``PSConfig.overlap="pipelined"`` declares (a) its
    mode and (b) the NAME of its serial twin — the identical config with
    ``overlap="serial"``. The rule then pins "same bytes, different
    schedule": the pipelined trace's gradient-path reduce bytes must
    equal the twin's exactly (pipelining reorders and splits the
    schedule, it never moves different bytes), the per-bucket dispatch
    must be real — at least ``n_buckets`` (× the scheme's per-bucket
    collective cost) reduce-kind collectives each feeding the updated
    params, so PSC102's dataflow guarantee holds PER BUCKET rather than
    only in aggregate — and a config claiming ``pipelined`` whose wire
    de-pipelined back to one fused eqn fails loudly."""

    mode: str = "pipelined"
    serial_twin: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ServePolicy:
    """PSC107: the serving hot path's contract (serve/engine.py).

    A serving decode step moves NO training bytes: any collective in its
    jaxpr is a regression (the step is slot-parallel by construction —
    weights replicated, pool sharded over slots). The KV pool arg at
    ``kv_argnum`` must also honor the declared storage dtype policy:
    ``quantized`` pools carry int8 payload leaves (``*_q``) with f32
    block-scale rows (``*_s``); unquantized pools carry ``kv_dtype``
    K/V — an f32 leaf sneaking into a declared-int8 pool is the serving
    analogue of PSC103's wire-dtype regression."""

    kv_argnum: int = 1
    quantized: bool = False
    kv_dtype: str = "float32"


@dataclasses.dataclass(frozen=True)
class NarrowingAllowance:
    """One tolerated precision-narrowing convert on the update path
    (PSC114): src/dst dtype names plus the reason it is sound."""

    src: str
    dst: str
    reason: str = ""


@dataclasses.dataclass(frozen=True)
class NumericsPolicy:
    """PSC111-114: the precision-flow contract (check/numerics.py).

    Declaring a policy turns the numerics rules on for the config:
    every dequantize's scale must share a max-abs-reduction root with
    its quantize's (PSC111), the error-feedback residual must close —
    computed, fed to the carry, never double-counted (PSC112, only when
    ``error_feedback`` is declared), every integer accumulation on the
    quantized lattice must provably fit its traced dtype — worst-case
    |sum| from the TRACED axis sizes, not the config-time
    ACCUM_CAPACITY table (PSC113), and every precision-narrowing
    convert downstream of the gradient reduce on the update path must
    be a detected quantization site or a declared allowance (PSC114).

    ``quantized``: the gradient wire carries a quantized lattice — the
    trace must contain at least one rooted quantization site on the
    gradient path, and every integer reduce-kind collective feeding the
    params needs a PROVEN peak (an unbounded int wire sum on a declared
    quantized wire is a finding, not a pass).
    ``accum_dtype``: the declared integer accumulator for the lattice
    sums ("int16"/"int32"); a traced lattice reduction in any OTHER
    dtype is a finding — the static half of PR 12's widened-payload
    regression, caught from dataflow instead of wire bytes.
    """

    quantized: bool = False
    error_feedback: bool = False
    accum_dtype: Optional[str] = None
    allow_narrowing: Tuple[NarrowingAllowance, ...] = ()


@dataclasses.dataclass
class Built:
    """What a spec's builder returns: the real jitted step plus abstract
    example args and a selector for the updated-params subtree."""

    step: Callable
    args: Tuple[Any, ...]
    select_params: Callable[[Any], Any]


@dataclasses.dataclass
class ContractSpec:
    name: str
    build: Callable[[], Built]
    axes: Tuple[str, ...]
    grad_reduce: Tuple[GradReduce, ...] = ()
    wire: Optional[WirePolicy] = None
    donation: Optional[DonationSpec] = None
    fusion: Optional[FusionSpec] = None
    serve: Optional[ServePolicy] = None
    adaptive: Optional[AdaptivePolicy] = None
    overlap: Optional[OverlapPolicy] = None
    numerics: Optional[NumericsPolicy] = None
    precision: Optional[PrecisionPolicy] = None


# metrics / loss pmean: a handful of f32 scalars, every scheme emits it
_METRICS_PSUM = WireAllowance(
    kind="psum", dtype="float32", max_bytes=64,
    reason="metrics/loss pmean (scalars)",
)
# shared-scale agreement for round-1 quantization (ops/quantize pmax)
_SCALE_PMAX = WireAllowance(
    kind="pmax", dtype="float32", max_bytes=4096,
    reason="per-tensor/per-block scale agreement (pmax)",
)
# round-2 scale rows ride an f32 all_gather next to the int8 payload
_SCALE_GATHER = WireAllowance(
    kind="all_gather", dtype="float32", max_bytes=4096,
    reason="round-2 quantization scale rows",
)
# the non-finite gradient guard's mesh-consensus flag: one int32 pmin,
# 4 bytes per step (resilience/guard.py; PSConfig.nonfinite_guard)
_FINITE_PMIN = WireAllowance(
    kind="pmin", dtype="int32", max_bytes=8,
    reason="non-finite gradient guard flag (skip-step consensus)",
)


# input HW shape per contract network (CIFAR-10 shapes for ResNet)
_NETWORK_HW = {"LeNet": (28, 28, 1), "ResNet18": (32, 32, 3)}

# f32 gradient payload bytes per contract network, memoized by a cheap
# eval_shape of the real init (nothing allocates) — the PSC106 budget's
# numerator, derived instead of hard-coded so a model edit cannot
# silently desync the fusion contract
_PAYLOAD_CACHE: dict = {}


def payload_bytes(network: str) -> int:
    if network not in _PAYLOAD_CACHE:
        _PAYLOAD_CACHE[network] = _model_bytes(network)
    return _PAYLOAD_CACHE[network]


def bn_state_bytes(network: str) -> int:
    """f32 bytes of the model's non-parameter state (BatchNorm running
    stats) — the payload the default ``bn_mode="pmean"`` averages across
    workers each step. Derived from the real init's eval_shape, like
    ``payload_bytes``, so the PSC103 allowance below can never desync
    from the model. 0 for BN-free networks (LeNet)."""
    key = (network, "bn")
    if key not in _PAYLOAD_CACHE:
        _PAYLOAD_CACHE[key] = _model_bytes(network, state=True)
    return _PAYLOAD_CACHE[key]


def _model_bytes(network: str, state: bool = False) -> int:
    import jax

    from ..models import build_model, init_model

    model = build_model(network, num_classes=10)
    out = jax.eval_shape(
        lambda: init_model(
            model, jax.random.key(0), (1,) + _NETWORK_HW[network]
        )
    )
    tree = out[1] if state else out[0]
    return 4 * sum(
        int(_prod(l.shape)) for l in jax.tree_util.tree_leaves(tree)
    )


def _prod(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _cnn_ps_built(cfg, network: str) -> Built:
    import jax
    import jax.numpy as jnp
    import optax

    from ..models import build_model
    from ..parallel.mesh import make_hybrid_mesh, make_mesh
    from ..parallel.ps import init_ps_state, make_ps_train_step

    hw = _NETWORK_HW[network]
    model = build_model(network, num_classes=10)
    tx = optax.sgd(0.1)
    if cfg.dcn_hosts > 1:
        mesh = make_hybrid_mesh(cfg.dcn_hosts, cfg.num_workers // cfg.dcn_hosts)
    else:
        mesh = make_mesh(num_workers=cfg.num_workers)
    step = make_ps_train_step(model, tx, cfg, mesh, donate=True)
    state = jax.eval_shape(
        lambda: init_ps_state(model, tx, cfg, jax.random.key(0), (1,) + hw)
    )
    batch = {
        "image": jax.ShapeDtypeStruct(
            (cfg.num_workers,) + hw, jnp.uint8
        ),
        "label": jax.ShapeDtypeStruct((cfg.num_workers,), jnp.int32),
    }
    key = jax.eval_shape(lambda: jax.random.key(0))
    args = (state, batch, key)
    if cfg.adaptive_aggregate:
        # the traced per-window aggregation count (same compiled step
        # for every value — the whole point of the adaptive signature)
        args += (jax.ShapeDtypeStruct((), jnp.int32),)
    if cfg.precision_adapt:
        # the traced per-bucket precision tag vector, sized by the SAME
        # state_plan the wire carves (declared extras order: after the
        # aggregation count when both are on)
        from ..parallel.ps import state_plan

        n_buckets = state_plan(cfg, payload_bytes(network) // 4).n_buckets
        args += (jax.ShapeDtypeStruct((n_buckets,), jnp.int32),)
    return Built(
        step=step,
        args=args,
        select_params=lambda out: out[0].params,
    )


def _ps_spec(
    compress,
    placement,
    dcn_hosts: int = 1,
    bucket_bytes: Optional[int] = None,
    network: str = "LeNet",
    state_layout: str = "flat",
    adaptive: bool = False,
    overlap: str = "serial",
    bucket_tag: str = "",
    quant_block_size: int = 0,
    wire_domain: str = "dequant",
    error_feedback: bool = False,
    precision_adapt: bool = False,
) -> ContractSpec:
    from ..parallel.mesh import DCN_AXIS, WORKER_AXIS

    name = "ps_{}_{}".format(compress or "none", placement)
    if dcn_hosts > 1:
        name = "ps_hier_{}_{}".format(compress, placement)
    if network != "LeNet":
        name = name.replace("ps_", f"ps_{network.lower()}_", 1)
    if bucket_bytes is not None:
        # bucket_tag distinguishes registry entries traced with a
        # different carving of the same scheme (e.g. the 64 KiB
        # multi-bucket PSC109 twins vs the fused "_bucketed" entries)
        name += "_bucketed" + bucket_tag
    if quant_block_size:
        # block-scale granularity changes the scale-row accounting (and
        # can overflow PSC103's scale allowances — the tune/ search uses
        # exactly that as a pruning constraint), so it must be visible
        # in the config name
        name += f"_qb{quant_block_size}"
    homomorphic = wire_domain == "homomorphic"
    if homomorphic:
        name += "_homomorphic"
    if error_feedback:
        name += "_ef"
    if precision_adapt:
        name += "_precadapt"
    if adaptive:
        name += "_adaptive"
    if overlap == "pipelined":
        serial_twin = name
        name += "_pipelined"
    if state_layout != "flat":
        # layout-parity twins only (layout_parity_pairs) — the registry
        # itself carries the default layout, and state layout is
        # compute-side, so its wire rows would duplicate the flat ones
        name += "_treestate"
    axes: Tuple[str, ...] = (
        (DCN_AXIS, WORKER_AXIS) if dcn_hosts > 1 else (WORKER_AXIS,)
    )

    def make_cfg():
        from ..parallel.ps import PSConfig

        return PSConfig(
            num_workers=MESH_DEVICES,
            compress=compress,
            opt_placement=placement,
            dcn_hosts=dcn_hosts,
            bucket_bytes=bucket_bytes,
            state_layout=state_layout,
            overlap=overlap,
            quant_block_size=quant_block_size,
            wire_domain=wire_domain,
            error_feedback=error_feedback,
            precision_adapt=precision_adapt,
            num_aggregate_min=2 if adaptive else None,
            num_aggregate_max=MESH_DEVICES if adaptive else None,
        )

    def build() -> Built:
        return _cnn_ps_built(make_cfg(), network)

    # the reduce that must feed the optimizer, per §6b ladder rung:
    # lossless/int8 reduce with a psum (psum_scatter when ZeRO-1 sharded);
    # the 2-round schemes reduce via all_to_all + local sum
    if compress == "int8_2round":
        reduce_kinds: Tuple[str, ...] = ("all_to_all",)
    elif placement == "sharded":
        reduce_kinds = ("psum_scatter",)
    else:
        reduce_kinds = ("psum",)
    grad_reduce = tuple(GradReduce(a, reduce_kinds) for a in axes)

    wire = None
    if compress == "int8_2round":
        if homomorphic:
            # compressed-domain wire (§6h): round 2's requantization is
            # a lattice rescale with the round-1 scales everyone already
            # holds — the f32 scale-row gather allowance disappears, and
            # the hierarchical reassembly gathers int8 payload so its
            # f32 allowance disappears too. The allowance list is
            # STRICTLY SMALLER than the dequant twin's; that shrink is
            # the proof mechanism the homomorphic mode banks on.
            allow = [_METRICS_PSUM, _SCALE_PMAX, _FINITE_PMIN]
        else:
            allow = [_METRICS_PSUM, _SCALE_PMAX, _SCALE_GATHER,
                     _FINITE_PMIN]
        if bn_state_bytes(network):
            # BatchNorm running stats (bn_mode="pmean", the default)
            # ride an f32 psum sized by the model's own state tree —
            # statistics, not gradient payload, so they are allowed on
            # a compressed wire. BN-free registry networks (LeNet)
            # never declare this, so committed entries are unchanged.
            allow.append(WireAllowance(
                kind="psum", dtype="float32",
                max_bytes=bn_state_bytes(network),
                reason="BatchNorm cross-replica stats pmean "
                       "(bn_mode=pmean; model state, not gradients)",
            ))
        if placement == "sharded":
            allow.append(
                WireAllowance(
                    kind="all_gather", dtype="float32", max_bytes=None,
                    reason="ZeRO-1 f32 update all_gather (the weight "
                           "bcast analogue; §6b sharded placement)",
                )
            )
        if dcn_hosts > 1 and not homomorphic:
            allow.append(
                WireAllowance(
                    kind="all_gather", dtype="float32", max_bytes=None,
                    axes=(WORKER_AXIS,),
                    reason="hierarchical reassembly all_gather rides ICI "
                           "only (§6b: spend bytes on the link that has "
                           "them)",
                )
            )
        wire = WirePolicy(axes=axes, payload_dtype="int8",
                          allow=tuple(allow))
    elif compress == "int8" and homomorphic:
        # the dequant "int8" scheme cannot declare a wire policy at all
        # (its psum payload is int32 by design); the homomorphic twin
        # CAN — the payload IS the minimal exact accumulator
        # (ops/quantize.accum_dtype: int16 on the 8-device registry
        # mesh), and any f32/int32 widening back onto the wire trips
        # PSC103. New policing the dequant twin never had.
        from ..ops.quantize import accum_dtype

        import jax.numpy as jnp

        allow = [_METRICS_PSUM, _SCALE_PMAX, _FINITE_PMIN]
        if bn_state_bytes(network):
            allow.append(WireAllowance(
                kind="psum", dtype="float32",
                max_bytes=bn_state_bytes(network),
                reason="BatchNorm cross-replica stats pmean "
                       "(bn_mode=pmean; model state, not gradients)",
            ))
        if placement == "sharded":
            allow.append(
                WireAllowance(
                    kind="all_gather", dtype="float32", max_bytes=None,
                    reason="ZeRO-1 f32 update all_gather (the weight "
                           "bcast analogue; §6b sharded placement)",
                )
            )
        wire = WirePolicy(
            axes=axes,
            payload_dtype=jnp.dtype(accum_dtype(MESH_DEVICES)).name,
            allow=tuple(allow),
        )

    fusion = None
    if bucket_bytes is not None or placement == "sharded":
        # bucketed configs declare their O(n_buckets) budget; the ZeRO-1
        # sharded wire is flat by construction, so its fusion contract
        # (ONE reduce per step) holds even in the legacy spelling. The
        # hierarchical scheme legitimately pays two all_to_alls per
        # bucket (ICI scatter + DCN scatter).
        from ..parallel.ps import wire_align

        fusion = FusionSpec(
            payload_bytes=payload_bytes(network),
            bucket_bytes=bucket_bytes or 0,
            align=wire_align(make_cfg()),
            per_bucket=2 if dcn_hosts > 1 else 1,
        )

    adaptive_policy = None
    if adaptive:
        # the envelope: exactly the bytes the equivalent STATIC config's
        # gradient reduce moves — adaptation must not add any. Both
        # registered adaptive wires carry 4 B/element on the reduce path
        # (f32 psum; int32 psum_scatter for the ZeRO-1 int8 wire), over
        # the engine's own padded bucket plan, so the bound is derived
        # from the same plan_buckets the step uses.
        from ..parallel.buckets import plan_buckets
        from ..parallel.ps import wire_align

        cfg = make_cfg()
        plan = plan_buckets(
            payload_bytes(network) // 4, cfg.bucket_bytes or 0,
            align=wire_align(cfg),
        )
        adaptive_policy = AdaptivePolicy(
            min_aggregate=cfg.num_aggregate_min,
            max_aggregate=cfg.num_aggregate_max,
            envelope_bytes=plan.padded_total * 4,
            # the host controller's proposal is min-reduced across
            # processes before the traced count changes (PSC110)
            consensus="trainer.Trainer._count_consensus",
        )

    overlap_policy = None
    if overlap == "pipelined":
        overlap_policy = OverlapPolicy(mode="pipelined",
                                       serial_twin=serial_twin)

    precision_policy = None
    if precision_adapt:
        # the envelope: exactly the bytes the STATIC config's gradient
        # reduce moves — a tag selects the lattice the values occupy
        # inside the same physical payload, so adaptation may never add
        # reduce bytes. Per-element reduce cost per scheme: the 2round
        # all_to_all ships the int8 payload itself; the homomorphic
        # psum rides the minimal exact accumulator; the dequant int8
        # psum rides int32.
        from ..parallel.ps import state_plan

        cfg = make_cfg()
        splan = state_plan(cfg, payload_bytes(network) // 4)
        if compress == "int8_2round":
            per_elem = 1
        elif homomorphic:
            import jax.numpy as jnp

            from ..ops.quantize import accum_dtype

            per_elem = jnp.dtype(accum_dtype(MESH_DEVICES)).itemsize
        else:
            per_elem = 4
        precision_policy = PrecisionPolicy(
            n_buckets=splan.n_buckets,
            envelope_bytes=splan.padded_total * per_elem,
            # the host controller's adopted tag vector is min-reduced
            # across processes before the traced step sees it (PSC110)
            consensus="trainer.Trainer._tags_consensus",
        )
        if wire is not None:
            # the controller's telemetry: one [n_buckets] f32 pmean of
            # per-bucket squared gradient norms per step — statistics,
            # not payload, and byte-bounded by the bucket count
            wire = dataclasses.replace(wire, allow=wire.allow + (
                WireAllowance(
                    kind="psum", dtype="float32",
                    max_bytes=4 * splan.n_buckets,
                    reason="per-bucket gradient-norm telemetry pmean "
                           "(adaptive precision controller)",
                ),
            ))

    # the precision-flow contract (PSC111-114): which integer
    # accumulator the quantized lattice sums into, per wire scheme —
    # quantized_psum widens int8 -> int32 (homomorphic: the minimal
    # exact accumulator, int16 on the registry mesh); both 2round
    # schemes sum their all_to_all'd slices in local int32
    if compress == "int8" and homomorphic:
        import jax.numpy as jnp

        from ..ops.quantize import accum_dtype

        num = NumericsPolicy(
            quantized=True,
            accum_dtype=jnp.dtype(accum_dtype(MESH_DEVICES)).name,
            error_feedback=error_feedback,
        )
    elif compress in ("int8", "int8_2round"):
        num = NumericsPolicy(quantized=True, accum_dtype="int32",
                             error_feedback=error_feedback)
    else:
        num = NumericsPolicy(quantized=False)

    return ContractSpec(
        name=name,
        build=build,
        axes=axes,
        grad_reduce=grad_reduce,
        wire=wire,
        donation=DonationSpec(argnums=(0,), out_positions=(0,)),
        fusion=fusion,
        adaptive=adaptive_policy,
        overlap=overlap_policy,
        numerics=num,
        precision=precision_policy,
    )


def _lm_cfg():
    from ..models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=32, dim=16, depth=2, heads=4, max_seq_len=16
    )


def _dp_tp_spec() -> ContractSpec:
    from ..parallel.mesh import WORKER_AXIS
    from ..parallel.tp import TP_AXIS

    def build() -> Built:
        import jax
        import jax.numpy as jnp
        import optax

        from ..parallel.dp_tp import make_dp_tp_train_step, make_mesh_dp_tp
        from ..parallel.tp import _tp_param_shapes

        cfg = _lm_cfg()
        tx = optax.sgd(0.1)
        mesh = make_mesh_dp_tp(4, 2)
        step = make_dp_tp_train_step(cfg, tx, mesh, donate=True)
        params = _tp_param_shapes(cfg)
        opt = jax.eval_shape(tx.init, params)
        toks = jax.ShapeDtypeStruct((8, 16), jnp.int32)
        return Built(
            step=step,
            args=(params, opt, toks),
            select_params=lambda out: out[0],
        )

    return ContractSpec(
        name="dp_tp",
        build=build,
        axes=(WORKER_AXIS, TP_AXIS),
        grad_reduce=(
            GradReduce(WORKER_AXIS, ("psum",)),
            GradReduce(TP_AXIS, ("psum",)),
        ),
        donation=DonationSpec(argnums=(0, 1), out_positions=(0, 1)),
        numerics=NumericsPolicy(),
    )


def _pp_spec() -> ContractSpec:
    from ..parallel.pp import PP_AXIS

    def build() -> Built:
        import jax
        import jax.numpy as jnp
        import optax

        from ..parallel.pp import (
            _pp_param_shapes,
            make_pp_mesh,
            make_pp_train_step,
        )

        cfg = _lm_cfg()
        tx = optax.sgd(0.1)
        mesh = make_pp_mesh(2)
        step = make_pp_train_step(cfg, tx, mesh, num_microbatches=2,
                                  donate=True)
        params = _pp_param_shapes(cfg)
        opt = jax.eval_shape(tx.init, params)
        toks = jax.ShapeDtypeStruct((4, 16), jnp.int32)
        return Built(
            step=step,
            args=(params, opt, toks),
            select_params=lambda out: out[0],
        )

    return ContractSpec(
        name="pp",
        build=build,
        axes=(PP_AXIS,),
        grad_reduce=(GradReduce(PP_AXIS, ("psum",)),),
        donation=DonationSpec(argnums=(0, 1), out_positions=(0, 1)),
        numerics=NumericsPolicy(),
    )


def _moe_spec() -> ContractSpec:
    from ..parallel.moe import EP_AXIS

    def build() -> Built:
        import jax
        import jax.numpy as jnp
        import optax

        from ..parallel.moe import (
            MoEConfig,
            _moe_param_shapes,
            make_ep_mesh,
            make_moe_train_step,
        )

        cfg = _lm_cfg()
        moe = MoEConfig(num_experts=MESH_DEVICES)
        tx = optax.sgd(0.1)
        mesh = make_ep_mesh(MESH_DEVICES)
        step = make_moe_train_step(cfg, moe, tx, mesh, donate=True)
        params = _moe_param_shapes(cfg, moe)
        opt = jax.eval_shape(tx.init, params)
        toks = jax.ShapeDtypeStruct((8, 16), jnp.int32)
        return Built(
            step=step,
            args=(params, opt, toks),
            select_params=lambda out: out[0],
        )

    return ContractSpec(
        name="moe",
        build=build,
        axes=(EP_AXIS,),
        grad_reduce=(GradReduce(EP_AXIS, ("psum",)),),
        donation=DonationSpec(argnums=(0, 1), out_positions=(0, 1)),
        numerics=NumericsPolicy(),
    )


def _dp_tp_pp_spec() -> ContractSpec:
    from ..parallel.dp_tp_pp import DP_AXIS
    from ..parallel.pp import PP_AXIS
    from ..parallel.tp import TP_AXIS

    def build() -> Built:
        import jax
        import jax.numpy as jnp
        import optax

        from ..models.transformer import init_transformer
        from ..parallel.dp_tp_pp import (
            make_3d_train_step,
            make_mesh_3d,
            to_3d_layout,
        )

        cfg = _lm_cfg()
        tx = optax.sgd(0.1)
        mesh = make_mesh_3d(2, 2, 2)
        step = make_3d_train_step(cfg, tx, mesh, num_microbatches=2,
                                  donate=True)
        params = jax.eval_shape(
            lambda: to_3d_layout(cfg, init_transformer(cfg, jax.random.key(0)))
        )
        opt = jax.eval_shape(tx.init, params)
        toks = jax.ShapeDtypeStruct((4, 16), jnp.int32)
        return Built(
            step=step,
            args=(params, opt, toks),
            select_params=lambda out: out[0],
        )

    return ContractSpec(
        name="dp_tp_pp",
        build=build,
        axes=(DP_AXIS, PP_AXIS, TP_AXIS),
        grad_reduce=(
            GradReduce(DP_AXIS, ("psum",)),
            GradReduce(PP_AXIS, ("psum",)),
            GradReduce(TP_AXIS, ("psum",)),
        ),
        donation=DonationSpec(argnums=(0, 1), out_positions=(0, 1)),
        numerics=NumericsPolicy(),
    )


def _serve_spec(int8_kv: bool) -> ContractSpec:
    """The serving hot path's contract: the REAL compiled decode step
    (serve/engine.make_decode_step — the same factory the engine jits),
    traced over abstract pool/weights. Zero collectives, donated KV
    pool, declared storage dtype (PSC105 + PSC107)."""

    def build() -> Built:
        import jax
        import jax.numpy as jnp

        from ..models.transformer import init_transformer
        from ..parallel.buckets import FlatVector, plan_buckets, tree_layout
        from ..serve.engine import ServeConfig, make_decode_step
        from ..serve.kv import init_kv_pool

        cfg = _lm_cfg()
        serve = ServeConfig(
            slots=MESH_DEVICES, max_len=16, max_prompt_len=8,
            kv_int8=int8_kv,
        )
        params_tree = jax.eval_shape(
            lambda: init_transformer(cfg, jax.random.key(0))
        )
        layout = tree_layout(params_tree)
        plan = plan_buckets(layout.total, 0, align=1)
        params = FlatVector(
            flat=jax.ShapeDtypeStruct((plan.padded_total,), jnp.float32),
            layout=layout, plan=plan,
        )
        pool = jax.eval_shape(
            lambda: init_kv_pool(cfg, serve.slots, serve.max_len,
                                 int8=serve.kv_int8)
        )
        step = jax.jit(make_decode_step(cfg, serve), donate_argnums=(1,))
        s = serve.slots
        return Built(
            step=step,
            args=(
                params,
                pool,
                jax.ShapeDtypeStruct((s,), jnp.int32),
                jax.ShapeDtypeStruct((s,), jnp.int32),
                jax.ShapeDtypeStruct((s,), jnp.bool_),
            ),
            # the pool is the state that persists across ticks
            select_params=lambda out: out[0],
        )

    return ContractSpec(
        name="serve_decode" + ("_int8kv" if int8_kv else ""),
        build=build,
        axes=(),  # slot-parallel: NO mesh axis may be consumed
        donation=DonationSpec(argnums=(1,), out_positions=(0,)),
        serve=ServePolicy(kv_argnum=1, quantized=int8_kv),
        numerics=NumericsPolicy(quantized=int8_kv),
    )


# the flagship bucketed config's bucket size (4 MiB): ResNet18's
# ~44.7 MB f32 gradient payload -> 11 buckets instead of 62 per-leaf
# collectives. MiB-scale buckets amortize collective latency without
# blowing up program size; tiny buckets on big models de-fuse again.
RESNET_BUCKET_BYTES = 4 << 20


def layout_parity_pairs() -> Tuple[Tuple[ContractSpec, ContractSpec], ...]:
    """(flat_spec, tree_spec) twins for the state-layout parity gate.

    PSConfig.state_layout is COMPUTE-side: the registry (and the
    committed artifact) trace the default flat layout, and these twins
    exist so tests/test_flat_state.py can assert that each pair's traced
    wire accounting — collective kinds, axes, dtypes, counts, bytes — is
    byte-identical, i.e. going flat moved zero bytes and added zero
    collectives. One twin per wire family: the per-leaf psum, the fused
    quantized bucket wire, and the ZeRO-1 scatter."""
    combos = (
        dict(compress=None, placement="replicated"),
        dict(compress="int8", placement="replicated", bucket_bytes=0),
        dict(compress="int8", placement="sharded"),
    )
    return tuple(
        (
            _ps_spec(state_layout="flat", **kw),
            _ps_spec(state_layout="tree", **kw),
        )
        for kw in combos
    )


def get_contracts() -> Tuple[ContractSpec, ...]:
    """The committed registry: the PS matrix (compress x placement, plus
    the hierarchical DCN x ICI composition), the bucketed-wire variants
    (PSC106), the ResNet per-leaf/bucketed pair whose artifact rows
    document the collective-count collapse, and the LM schemes."""
    specs = [
        _ps_spec(c, p)
        for c in (None, "int8", "int8_2round")
        for p in ("replicated", "sharded")
    ]
    specs.append(_ps_spec("int8_2round", "replicated", dcn_hosts=2))
    # fused-wire variants of every replicated scheme (bucket_bytes=0: ONE
    # flat buffer; the sharded placement is already flat, its legacy
    # specs above carry the fusion contract directly)
    specs.extend(
        _ps_spec(c, "replicated", bucket_bytes=0)
        for c in (None, "int8", "int8_2round")
    )
    specs.append(
        _ps_spec("int8_2round", "replicated", dcn_hosts=2, bucket_bytes=0)
    )
    # the headline A/B pair: the reference-shaped per-leaf wire vs the
    # 4 MiB bucketed wire on the real ResNet18 gradient pytree — the
    # committed artifact pins one-psum-per-leaf collapsing to
    # ceil(payload / bucket_bytes)
    specs.append(_ps_spec("int8", "replicated", network="ResNet18"))
    specs.append(
        _ps_spec(
            "int8", "replicated", network="ResNet18",
            bucket_bytes=RESNET_BUCKET_BYTES,
        )
    )
    # adaptive partial aggregation (PSC108): the traced-count mask on the
    # fused replicated wire and on the ZeRO-1 int8 scatter — the two
    # paths whose masking/denominator code diverges in ps.py
    specs.append(
        _ps_spec(None, "replicated", bucket_bytes=0, adaptive=True)
    )
    specs.append(_ps_spec("int8", "sharded", adaptive=True))
    # PSC109 serial/pipelined twins (overlap="pipelined", §6g): a
    # genuinely multi-bucket LeNet pair per wire family at 64 KiB
    # buckets (LeNet's ~1.7 MB payload -> ~27 buckets), the flagship
    # ResNet18 int8 4 MiB config's pipelined twin, and the ZeRO-1
    # scatter's — each pipelined entry pins "same bytes, different
    # schedule" against the serial entry traced beside it
    for ov in ("serial", "pipelined"):
        specs.append(_ps_spec(None, "replicated", bucket_bytes=64 << 10,
                              bucket_tag="64k", overlap=ov))
        specs.append(_ps_spec("int8", "replicated", bucket_bytes=64 << 10,
                              bucket_tag="64k", overlap=ov))
    specs.append(
        _ps_spec(
            "int8", "replicated", network="ResNet18",
            bucket_bytes=RESNET_BUCKET_BYTES, overlap="pipelined",
        )
    )
    specs.append(_ps_spec("int8", "sharded", overlap="pipelined"))
    # homomorphic (compressed-domain) twins of the committed int8 wires
    # (§6h, wire_domain="homomorphic"): the artifact rows document the
    # f32 widening leaving the wire — the "int8" psum narrows int32 ->
    # int16, the 2round gather hop drops its f32 scale rows, and the
    # hierarchical twin's ICI reassembly shrinks f32 -> int8 (4x). Each
    # twin's PSC103 allowance list is strictly smaller than (or, for
    # "int8", newly existent vs) its dequant twin's.
    specs.append(_ps_spec("int8", "replicated", wire_domain="homomorphic"))
    specs.append(_ps_spec("int8", "sharded", wire_domain="homomorphic"))
    specs.append(_ps_spec("int8_2round", "replicated", bucket_bytes=0,
                          wire_domain="homomorphic"))
    specs.append(_ps_spec("int8_2round", "sharded",
                          wire_domain="homomorphic"))
    specs.append(_ps_spec("int8_2round", "replicated", dcn_hosts=2,
                          bucket_bytes=0, wire_domain="homomorphic"))
    # the cost-model leg: the flagship ResNet18 bucketed int8 wire in
    # the compressed domain (tests/test_tune.py pins that the model
    # ranks it <= the dequant twin), plus a pipelined 64 KiB pair so
    # PSC109's same-bytes/per-bucket-dispatch pins hold on the
    # homomorphic wire too
    specs.append(
        _ps_spec(
            "int8", "replicated", network="ResNet18",
            bucket_bytes=RESNET_BUCKET_BYTES, wire_domain="homomorphic",
        )
    )
    for ov in ("serial", "pipelined"):
        specs.append(_ps_spec("int8", "replicated", bucket_bytes=64 << 10,
                              bucket_tag="64k", overlap=ov,
                              wire_domain="homomorphic"))
    # adaptive per-bucket precision (PSC108/110 precision half, §6i):
    # the traced tag vector on the dequant int8 bucketed wire, and the
    # smoke-leg twin — homomorphic 2round + EF, where the tags retune
    # round 1's lattice under shared scales while EF closes over the
    # added error (PSC112 must still prove the residual against the
    # traced-peak mirror). Both pin "tags reshape values, never bytes".
    specs.append(_ps_spec("int8", "replicated", bucket_bytes=64 << 10,
                          bucket_tag="64k", precision_adapt=True))
    specs.append(_ps_spec("int8_2round", "replicated",
                          bucket_bytes=64 << 10, bucket_tag="64k",
                          wire_domain="homomorphic", error_feedback=True,
                          precision_adapt=True))
    specs.extend(
        [_dp_tp_spec(), _pp_spec(), _moe_spec(), _dp_tp_pp_spec()]
    )
    # the serving hot path (ARCHITECTURE §7e): the compiled decode step
    # must stay collective-free with a donated, dtype-honest KV pool
    specs.extend([_serve_spec(False), _serve_spec(True)])
    return tuple(specs)
