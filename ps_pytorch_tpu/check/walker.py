"""Jaxpr collective walker: the measurement half of pscheck.

Walks a traced step function's jaxpr (recursing through pjit/shard_map/
scan/while/cond/custom_* sub-jaxprs) and returns every collective
equation with its axes, per-device payload shape/dtype, and byte count —
the ground truth the contract rules (rules.py) check against. A reverse
liveness pass simultaneously marks which collectives feed the updated
parameters (as opposed to, say, the metrics pmean), which is what lets
PSC102 say "psummed over that axis BEFORE the optimizer" instead of
"psummed somewhere".

Liveness is exact through pjit / shard_map / custom_{jvp,vjp} / remat
call boundaries (1:1 invar/outvar mapping) and conservative inside
scan / while / cond bodies (any live output marks the whole body live —
an over-approximation that can only add ancestors, never lose one).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

# primitive name -> canonical collective kind reported in contracts.
# psum_scatter lowers to the reduce_scatter primitive; both spellings are
# mapped so the walker is robust across jax versions.
COLLECTIVE_PRIMS: Dict[str, str] = {
    "psum": "psum",
    "pmax": "pmax",
    "pmin": "pmin",
    "ppermute": "ppermute",
    "pshuffle": "ppermute",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "reduce_scatter": "psum_scatter",
    "psum_scatter": "psum_scatter",
}

# reduce-style kinds that consume (sum over) an axis — the family PSC102
# accepts as "the gradient reduction"
REDUCE_KINDS = ("psum", "psum_scatter", "all_to_all")


@dataclasses.dataclass(frozen=True)
class Collective:
    """One collective equation from the traced step."""

    kind: str                 # canonical kind (COLLECTIVE_PRIMS values)
    axes: Tuple[str, ...]     # mesh axis names it rides
    dtype: str                # payload dtype (first operand)
    shapes: Tuple[Tuple[int, ...], ...]  # per-operand payload shapes
    bytes: int                # per-device payload bytes (sum of operands)
    feeds_params: bool        # reverse-reachable from the params outputs

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "axes": list(self.axes),
            "dtype": self.dtype,
            "shapes": [list(s) for s in self.shapes],
            "bytes": self.bytes,
            "feeds_params": self.feeds_params,
        }


def _axes_of(eqn) -> Tuple[str, ...]:
    ax = eqn.params.get("axes", None)
    if ax is None:
        ax = eqn.params.get("axis_name", None)
    if ax is None:
        return ()
    if isinstance(ax, (tuple, list)):
        return tuple(str(a) for a in ax)
    return (str(ax),)


def _payload_by_dtype(eqn) -> List[Tuple[str, Tuple[Tuple[int, ...], ...], int]]:
    """(dtype, shapes, bytes) PER OPERAND DTYPE. jax batches a whole-tree
    psum into one eqn with every leaf as an operand; splitting by dtype
    here means a single f32 leaf smuggled into an otherwise-int8
    collective still surfaces as its own f32 record for PSC103 instead of
    hiding behind the first operand's dtype."""
    groups: Dict[str, List] = {}
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        dtype = str(aval.dtype)
        g = groups.setdefault(dtype, [[], 0])
        g[0].append(tuple(int(d) for d in aval.shape))
        numel = 1
        for d in aval.shape:
            numel *= int(d)
        g[1] += numel * aval.dtype.itemsize
    return [
        (dtype, tuple(shapes), nbytes)
        for dtype, (shapes, nbytes) in sorted(groups.items())
    ]


def _subjaxprs(eqn) -> List[Tuple[Any, bool]]:
    """(jaxpr-like, exact_io_mapping) pairs under one equation.

    exact=True means eqn invars/outvars map 1:1 onto the sub-jaxpr's —
    true for the call-like primitives; loops and branches get the
    conservative treatment.
    """
    name = eqn.primitive.name
    exact_names = {
        "pjit", "closed_call", "core_call", "xla_call", "remat", "remat2",
        "checkpoint", "custom_jvp_call", "custom_vjp_call",
        "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "shard_map",
        "custom_lin",
    }
    out: List[Tuple[Any, bool]] = []
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr",
                "body_jaxpr"):
        sub = eqn.params.get(key)
        if sub is not None:
            exact = name in exact_names and key in ("jaxpr", "call_jaxpr",
                                                    "fun_jaxpr")
            out.append((sub, exact))
    for br in eqn.params.get("branches", ()) or ():
        out.append((br, False))
    return out


def _open(jaxpr_like):
    """ClosedJaxpr | Jaxpr -> Jaxpr."""
    return getattr(jaxpr_like, "jaxpr", jaxpr_like)


def _is_var(v) -> bool:
    return hasattr(v, "aval") and not hasattr(v, "val")  # Var, not Literal


def _walk(
    jaxpr,
    live: Set[Any],
    all_live: bool,
    out: List[Collective],
) -> Set[Any]:
    """Reverse pass over one (open) jaxpr.

    `live` holds vars of THIS jaxpr known to feed the params outputs;
    returns the subset of this jaxpr's invars that feed them. Collects
    every collective eqn into `out`, marking feeds_params.
    """
    needed: Set[Any] = set(live)
    for eqn in reversed(jaxpr.eqns):
        eqn_live = all_live or any(
            v in needed for v in eqn.outvars if _is_var(v)
        )
        subs = _subjaxprs(eqn)
        name = eqn.primitive.name
        if subs:
            for sub, exact in subs:
                inner = _open(sub)
                if exact and not all_live:
                    sub_live = {
                        iv
                        for ov, iv in zip(eqn.outvars, inner.outvars)
                        if _is_var(ov) and ov in needed and _is_var(iv)
                    }
                    sub_needed = _walk(inner, sub_live, False, out)
                    # eqn invars map 1:1 onto sub invars for call-likes;
                    # zip from the END so leading const-vars (remat-style
                    # open jaxprs) stay aligned
                    n = min(len(eqn.invars), len(inner.invars))
                    for ov, iv in zip(eqn.invars[-n:], inner.invars[-n:]):
                        if iv in sub_needed and _is_var(ov):
                            needed.add(ov)
                    # constvars feeding params conservatively mark all
                    if any(cv in sub_needed for cv in inner.constvars):
                        for v in eqn.invars:
                            if _is_var(v):
                                needed.add(v)
                else:
                    _walk(inner, set(), eqn_live, out)
                    if eqn_live:
                        for v in eqn.invars:
                            if _is_var(v):
                                needed.add(v)
            continue
        if name in COLLECTIVE_PRIMS:
            for dtype, shapes, nbytes in _payload_by_dtype(eqn):
                out.append(
                    Collective(
                        kind=COLLECTIVE_PRIMS[name],
                        axes=_axes_of(eqn),
                        dtype=dtype,
                        shapes=shapes,
                        bytes=nbytes,
                        feeds_params=bool(eqn_live),
                    )
                )
        if eqn_live:
            for v in eqn.invars:
                if _is_var(v):
                    needed.add(v)
    return needed


def collect_collectives(
    closed_jaxpr,
    param_out_indices: Optional[Sequence[int]] = None,
) -> List[Collective]:
    """All collectives in a ClosedJaxpr, in reverse traversal order.

    `param_out_indices` are flat output positions (into jaxpr.outvars)
    holding the updated parameters; collectives that reach them get
    feeds_params=True. With None, every collective is (conservatively)
    marked as feeding params.
    """
    jaxpr = _open(closed_jaxpr)
    out: List[Collective] = []
    if param_out_indices is None:
        _walk(jaxpr, set(), True, out)
    else:
        live = {
            jaxpr.outvars[i]
            for i in param_out_indices
            if _is_var(jaxpr.outvars[i])
        }
        _walk(jaxpr, live, False, out)
    out.reverse()
    return out


def summarize(collectives: Sequence[Collective]) -> List[dict]:
    """Aggregate per (kind, axes, dtype): the stable accounting rows the
    committed contract artifact pins (PSC104)."""
    acc: Dict[Tuple[str, Tuple[str, ...], str], dict] = {}
    for c in collectives:
        key = (c.kind, c.axes, c.dtype)
        row = acc.setdefault(
            key,
            {
                "kind": c.kind,
                "axes": list(c.axes),
                "dtype": c.dtype,
                "count": 0,
                "bytes": 0,
            },
        )
        row["count"] += 1
        row["bytes"] += c.bytes
    return [
        acc[k]
        for k in sorted(acc, key=lambda k: (k[0], k[1], k[2]))
    ]
