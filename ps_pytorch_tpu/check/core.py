"""pscheck engine: trace contract specs, run rules, round-trip the
committed accounting artifact (runs/comm_contract.json).

Tracing is CPU-only and executes nothing: jax.make_jaxpr over abstract
args gives the collective-level truth, one extra .lower() gives the
donation attributes. Everything downstream is pure data.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .contracts import ContractSpec
from .walker import Collective, collect_collectives, summarize

CONTRACT_VERSION = 1
DEFAULT_CONTRACT = "runs/comm_contract.json"

# MLIR attributes marking a donated input: tf.aliasing_output when the
# lowering already paired it with an output, jax.buffer_donor when the
# pairing is left to XLA. Either means donation survived lowering.
_DONOR_MARKS = ("tf.aliasing_output", "jax.buffer_donor")


@dataclasses.dataclass(frozen=True)
class CheckFinding:
    rule: str
    config: str
    message: str

    def to_json(self) -> dict:
        return {"rule": self.rule, "config": self.config,
                "message": self.message}


@dataclasses.dataclass
class TraceResult:
    """One contract spec's measured truth."""

    spec: ContractSpec
    collectives: List[Collective]
    summary: List[dict]               # PSC104 accounting rows
    donor_marks: int                  # donated inputs that survived lowering
    donated_leaves: int               # leaves of the declared donated args
    donation_mismatches: List[str]    # in/out aval mismatches (would drop
                                      # aliasing on the pod)
    kv_leaves: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list
    )                                 # (path, dtype) of the KV pool arg,
                                      # collected when spec.serve is set
                                      # (PSC107 storage-dtype policy)
    numerics: Any = None              # NumericsReport (check/numerics.py)
                                      # — the precision-flow record the
                                      # PSC111-114 rules read, computed
                                      # whenever spec.numerics is set
    closed: Any = None                # the traced ClosedJaxpr, retained
                                      # only when trace_spec(keep_jaxpr=
                                      # True) — the tune/ cost model
                                      # derives update-path ops and
                                      # overlap headroom from the SAME
                                      # trace the rules ran on, instead
                                      # of re-tracing per probe


def _tree_leaves_with_none(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def _donation_info(built, spec: ContractSpec) -> Tuple[int, int, List[str]]:
    import jax

    if spec.donation is None:
        return 0, 0, []
    lowered = built.step.lower(*built.args)
    txt = lowered.as_text()
    marks = sum(txt.count(m) for m in _DONOR_MARKS)
    out = jax.eval_shape(built.step, *built.args)
    donated = 0
    mismatches: List[str] = []
    for argnum, pos in zip(spec.donation.argnums,
                           spec.donation.out_positions):
        in_sub = built.args[argnum]
        out_sub = out[pos]
        in_leaves, in_def = jax.tree_util.tree_flatten(in_sub)
        out_leaves, out_def = jax.tree_util.tree_flatten(out_sub)
        donated += len(in_leaves)
        if in_def != out_def:
            mismatches.append(
                f"arg {argnum}: donated tree structure != output {pos} "
                f"structure (aliasing impossible)"
            )
            continue
        for i, (a, b) in enumerate(zip(in_leaves, out_leaves)):
            if tuple(a.shape) != tuple(b.shape) or str(a.dtype) != str(b.dtype):
                mismatches.append(
                    f"arg {argnum} leaf {i}: donated "
                    f"{a.dtype}{list(a.shape)} but output {pos} returns "
                    f"{b.dtype}{list(b.shape)} — XLA cannot alias "
                    f"mismatched buffers, donation is silently dropped"
                )
    return marks, donated, mismatches


def trace_spec(spec: ContractSpec, keep_jaxpr: bool = False) -> TraceResult:
    """Trace one contract's real step and measure its collectives.

    ``keep_jaxpr=True`` retains the ClosedJaxpr on the result so
    downstream consumers (tune/costmodel.py) can run further jaxpr-level
    analyses without paying a second trace."""
    import jax

    built = spec.build()
    closed = jax.make_jaxpr(built.step)(*built.args)
    out_shapes = jax.eval_shape(built.step, *built.args)
    flat_out, _ = jax.tree_util.tree_flatten(out_shapes)
    sel_ids = {
        id(leaf)
        for leaf in jax.tree_util.tree_leaves(
            built.select_params(out_shapes)
        )
    }
    param_idx = [i for i, leaf in enumerate(flat_out) if id(leaf) in sel_ids]
    colls = collect_collectives(closed, param_out_indices=param_idx)
    marks, donated, mismatches = _donation_info(built, spec)
    kv_leaves: List[Tuple[str, str]] = []
    if spec.serve is not None:
        flat_kv = jax.tree_util.tree_flatten_with_path(
            built.args[spec.serve.kv_argnum]
        )[0]
        kv_leaves = [
            (jax.tree_util.keystr(path), str(leaf.dtype))
            for path, leaf in flat_kv
        ]
    numerics = None
    if spec.numerics is not None:
        from .numerics import analyze_numerics

        numerics = analyze_numerics(closed, param_out_indices=param_idx)
    return TraceResult(
        spec=spec,
        collectives=colls,
        summary=summarize(colls),
        donor_marks=marks,
        donated_leaves=donated,
        donation_mismatches=mismatches,
        kv_leaves=kv_leaves,
        numerics=numerics,
        closed=closed if keep_jaxpr else None,
    )


def trace_registry(
    specs: Sequence[ContractSpec], only: Optional[Sequence[str]] = None
) -> List[TraceResult]:
    chosen = [s for s in specs if only is None or s.name in only]
    return [trace_spec(s) for s in chosen]


# ---------------------------------------------------------------- artifact

def to_contract_json(results: Sequence[TraceResult]) -> dict:
    from .contracts import MESH_DEVICES

    return {
        "version": CONTRACT_VERSION,
        "tool": "pscheck",
        "mesh_devices": MESH_DEVICES,
        "configs": {
            r.spec.name: {
                "axes": list(r.spec.axes),
                "collectives": r.summary,
                "n_collectives": sum(row["count"] for row in r.summary),
                "total_bytes": sum(row["bytes"] for row in r.summary),
            }
            for r in sorted(results, key=lambda r: r.spec.name)
        },
    }


def load_contract(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("tool") != "pscheck":
        raise ValueError(f"{path} is not a pscheck contract artifact")
    return data


def write_contract(path: str, results: Sequence[TraceResult]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_contract_json(results), f, indent=2, sort_keys=True)
        f.write("\n")


def run_checks(
    results: Sequence[TraceResult],
    contract: Optional[dict],
    check_stale: bool = True,
) -> List[CheckFinding]:
    """Run every rule over traced results; `contract` is the committed
    artifact (None skips PSC104 — used by --write-contract)."""
    from .rules import (
        check_result,
        psc104_roundtrip,
        psc109_schedule,
        psc110_consensus,
    )

    findings: List[CheckFinding] = []
    for r in results:
        findings.extend(check_result(r))
    findings.extend(psc109_schedule(results))
    findings.extend(psc110_consensus(results))
    if contract is not None:
        findings.extend(psc104_roundtrip(results, contract,
                                         check_stale=check_stale))
    findings.sort(key=lambda f: (f.config, f.rule, f.message))
    return findings


def render_text(findings: Sequence[CheckFinding],
                n_configs: int) -> str:
    out: List[str] = []
    for f in findings:
        out.append(f"{f.config}: {f.rule} {f.message}")
    rules = sorted({f.rule for f in findings})
    out.append(
        f"pscheck: {len(findings)} finding(s)"
        + (f" ({', '.join(rules)})" if rules else "")
        + f" across {n_configs} traced config(s)"
    )
    return "\n".join(out)
