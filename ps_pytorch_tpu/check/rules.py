"""pscheck rules PSC101-PSC105: contract checks over a traced step.

| rule   | guards against                                                  |
|--------|-----------------------------------------------------------------|
| PSC101 | a declared mesh axis no collective consumes (dead parallelism — |
|        | e.g. a dropped dp reduction), or a collective riding an axis    |
|        | the scheme never declared                                       |
| PSC102 | a gradient reduction that no longer feeds the optimizer: for    |
|        | each axis with replicated gradient leaves, a reduce of the      |
|        | declared kind must be an ancestor of the updated params (the    |
|        | ARCHITECTURE §2 recipe, checked by jaxpr dataflow — a metrics   |
|        | pmean over the same axis does NOT count)                        |
| PSC103 | wire-dtype regressions on compressed paths: with an int8 wire   |
|        | declared, every collective on those axes must carry int8 except |
|        | the explicitly-allowed scale rows / metrics / update gathers    |
| PSC104 | silent wire-byte drift: the full per-collective accounting      |
|        | (kind, axes, dtype, count, bytes) must round-trip against the   |
|        | committed runs/comm_contract.json                               |
| PSC105 | dropped donation: every donated input must survive lowering as  |
|        | a donor/alias mark, and its output partner must match in        |
|        | structure/shape/dtype (mismatch = XLA silently un-donates)      |
| PSC106 | silent de-fusion on a bucketed wire: a scheme declaring a       |
|        | FusionSpec may emit at most per_bucket * ceil(payload_bytes /   |
|        | bucket_bytes) + slack reduce-kind collectives feeding the       |
|        | updated params — a refactor quietly going back to one           |
|        | collective per pytree leaf fails the gate                       |
| PSC107 | serving hot-path regressions: a step declaring a ServePolicy    |
|        | (the slot-parallel decode step, serve/engine.py) must emit ZERO |
|        | collectives, and its KV pool must honor the declared storage    |
|        | dtype (int8 payload + f32 block scales when quantized; the      |
|        | compute dtype otherwise) — an f32 leaf in a declared-int8 pool  |
|        | is the serving analogue of a PSC103 wire regression             |
| PSC108 | adaptive-mask regressions: a config declaring an AdaptivePolicy |
|        | (traced aggregation count, PSConfig.num_aggregate_min/max) must |
|        | still declare its grad-reduce requirement — so PSC102's         |
|        | dataflow rule keeps pinning the masked reduce — and its         |
|        | gradient-path reduce bytes must stay inside the declared        |
|        | envelope: adaptation reshapes values, never wire bytes. The     |
|        | same discipline covers adaptive per-bucket precision: a config  |
|        | declaring a PrecisionPolicy (traced tag vector, PSConfig.       |
|        | precision_adapt) keeps grad_reduce declared and its reduce      |
|        | bytes inside the precision envelope — a tag picks the LATTICE   |
|        | the values occupy, never the payload's size                     |
| PSC109 | schedule-variance on the pipelined wire: a config declaring an  |
|        | OverlapPolicy (PSConfig.overlap="pipelined") must move EXACTLY  |
|        | the gradient-path reduce bytes of its named serial twin (same   |
|        | bytes, different schedule — pipelining may reorder and split,   |
|        | never grow or shrink the wire), and must really dispatch per    |
|        | bucket: at least n_buckets x per_bucket reduce-kind             |
|        | collectives each feeding the updated params, so the PSC102      |
|        | dataflow guarantee holds PER BUCKET — a "pipelined" config      |
|        | whose wire quietly re-fused into one barrier eqn fails          |
| PSC110 | undeclared host-consensus for adaptive configs: a config        |
|        | declaring an AdaptivePolicy (or PrecisionPolicy) must NAME the  |
|        | host-consensus point (``.consensus``, a package-relative dotted |
|        | path) that agrees the traced values across processes, and that  |
|        | must resolve in pslint's consensus inventory (lint/diverge.py:  |
|        | a function whose return passes through broadcast_one_to_all /   |
|        | process_allgather) — an adaptive knob with no consensus point   |
|        | is PR 7's per-host agg_count tear waiting to recur              |
| PSC111 | fresh or mismatched scale rows: every dequantize's scale must   |
|        | be a dataflow descendant of the SAME max-abs reduction that     |
|        | produced its quantize's scale, across every hop of the 2round   |
|        | and hier wires (check/numerics.py provenance roots) — a scale   |
|        | minted from a constant or a different reduction would decode    |
|        | the lattice against the wrong dynamic range                     |
| PSC112 | broken error-feedback closure: with error_feedback declared,    |
|        | every primary quantization site on the gradient path must have  |
|        | a residual consumer of the form grad - dequant(quant) whose     |
|        | result feeds the next step's carry (and NOT the updated params  |
|        | too — that double-counts the correction); a dropped residual    |
|        | silently degrades EF-SGD back to biased quantized SGD           |
| PSC113 | integer-accumulation overflow proven from the trace: worst-case |
|        | |sum| bound = clamp peak x product of the traced collective     |
|        | axis sizes (hier = DCN x ICI product) must fit the payload      |
|        | dtype, replacing trust in the config-time ACCUM_CAPACITY table; |
|        | also refuses lattice reductions whose traced dtype is not the   |
|        | declared accumulator (PR 12's widened-payload regression) and   |
|        | homomorphic_rescale divisors that saturate the requant clamp    |
| PSC114 | silent downcast on the update path: every convert_element_type  |
|        | downstream of the gradient reduce that narrows precision and    |
|        | feeds the updated params must be a detected quantization site   |
|        | or a declared NarrowingAllowance — extends PSC103 from policing |
|        | wire dtypes to proving WHERE narrowing may happen at all        |

PSC111-114 read the NumericsReport that check/numerics.py distills from
the same traced jaxpr (TraceResult.numerics, present whenever the spec
declares a NumericsPolicy). Events flagged ``conservative`` crossed a
scan/while carry, where the analyzer widens to unknown — the rules turn
those into explicit "cannot prove" findings rather than passing
vacuously inside a loop body.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .core import CheckFinding, TraceResult
from .walker import REDUCE_KINDS

RULE_IDS = ("PSC101", "PSC102", "PSC103", "PSC104", "PSC105", "PSC106",
            "PSC107", "PSC108", "PSC109", "PSC110", "PSC111", "PSC112",
            "PSC113", "PSC114")


def psc101_axes(r: TraceResult) -> List[CheckFinding]:
    declared = set(r.spec.axes)
    used = set()
    for c in r.collectives:
        used.update(c.axes)
    out = []
    for ax in sorted(declared - used):
        out.append(CheckFinding(
            "PSC101", r.spec.name,
            f"declared mesh axis '{ax}' is consumed by no collective "
            f"(dead parallel axis — dropped reduction?)",
        ))
    for ax in sorted(used - declared):
        out.append(CheckFinding(
            "PSC101", r.spec.name,
            f"collective rides undeclared axis '{ax}' "
            f"(declared: {sorted(declared)})",
        ))
    return out


def psc102_grad_reduce(r: TraceResult) -> List[CheckFinding]:
    out = []
    for req in r.spec.grad_reduce:
        hit = any(
            c.feeds_params and req.axis in c.axes and c.kind in req.kinds
            for c in r.collectives
        )
        if not hit:
            near_misses = sorted({
                c.kind for c in r.collectives
                if req.axis in c.axes and c.kind in req.kinds
            })
            hint = (
                " (a matching reduce exists but feeds only non-param "
                "outputs, e.g. metrics)" if near_misses else ""
            )
            out.append(CheckFinding(
                "PSC102", r.spec.name,
                f"no {'/'.join(req.kinds)} over axis '{req.axis}' feeds "
                f"the updated params — replicated gradient leaves are "
                f"not reduced before the optimizer{hint}",
            ))
    return out


def psc103_wire(r: TraceResult) -> List[CheckFinding]:
    wire = r.spec.wire
    if wire is None:
        return []
    out = []
    for c in r.collectives:
        if not set(c.axes) & set(wire.axes):
            continue
        if c.dtype == wire.payload_dtype:
            continue
        allowed = False
        for a in wire.allow:
            if a.kind != c.kind or a.dtype != c.dtype:
                continue
            if a.axes is not None and not set(c.axes) <= set(a.axes):
                continue
            if a.max_bytes is not None and c.bytes > a.max_bytes:
                continue
            allowed = True
            break
        if not allowed:
            out.append(CheckFinding(
                "PSC103", r.spec.name,
                f"{c.kind} over {list(c.axes)} carries {c.dtype} "
                f"({c.bytes} B) on a declared {wire.payload_dtype} wire "
                f"— compression regression (no allowance covers it)",
            ))
    return out


def psc106_fusion(r: TraceResult) -> List[CheckFinding]:
    """Count the reduce-kind collectives on the gradient path (the
    payload-carrying psum / psum_scatter / all_to_all eqns that feed the
    updated params — scale pmax rows, the guard pmin, gathers, and the
    metrics pmean are out of scope) against the declared bucket budget."""
    fu = r.spec.fusion
    if fu is None:
        return []
    got = _grad_reduce_count(r)
    if got <= fu.max_collectives:
        return []
    granularity = (
        "one fused buffer"
        if not fu.bucket_bytes
        else f"{fu.n_buckets} bucket(s) of ~{fu.bucket_bytes} B"
    )
    return [CheckFinding(
        "PSC106", r.spec.name,
        f"{got} gradient-path reduce collectives, but the declared "
        f"bucket plan ({granularity} over {fu.payload_bytes} B payload, "
        f"per_bucket={fu.per_bucket}, slack={fu.slack}) allows at most "
        f"{fu.max_collectives} — the wire has silently de-fused "
        f"(per-leaf collectives crept back in?)",
    )]


def psc107_serve(r: TraceResult) -> List[CheckFinding]:
    """The serving hot path: zero collectives + KV storage dtype policy.

    Collectives are checked at the jaxpr level (named-axis ops): the
    decode step is slot-parallel by construction — weights replicated,
    pool sharded over slots — so ANY collective means training-style
    communication crept into the request loop. The dtype policy walks
    the KV pool arg's leaves by path: ``*_q`` payload / ``*_s`` scale
    rows for a quantized pool, plain K/V in the declared compute dtype
    otherwise."""
    sp = r.spec.serve
    if sp is None:
        return []
    out = []
    for c in r.collectives:
        out.append(CheckFinding(
            "PSC107", r.spec.name,
            f"{c.kind} over {list(c.axes)} [{c.dtype}, {c.bytes} B] on "
            f"the serving hot path — the decode step is slot-parallel "
            f"and must emit zero collectives",
        ))
    for path, dtype in r.kv_leaves:
        if sp.quantized:
            if path.endswith("_q']"):
                want = "int8"
            elif path.endswith("_s']"):
                want = "float32"
            else:
                out.append(CheckFinding(
                    "PSC107", r.spec.name,
                    f"KV pool leaf {path} [{dtype}] on a declared int8 "
                    f"pool is neither payload (*_q) nor scale row (*_s) "
                    f"— unquantized storage crept in",
                ))
                continue
        else:
            want = sp.kv_dtype
        if dtype != want:
            out.append(CheckFinding(
                "PSC107", r.spec.name,
                f"KV pool leaf {path} carries {dtype}, declared storage "
                f"dtype is {want} — serving cache dtype regression",
            ))
    return out


def psc108_adaptive(r: TraceResult) -> List[CheckFinding]:
    """The adaptive-mask contract: (a) the spec must keep a grad_reduce
    declaration — the traced count is a pre-reduce multiply, so PSC102's
    "masked reduce feeds the updated params" check is the dataflow rule
    and PSC108 refuses the opt-out of it; (b) the gradient-path reduce
    collectives must fit the declared byte envelope — a mask count is
    VALUES (which workers contribute, what divides the sum), so any
    per-count growth of the wire (mask gathers, resized payloads) is a
    regression."""
    ap = r.spec.adaptive
    if ap is None:
        return []
    out = []
    if not r.spec.grad_reduce:
        out.append(CheckFinding(
            "PSC108", r.spec.name,
            "adaptive aggregation declared but no grad_reduce "
            "requirement — without it PSC102 cannot pin the masked "
            "reduce's dataflow to the updated params",
        ))
    got = _grad_reduce_bytes(r)
    if got > ap.envelope_bytes:
        out.append(CheckFinding(
            "PSC108", r.spec.name,
            f"gradient-path reduce collectives move {got} B, but the "
            f"adaptive envelope (counts {ap.min_aggregate}-"
            f"{ap.max_aggregate}) declares at most {ap.envelope_bytes} B "
            f"— the traced mask must reshape values, not add wire bytes",
        ))
    return out


def psc108_precision(r: TraceResult) -> List[CheckFinding]:
    """The adaptive-precision half of PSC108: a config taking a traced
    per-bucket tag vector (PrecisionPolicy) keeps the same discipline as
    the traced mask count — (a) a grad_reduce declaration so PSC102 pins
    the (re-lattice'd) reduce's dataflow, and (b) the gradient-path
    reduce bytes inside the declared envelope: a tag selects which
    LATTICE a bucket's values occupy inside the same physical payload
    (the traced clipping peak), so per-tag payload resizes or side
    channels are wire regressions, not adaptation."""
    pp = r.spec.precision
    if pp is None:
        return []
    out = []
    if not r.spec.grad_reduce:
        out.append(CheckFinding(
            "PSC108", r.spec.name,
            "adaptive precision declared but no grad_reduce requirement "
            "— without it PSC102 cannot pin the tagged reduce's dataflow "
            "to the updated params",
        ))
    got = _grad_reduce_bytes(r)
    if got > pp.envelope_bytes:
        out.append(CheckFinding(
            "PSC108", r.spec.name,
            f"gradient-path reduce collectives move {got} B, but the "
            f"precision envelope ({pp.n_buckets} traced bucket tags) "
            f"declares at most {pp.envelope_bytes} B — precision tags "
            f"must reshape values on the lattice, not add wire bytes",
        ))
    return out


def _grad_reduce_bytes(r: TraceResult) -> int:
    return sum(
        c.bytes
        for c in r.collectives
        if c.feeds_params and c.kind in REDUCE_KINDS
    )


def _grad_reduce_count(r: TraceResult) -> int:
    return sum(
        1
        for c in r.collectives
        if c.feeds_params and c.kind in REDUCE_KINDS
    )


def psc109_schedule(results: Sequence[TraceResult]) -> List[CheckFinding]:
    """Schedule invariance for pipelined configs (cross-result rule,
    like PSC104): byte-equality against the serial twin when the twin
    was traced in the same batch, and per-bucket dispatch — the
    pipelined wire must emit one reduce chain per bucket (x the
    scheme's per-bucket collective cost), each a dataflow ancestor of
    the updated params."""
    out: List[CheckFinding] = []
    by_name = {r.spec.name: r for r in results}
    for r in results:
        ov = r.spec.overlap
        if ov is None or ov.mode != "pipelined":
            continue
        fu = r.spec.fusion
        if fu is None:
            out.append(CheckFinding(
                "PSC109", r.spec.name,
                "pipelined overlap declared without a FusionSpec — the "
                "per-bucket dispatch requirement needs the bucket plan "
                "to know how many reduce chains to demand",
            ))
        else:
            want = fu.per_bucket * fu.n_buckets
            got = _grad_reduce_count(r)
            if got < want:
                out.append(CheckFinding(
                    "PSC109", r.spec.name,
                    f"only {got} gradient-path reduce collectives for a "
                    f"pipelined plan of {fu.n_buckets} bucket(s) "
                    f"(x{fu.per_bucket} per bucket = {want} expected) — "
                    f"the wire has re-fused into a barrier; the "
                    f"schedule is serial no matter what the config "
                    f"declares",
                ))
        twin = by_name.get(ov.serial_twin) if ov.serial_twin else None
        if twin is None:
            # the twin wasn't traced in this batch (e.g. --only) — the
            # byte pin still holds transitively via PSC104 on both
            continue
        mine, theirs = _grad_reduce_bytes(r), _grad_reduce_bytes(twin)
        if mine != theirs:
            out.append(CheckFinding(
                "PSC109", r.spec.name,
                f"gradient-path reduce collectives move {mine} B but the "
                f"serial twin '{twin.spec.name}' moves {theirs} B — "
                f"pipelining must reorder the schedule, never change "
                f"the bytes",
            ))
    return out


def psc110_consensus(results: Sequence[TraceResult]) -> List[CheckFinding]:
    """Adaptive configs must declare a REAL host-consensus point.

    The traced aggregation count is a jitted-step input that must be
    bit-identical on every process (a torn count = different masked
    reduces = divergent replicated params, PR 7's bug). The dynamic half
    of that guarantee is pslint's PSL007; this is the static registry
    half: every AdaptivePolicy names where consensus happens, and the
    name must resolve to a consensus-shaped function (its return value
    passes through broadcast_one_to_all/process_allgather) in the
    package — found by the same AST walker the divergence lint uses
    (lint/diverge.py:consensus_inventory), so a renamed or de-consensused
    helper breaks this gate, not a pod run."""
    from ..lint.diverge import consensus_inventory

    out: List[CheckFinding] = []
    inventory = None
    # (policy object, traced-knob label, example) per adaptive surface:
    # the mask count and the precision tag vector carry the same torn-
    # traced-value hazard, so both must name an inventory-backed point
    knobs = (
        ("adaptive", "traced aggregation count",
         "trainer.Trainer._count_consensus"),
        ("precision", "traced per-bucket precision tag vector",
         "trainer.Trainer._tags_consensus"),
    )
    for r in results:
        for attr, what, example in knobs:
            pol = getattr(r.spec, attr, None)
            if pol is None:
                continue
            if not pol.consensus:
                out.append(CheckFinding(
                    "PSC110", r.spec.name,
                    f"{type(pol).__name__} declares a {what} but no "
                    f"host-consensus point — each process would adapt "
                    f"on its own telemetry and feed the step torn "
                    f"values; name the function that agrees them "
                    f"(e.g. '{example}')",
                ))
                continue
            if inventory is None:
                inventory = consensus_inventory()
            if pol.consensus not in inventory:
                known = ", ".join(sorted(inventory)) or "none found"
                out.append(CheckFinding(
                    "PSC110", r.spec.name,
                    f"declared host-consensus point '{pol.consensus}' "
                    f"is not in the package's consensus inventory "
                    f"(functions whose return passes through "
                    f"broadcast_one_to_all/process_allgather; known: "
                    f"{known}) — renamed, or no longer consensus-shaped",
                ))
    return out


def psc105_donation(r: TraceResult) -> List[CheckFinding]:
    if r.spec.donation is None:
        return []
    out = []
    if r.donor_marks < r.donated_leaves:
        out.append(CheckFinding(
            "PSC105", r.spec.name,
            f"only {r.donor_marks} of {r.donated_leaves} donated input "
            f"buffers survive lowering with a donor/alias mark — "
            f"donation was dropped (donate_argnums missing or overridden)",
        ))
    for msg in r.donation_mismatches:
        out.append(CheckFinding("PSC105", r.spec.name, msg))
    return out


def _numerics(r: TraceResult):
    """The (policy, report) pair the PSC111-114 rules read, or (None,
    None) when the spec declares no NumericsPolicy (old fixtures)."""
    pol = getattr(r.spec, "numerics", None)
    rep = r.numerics
    if pol is None or rep is None:
        return None, None
    return pol, rep


def psc111_scale_provenance(r: TraceResult) -> List[CheckFinding]:
    """Every dequantize's scale must descend from the SAME max-abs
    reduction that produced its quantize's scale (shared provenance
    root), across every hop of the 2round / hier wires."""
    pol, rep = _numerics(r)
    if rep is None:
        return []
    out = []
    by_sid = {s.sid: s for s in rep.sites}
    for d in rep.dequants:
        for sid in sorted(d.payload_sites):
            s = by_sid.get(sid)
            if s is None or s.roots & d.scale_roots:
                continue
            origin = ("a static constant" if d.scale_literal
                      else "a different dataflow origin" if d.scale_roots
                      else "no max-abs reduction at all")
            verb = ("cannot be proven to descend"
                    if (d.conservative or s.conservative)
                    else "does not descend")
            out.append(CheckFinding(
                "PSC111", r.spec.name,
                f"dequantize of the {s.dtype} payload at offset "
                f"{s.start_offset} takes its scale from {origin}: the "
                f"scale {verb} from the max-abs reduction behind the "
                f"quantize's scale — the lattice decodes against the "
                f"wrong dynamic range",
            ))
    if pol.quantized:
        for s in rep.sites:
            if s.primary and s.feeds_params and not s.roots:
                out.append(CheckFinding(
                    "PSC111", r.spec.name,
                    f"quantization site at offset {s.start_offset} "
                    f"({s.dtype}, {s.size} elem) on the gradient path "
                    f"has no max-abs reduction in its scale chain — its "
                    f"clamp bound was minted from a constant, not from "
                    f"the data's dynamic range",
                ))
    return out


def psc112_error_feedback(r: TraceResult) -> List[CheckFinding]:
    """With error_feedback declared, every primary quantization site on
    the gradient path needs a grad - dequant(quant) residual that feeds
    the next step's carry — and only the carry (feeding the params too
    double-counts the correction)."""
    pol, rep = _numerics(r)
    if rep is None or not pol.error_feedback:
        return []
    primary = [s for s in rep.sites if s.primary and s.feeds_params]
    if not primary:
        return [CheckFinding(
            "PSC112", r.spec.name,
            "error_feedback declared but the trace has no primary "
            "quantization site on the gradient path — there is no "
            "quantization error for a residual to close over",
        )]
    out = []
    live = [e for e in rep.residuals if e.feeds_carry]
    for s in primary:
        cov = [e for e in live if s.sid in e.covered_sites]
        if not cov:
            out.append(CheckFinding(
                "PSC112", r.spec.name,
                f"quantization site at offset {s.start_offset} "
                f"({s.dtype}, {s.size} elem) has no residual consumer "
                f"grad - dequant(quant) feeding the next step's carry — "
                f"the quantization error is dropped and EF-SGD silently "
                f"degrades to biased quantized SGD",
            ))
        elif s.conservative or all(e.conservative for e in cov):
            out.append(CheckFinding(
                "PSC112", r.spec.name,
                f"cannot prove error-feedback closure for the "
                f"quantization site at offset {s.start_offset}: the "
                f"residual chain crosses a scan/while carry, where "
                f"bounds and dataflow widen to unknown",
            ))
    for e in rep.residuals:
        if e.covered_sites and e.feeds_carry and e.feeds_params:
            out.append(CheckFinding(
                "PSC112", r.spec.name,
                f"the error-feedback residual covering site(s) "
                f"{sorted(e.covered_sites)} feeds BOTH the carried "
                f"residual and the updated params — the correction is "
                f"applied this step AND replayed next step "
                f"(double-counted)",
            ))
    return out


def psc113_capacity(r: TraceResult) -> List[CheckFinding]:
    """Integer-accumulation capacity proven from the trace: worst-case
    |sum| = clamp peak x the traced summand count (collective axis
    sizes, reduce dims) must fit the payload dtype — plus the declared-
    accumulator dtype pin (PR 12's widened-payload shape) and the
    homomorphic_rescale saturation check."""
    pol, rep = _numerics(r)
    if rep is None:
        return []
    out = []
    for a in rep.accums:
        where = f"{a.kind} over {list(a.axes)}" if a.axes else a.kind
        if (a.peak_out is not None and a.capacity is not None
                and a.peak_out > a.capacity):
            summands = (
                f" ({a.multiplier} summands x |payload| <= {a.peak_in:g})"
                if a.multiplier is not None and a.peak_in is not None
                else ""
            )
            cap_kind = ("exact-mantissa capacity"
                        if a.kind == "mantissa" or not a.dtype.startswith(
                            "int")
                        else "dtype capacity")
            out.append(CheckFinding(
                "PSC113", r.spec.name,
                f"{where} in {a.dtype} reaches worst-case |sum| = "
                f"{a.peak_out:g}{summands}, over the {cap_kind} "
                f"{a.capacity} — the traced accumulation overflows",
            ))
        elif a.lattice and a.peak_out is None:
            reason = (
                "the bound crosses a scan/while carry"
                if a.conservative
                else "unknown axis size"
                if a.multiplier is None and a.kind in ("psum",
                                                       "psum_scatter")
                else "the payload bound is unknown"
            )
            out.append(CheckFinding(
                "PSC113", r.spec.name,
                f"cannot prove {where} in {a.dtype} fits: lattice "
                f"payload with no provable |sum| bound ({reason}) — "
                f"quantized accumulation must be proven from the trace, "
                f"not assumed",
            ))
        elif (pol.quantized and a.kind in ("psum", "psum_scatter")
              and a.dtype in ("int8", "int16") and a.feeds_params
              and a.peak_out is None):
            out.append(CheckFinding(
                "PSC113", r.spec.name,
                f"cannot prove {where} fits {a.dtype}: the wire payload "
                f"carries no provable clamp bound into the reduce — an "
                f"unclamped cast is on the quantized wire",
            ))
        if (pol.accum_dtype is not None and a.lattice
                and a.kind in ("psum", "psum_scatter")
                and a.dtype.startswith("int")
                and a.dtype != pol.accum_dtype):
            out.append(CheckFinding(
                "PSC113", r.spec.name,
                f"lattice {where} carries {a.dtype} on a declared "
                f"{pol.accum_dtype} accumulator — the widened payload "
                f"crept back onto the wire (the PR 12 regression shape)",
            ))
    for s in rep.sites:
        if s.primary or not s.feeds_params:
            # primary quantizes divide by their own max-abs: in-range by
            # construction; only lattice REQUANTS (homomorphic_rescale)
            # carry a divisor that can saturate the clamp
            continue
        if s.pre_peak is None:
            out.append(CheckFinding(
                "PSC113", r.spec.name,
                f"cannot prove the lattice requantize at offset "
                f"{s.start_offset} ({s.dtype}) stays in range: the "
                f"pre-clamp |value| bound is unknown, so the "
                f"homomorphic_rescale divisor cannot be proven to "
                f"prevent saturation",
            ))
        elif s.peak is not None and s.pre_peak > s.peak + 1e-6:
            out.append(CheckFinding(
                "PSC113", r.spec.name,
                f"lattice requantize at offset {s.start_offset} "
                f"saturates: |value| reaches {s.pre_peak:g} before the "
                f"+-{s.peak:g} clamp — the homomorphic_rescale divisor "
                f"is too small and the wire clips",
            ))
    return out


def psc114_downcast(r: TraceResult) -> List[CheckFinding]:
    """No silent downcast on the update path: a precision-narrowing
    convert downstream of the gradient reduce that feeds the updated
    params must be a detected quantization site (those never land in
    ``narrows``) or a declared NarrowingAllowance."""
    pol, rep = _numerics(r)
    if rep is None:
        return []
    allowed = {(a.src, a.dst) for a in pol.allow_narrowing}
    out = []
    for n in rep.narrows:
        if not n.downstream_of_reduce or not n.feeds_params:
            continue
        if (n.src, n.dst) in allowed:
            continue
        out.append(CheckFinding(
            "PSC114", r.spec.name,
            f"convert {n.src}->{n.dst} downstream of the gradient "
            f"reduce feeds the updated params but is neither a "
            f"quantization site (no provable clamp bound) nor a "
            f"declared NarrowingAllowance — precision drops silently "
            f"on the update path",
        ))
    return out


def check_result(r: TraceResult) -> List[CheckFinding]:
    return (
        psc101_axes(r)
        + psc102_grad_reduce(r)
        + psc103_wire(r)
        + psc105_donation(r)
        + psc106_fusion(r)
        + psc107_serve(r)
        + psc108_adaptive(r)
        + psc108_precision(r)
        + psc111_scale_provenance(r)
        + psc112_error_feedback(r)
        + psc113_capacity(r)
        + psc114_downcast(r)
    )


def _row_key(row: dict) -> tuple:
    return (row["kind"], tuple(row["axes"]), row["dtype"])


def psc104_roundtrip(
    results: Sequence[TraceResult],
    contract: dict,
    check_stale: bool = True,
) -> List[CheckFinding]:
    """Diff the measured accounting against the committed artifact."""
    out: List[CheckFinding] = []
    configs: Dict[str, dict] = contract.get("configs", {})
    for r in results:
        pinned = configs.get(r.spec.name)
        if pinned is None:
            out.append(CheckFinding(
                "PSC104", r.spec.name,
                "config missing from the contract artifact — refresh with "
                "--write-contract",
            ))
            continue
        want = {_row_key(row): row for row in pinned.get("collectives", [])}
        got = {_row_key(row): row for row in r.summary}
        for key in sorted(set(want) | set(got)):
            kind, axes, dtype = key
            label = f"{kind} over {list(axes)} [{dtype}]"
            if key not in want:
                out.append(CheckFinding(
                    "PSC104", r.spec.name,
                    f"unpinned collective appeared: {label} "
                    f"(count={got[key]['count']}, bytes={got[key]['bytes']})",
                ))
            elif key not in got:
                out.append(CheckFinding(
                    "PSC104", r.spec.name,
                    f"pinned collective vanished: {label} "
                    f"(was count={want[key]['count']}, "
                    f"bytes={want[key]['bytes']})",
                ))
            elif (want[key]["count"] != got[key]["count"]
                  or want[key]["bytes"] != got[key]["bytes"]):
                out.append(CheckFinding(
                    "PSC104", r.spec.name,
                    f"wire accounting drift for {label}: pinned "
                    f"count={want[key]['count']} bytes={want[key]['bytes']}"
                    f", measured count={got[key]['count']} "
                    f"bytes={got[key]['bytes']}",
                ))
    if check_stale:
        traced = {r.spec.name for r in results}
        for name in sorted(set(configs) - traced):
            out.append(CheckFinding(
                "PSC104", name,
                "stale contract entry: config no longer in the registry — "
                "refresh with --write-contract",
            ))
    return out
