"""CLI: ``python -m ps_pytorch_tpu.check [options]``.

Exit codes mirror pslint: 0 = every contract holds, 1 = findings,
2 = usage error. ``--write-contract`` regenerates the committed
accounting artifact (runs/comm_contract.json) from the current registry
and exits 0 — the PSC101/102/103/105/106 rules still run first, so a
broken step cannot silently re-baseline itself.

Tracing needs a deterministic 8-device CPU backend; when launched as a
real CLI in the ambient (broken-TPU-plugin) environment the process
re-execs itself under the tpu_env scrub first, exactly like the test
suite's root conftest. Programmatic callers (tests) are already clean
and skip the re-exec.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys


def _reexec_clean_env() -> None:
    """Re-exec under the CPU scrub if the ambient env would hang jax."""
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        from tpu_env import clean_cpu_env, env_is_clean
    except ImportError:
        return  # installed outside the repo: trust the caller's env
    from .contracts import MESH_DEVICES

    if env_is_clean(n_devices=MESH_DEVICES):
        return
    os.execve(
        sys.executable,
        [sys.executable, "-m", "ps_pytorch_tpu.check", *sys.argv[1:]],
        clean_cpu_env(n_devices=MESH_DEVICES),
    )


def _load_registry(module_name: str):
    mod = importlib.import_module(module_name)
    get = getattr(mod, "get_contracts", None)
    if get is None:
        raise AttributeError(
            f"registry module {module_name!r} defines no get_contracts()"
        )
    return list(get())


def main(argv=None) -> int:
    from .core import DEFAULT_CONTRACT

    parser = argparse.ArgumentParser(
        prog="python -m ps_pytorch_tpu.check",
        description="jaxpr-level contract checker (rules PSC101-PSC114).",
    )
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--contract", default=None,
                        help=f"accounting artifact (default: "
                             f"./{DEFAULT_CONTRACT} if present)")
    parser.add_argument("--write-contract", action="store_true",
                        help="regenerate the accounting artifact from the "
                             "current registry and exit 0 (PSC101/102/103/"
                             "105/106 still run)")
    parser.add_argument("--registry",
                        default="ps_pytorch_tpu.check.contracts",
                        help="module exposing get_contracts() "
                             "(default: the committed registry)")
    parser.add_argument("--only", default=None,
                        help="comma-separated config names to trace "
                             "(PSC104 stale-entry checking is skipped)")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to enable "
                             "(default: all)")
    parser.add_argument("--list", action="store_true",
                        help="list registry config names and exit")
    args = parser.parse_args(argv)

    if args.write_contract and args.only:
        print(
            "pscheck: --write-contract cannot be combined with --only "
            "(a partial write would drop the other configs' pinned rows)",
            file=sys.stderr,
        )
        return 2

    if args.write_contract and args.select:
        print(
            "pscheck: --write-contract cannot be combined with --select "
            "(a re-baseline must clear every rule, not a subset)",
            file=sys.stderr,
        )
        return 2

    selected = None
    if args.select:
        from .rules import RULE_IDS

        selected = {r.strip().upper() for r in args.select.split(",")
                    if r.strip()}
        unknown = selected - set(RULE_IDS)
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    try:
        specs = _load_registry(args.registry)
    except (ImportError, AttributeError) as e:
        print(f"pscheck: cannot load registry: {e}", file=sys.stderr)
        return 2

    names = [s.name for s in specs]
    if args.list:
        print("\n".join(names))
        return 0

    only = None
    if args.only:
        only = [n.strip() for n in args.only.split(",") if n.strip()]
        unknown = sorted(set(only) - set(names))
        if unknown:
            print(f"pscheck: unknown config(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    from .core import (
        load_contract,
        render_text,
        run_checks,
        trace_registry,
        write_contract,
    )

    results = trace_registry(specs, only=only)

    if args.write_contract:
        findings = run_checks(results, contract=None)
        path = args.contract or DEFAULT_CONTRACT
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        write_contract(path, results)
        print(f"pscheck: wrote {len(results)} config(s) to {path}")
        if findings:
            print(render_text(findings, len(results)))
            print(
                "pscheck: WARNING: the artifact was written but "
                f"{len(findings)} non-PSC104 finding(s) remain — the "
                "contract rules above still fail",
                file=sys.stderr,
            )
            return 1
        return 0

    contract_path = args.contract or (
        DEFAULT_CONTRACT if os.path.exists(DEFAULT_CONTRACT) else None
    )
    contract = None
    if contract_path:
        try:
            contract = load_contract(contract_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"pscheck: cannot read contract {contract_path}: {e}",
                  file=sys.stderr)
            return 2
    findings = run_checks(results, contract, check_stale=only is None)
    if selected is not None:
        findings = [f for f in findings if f.rule in selected]

    if args.format == "json":
        print(json.dumps(
            {
                "version": 1,
                "tool": "pscheck",
                "configs": [r.spec.name for r in results],
                "findings": [f.to_json() for f in findings],
                "collectives": {
                    r.spec.name: r.summary for r in results
                },
            },
            indent=2,
            sort_keys=True,
        ))
    else:
        print(render_text(findings, len(results)))
    return 1 if findings else 0


if __name__ == "__main__":
    _reexec_clean_env()
    sys.exit(main())
