"""Host-side batch iteration with device prefetch.

Replaces the reference's vendored multiprocessing DataLoader
(/root/reference/src/data_loader_ops/my_data_loader.py:254-319 — worker pool,
index/data queues, out-of-order reordering, pin-memory thread). On TPU the
datasets fit in host RAM as numpy arrays, so "loading" is an index gather;
the heavy lifting (augment/normalize) happens on-device (augment.py) and
`prefetch_to_device` keeps one batch in flight, which is the TPU-shaped
equivalent of the reference's pin-memory + worker prefetch machinery.

The reference shards data implicitly: every worker constructs its own
independently-shuffled DataLoader over the FULL dataset (distributed_nn.py:
each rank calls prepare_data; README.md:24 "no data is shipped"). `shard`
reproduces exactly that (seeded per-worker shuffles of the full set) while
`shard="disjoint"` offers the sane improvement (true partition).
"""

from __future__ import annotations

import collections
import ctypes
from typing import Iterator

import jax
import numpy as np


def gather_rows(array: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Batch assembly: array[indices] through the native threaded gather
    core (native/loader.cc — the reference's DataLoader worker pool reduced
    to its actual job, a parallel strided copy), with a numpy fallback.

    Index semantics are identical on both paths: out-of-range (including
    negative — no numpy wrapping) raises IndexError."""
    from ..ops.codec import _load

    idx = np.ascontiguousarray(indices, np.int64)
    if idx.size and (idx.min() < 0 or idx.max() >= len(array)):
        raise IndexError("gather index out of range")
    lib = _load()
    if (
        lib is None
        or getattr(lib, "psl_gather", None) is None
        or array.nbytes == 0
        or not array.flags.c_contiguous
    ):
        return array[idx]
    item_bytes = array.dtype.itemsize * int(np.prod(array.shape[1:], dtype=np.int64))
    out = np.empty((len(idx),) + array.shape[1:], array.dtype)
    ok = lib.psl_gather(
        array.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        array.shape[0],
        item_bytes,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        0,
    )
    if not ok:
        raise IndexError("gather index out of range")
    return out


class BatchIterator:
    """Epoch-shuffled minibatch iterator over in-memory arrays.

    Yields dicts {"image": uint8 [B,H,W,C], "label": int32 [B]} as numpy.
    Drops the last partial batch (static shapes for jit).
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ):
        if len(images) < batch_size:
            # replicate up to one batch so tiny (test) datasets still yield
            reps = -(-batch_size // len(images))
            images = np.concatenate([images] * reps)
            labels = np.concatenate([labels] * reps)
        # contiguous once up front: the native gather needs C layout, and
        # doing it per batch would copy the whole dataset every iteration
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(labels)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.RandomState(seed)
        self._epoch = 0

    def __len__(self) -> int:
        n = len(self.images) // self.batch_size
        if not self.drop_last and len(self.images) % self.batch_size:
            n += 1
        return n

    @property
    def num_samples(self) -> int:
        return len(self.images)

    def epoch(self) -> Iterator[dict]:
        idx = np.arange(len(self.images))
        if self.shuffle:
            self._rng.shuffle(idx)
        self._epoch += 1
        for start in range(0, len(idx), self.batch_size):
            batch_idx = idx[start : start + self.batch_size]
            if len(batch_idx) < self.batch_size and self.drop_last:
                return
            yield {
                "image": gather_rows(self.images, batch_idx),
                "label": gather_rows(self.labels, batch_idx),
            }

    def __iter__(self):
        return self.epoch()

    def forever(self) -> Iterator[dict]:
        while True:
            yield from self.epoch()


def shard_for_worker(
    images: np.ndarray,
    labels: np.ndarray,
    worker_index: int,
    num_workers: int,
    mode: str = "reshuffle",
    seed: int = 0,
):
    """Per-worker data assignment.

    mode="reshuffle": reference parity — every worker sees the full dataset
    under its own shuffle seed (see module docstring).
    mode="disjoint": contiguous 1/num_workers partition (improvement).
    """
    if mode == "reshuffle":
        return images, labels, seed + worker_index * 1009
    if mode == "disjoint":
        n = len(images) // num_workers
        lo = worker_index * n
        return images[lo : lo + n], labels[lo : lo + n], seed
    raise ValueError(f"unknown shard mode {mode!r}")


def prefetch_to_device(
    iterator: Iterator[dict], size: int = 2, device=None, tracer=None
) -> Iterator[dict]:
    """Keep `size` batches ahead on device (reference's pin-memory analogue).

    ``device`` is anything ``jax.device_put`` accepts: None (default
    device — the single-device evaluator path), a concrete ``Device``,
    or a ``jax.sharding.Sharding`` (e.g. ``NamedSharding(mesh,
    P(axis))``) — with a sharding, prefetched batches land on the mesh
    ALREADY split across workers, so the train step consumes them
    directly instead of re-laying-out a replicated batch inside the
    step. A PartitionSpec shorter than a leaf's rank shards the leading
    (batch) dim and replicates the rest, which fits both the [B,H,W,C]
    images and the [B] labels.

    ``tracer`` (obs/trace.py) wraps each device_put dispatch in an
    ``h2d`` span — dispatch walltime, not transfer completion: the
    transfer itself overlaps compute, which is the point of prefetching."""
    queue = collections.deque()
    if tracer is None:
        from ..obs import NULL_TRACER as tracer  # noqa: N811 - constant

    def enqueue(n):
        for _ in range(n):
            batch = next(iterator, None)
            if batch is None:
                return
            with tracer.span("h2d"):
                queue.append(jax.device_put(batch, device))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)
