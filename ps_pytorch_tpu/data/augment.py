"""On-device batch preprocessing: normalize + (pad, random-crop, random-flip).

Replaces the reference's host-side PIL transform pipeline
(/root/reference/src/util.py:37-47: 4px reflect pad -> RandomCrop(32) ->
RandomHorizontalFlip -> normalize) with jit-compiled batched jax ops, so
augmentation rides the accelerator instead of Python workers
(src/data_loader_ops/my_data_loader.py's multiprocessing pool).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def normalize(images: jax.Array, mean: np.ndarray, std: np.ndarray) -> jax.Array:
    """uint8 [N,H,W,C] -> normalized f32 (parity: transforms.Normalize)."""
    x = images.astype(jnp.float32) / 255.0
    return (x - jnp.asarray(mean, jnp.float32)) / jnp.asarray(std, jnp.float32)


@partial(jax.jit, static_argnames=("pad", "pad_mode"))
def random_crop_flip(
    key: jax.Array, images: jax.Array, pad: int = 4, pad_mode: str = "reflect"
) -> jax.Array:
    """Batched 4px-pad + random crop back to original size + random hflip."""
    n, h, w, c = images.shape
    kc, kf = jax.random.split(key)
    padded = jnp.pad(
        images, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode=pad_mode
    )
    offs = jax.random.randint(kc, (n, 2), 0, 2 * pad + 1)

    def crop_one(img, off):
        return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

    cropped = jax.vmap(crop_one)(padded, offs)
    flip = jax.random.bernoulli(kf, 0.5, (n,))
    flipped = jnp.where(flip[:, None, None, None], cropped[:, :, ::-1, :], cropped)
    return flipped


def preprocess_batch(
    key: jax.Array,
    images: jax.Array,
    mean: np.ndarray,
    std: np.ndarray,
    augment: bool,
    pad_mode: str = "reflect",
) -> jax.Array:
    """Full train/eval preprocessing. `augment=False` = test-path transform."""
    if augment:
        images = random_crop_flip(key, images, pad_mode=pad_mode)
    return normalize(images, mean, std)


def make_preprocessor(dataset_name: str, train: bool):
    """Returns fn(key, uint8_images) -> f32 images for the named dataset,
    with the reference's per-dataset augmentation policy baked in."""
    from .datasets import AUGMENT, NORM_STATS, PAD_MODE

    mean, std = NORM_STATS[dataset_name]
    augment = train and AUGMENT[dataset_name]
    pad_mode = PAD_MODE.get(dataset_name, "reflect")

    def fn(key: jax.Array, images: jax.Array) -> jax.Array:
        return preprocess_batch(key, images, mean, std, augment, pad_mode)

    return fn
