"""Data layer: datasets (reference src/util.py:21-106), on-device augmentation
(replacing the PIL pipeline), and host iteration with device prefetch
(replacing src/data_loader_ops/my_data_loader.py)."""

from .augment import make_preprocessor, normalize, preprocess_batch, random_crop_flip
from .datasets import (
    AUGMENT,
    DATASET_NAMES,
    IMAGE_SHAPES,
    NORM_STATS,
    NUM_CLASSES,
    Dataset,
    make_synthetic,
    prepare_data,
)
from .loader import BatchIterator, prefetch_to_device, shard_for_worker
