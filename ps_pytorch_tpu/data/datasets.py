"""Datasets: MNIST / CIFAR-10 / CIFAR-100 / SVHN with the reference's exact
normalization statistics (/root/reference/src/util.py:21-106), plus a
deterministic synthetic fallback for machines with no downloaded data
(this build environment has zero egress).

Data is held as plain numpy arrays (images uint8 HWC, labels int32); all
per-batch work (normalize, augment) happens on-device in jax — replacing the
reference's PIL/torchvision transform pipeline and its forked multiprocessing
DataLoader (src/data_loader_ops/my_data_loader.py) with device compute, which
is the TPU-native shape of the same capability.

On-disk format support (checked under `root` / $PS_TPU_DATA_DIR):
- MNIST: idx files (train-images-idx3-ubyte etc., optionally .gz)
- CIFAR-10/100: the python pickle batches (cifar-10-batches-py/, cifar-100-python/)
- SVHN: train_32x32.mat / test_32x32.mat (scipy.io)
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

# Normalization constants — identical values to util.py:24-105.
NORM_STATS = {
    "MNIST": (np.array([0.1307]), np.array([0.3081])),
    "Cifar10": (
        np.array([125.3, 123.0, 113.9]) / 255.0,
        np.array([63.0, 62.1, 66.7]) / 255.0,
    ),
    "Cifar100": (
        np.array([125.3, 123.0, 113.9]) / 255.0,
        np.array([63.0, 62.1, 66.7]) / 255.0,
    ),
    "SVHN": (
        np.array([0.4914, 0.4822, 0.4465]),
        np.array([0.2023, 0.1994, 0.2010]),
    ),
}

NUM_CLASSES = {"MNIST": 10, "Cifar10": 10, "Cifar100": 100, "SVHN": 10}
IMAGE_SHAPES = {
    "MNIST": (28, 28, 1),
    "Cifar10": (32, 32, 3),
    "Cifar100": (32, 32, 3),
    "SVHN": (32, 32, 3),
}
DATASET_NAMES = tuple(NUM_CLASSES)

# Reference augmentation policy per dataset (util.py:37-47, 91-95):
# 4-pixel pad (reflect for CIFAR, zero for SVHN) + random 32x32 crop + hflip.
# MNIST gets no augmentation (util.py:25-28). SVHN's reference pipeline
# includes RandomHorizontalFlip (util.py:92) which we reproduce even though
# flipping digits is dubious — parity over taste; disable via augment=False.
AUGMENT = {"MNIST": False, "Cifar10": True, "Cifar100": True, "SVHN": True}
PAD_MODE = {"Cifar10": "reflect", "Cifar100": "reflect", "SVHN": "constant"}


@dataclass
class Dataset:
    """In-memory split pair. images are uint8 [N,H,W,C]; labels int32 [N]."""

    name: str
    train_images: np.ndarray
    train_labels: np.ndarray
    test_images: np.ndarray
    test_labels: np.ndarray
    synthetic: bool = False

    @property
    def num_classes(self) -> int:
        return NUM_CLASSES[self.name]

    @property
    def norm_stats(self) -> Tuple[np.ndarray, np.ndarray]:
        return NORM_STATS[self.name]


def _data_root(root: Optional[str]) -> str:
    return root or os.environ.get("PS_TPU_DATA_DIR", "./data")


# ---------------------------------------------------------------- raw readers


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def _find(root: str, names) -> Optional[str]:
    for dirpath, _, files in os.walk(root):
        for n in names:
            if n in files:
                return os.path.join(dirpath, n)
    return None


def _load_mnist(root: str) -> Optional[Tuple[np.ndarray, ...]]:
    parts = []
    for stem in (
        "train-images-idx3-ubyte",
        "train-labels-idx1-ubyte",
        "t10k-images-idx3-ubyte",
        "t10k-labels-idx1-ubyte",
    ):
        p = _find(root, (stem, stem + ".gz"))
        if p is None:
            return None
        parts.append(_read_idx(p))
    tr_x, tr_y, te_x, te_y = parts
    return (
        tr_x[..., None],
        tr_y.astype(np.int32),
        te_x[..., None],
        te_y.astype(np.int32),
    )


def _load_cifar(root: str, fine: bool) -> Optional[Tuple[np.ndarray, ...]]:
    def unpickle(p):
        with open(p, "rb") as f:
            return pickle.load(f, encoding="bytes")

    if not fine:
        first = _find(root, ("data_batch_1",))
        if first is None:
            return None
        d = os.path.dirname(first)
        batches = [unpickle(os.path.join(d, f"data_batch_{i}")) for i in range(1, 6)]
        test = unpickle(os.path.join(d, "test_batch"))
        tr_x = np.concatenate([b[b"data"] for b in batches])
        tr_y = np.concatenate([b[b"labels"] for b in batches])
        te_x, te_y = test[b"data"], np.asarray(test[b"labels"])
    else:
        trainp = _find(root, ("train",))
        if trainp is None or "cifar-100" not in trainp:
            return None
        d = os.path.dirname(trainp)
        tr = unpickle(os.path.join(d, "train"))
        te = unpickle(os.path.join(d, "test"))
        tr_x, tr_y = tr[b"data"], np.asarray(tr[b"fine_labels"])
        te_x, te_y = te[b"data"], np.asarray(te[b"fine_labels"])
    to_hwc = lambda a: a.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return (
        to_hwc(tr_x),
        np.asarray(tr_y, np.int32),
        to_hwc(te_x),
        np.asarray(te_y, np.int32),
    )


def _load_svhn(root: str) -> Optional[Tuple[np.ndarray, ...]]:
    import scipy.io

    trp = _find(root, ("train_32x32.mat",))
    tep = _find(root, ("test_32x32.mat",))
    if trp is None or tep is None:
        return None

    def load(p):
        m = scipy.io.loadmat(p)
        x = m["X"].transpose(3, 0, 1, 2)  # HWCN -> NHWC
        y = m["y"].astype(np.int32).reshape(-1)
        y[y == 10] = 0
        return x, y

    tr_x, tr_y = load(trp)
    te_x, te_y = load(tep)
    return tr_x, tr_y, te_x, te_y


# ------------------------------------------------------------------ synthetic


def make_synthetic(
    name: str, train_size: int = 4096, test_size: int = 1024, seed: int = 0
) -> Dataset:
    """Deterministic class-structured fake data: each class has a fixed random
    template; samples are template + noise, so models can actually learn —
    making convergence smoke tests meaningful without any downloads."""
    h, w, c = IMAGE_SHAPES[name]
    k = NUM_CLASSES[name]
    rng = np.random.RandomState(seed)
    templates = rng.randint(0, 256, size=(k, h, w, c))

    def split(n, seed_):
        r = np.random.RandomState(seed_)
        y = r.randint(0, k, size=n)
        noise = r.normal(0, 32, size=(n, h, w, c))
        x = np.clip(templates[y] + noise, 0, 255).astype(np.uint8)
        return x, y.astype(np.int32)

    tr_x, tr_y = split(train_size, seed + 1)
    te_x, te_y = split(test_size, seed + 2)
    return Dataset(name, tr_x, tr_y, te_x, te_y, synthetic=True)


# -------------------------------------------------------------------- factory


def prepare_data(
    name: str,
    root: Optional[str] = None,
    allow_synthetic: bool = True,
    synthetic_train_size: int = 4096,
) -> Dataset:
    """Load a dataset by reference CLI name (`--dataset`, util.py:21-106),
    falling back to synthetic data when no files are on disk."""
    if name not in NUM_CLASSES:
        raise ValueError(f"unknown dataset {name!r}; choose from {DATASET_NAMES}")
    root_dir = _data_root(root)
    loaded = None
    if os.path.isdir(root_dir):
        if name == "MNIST":
            loaded = _load_mnist(root_dir)
        elif name == "Cifar10":
            loaded = _load_cifar(root_dir, fine=False)
        elif name == "Cifar100":
            loaded = _load_cifar(root_dir, fine=True)
        elif name == "SVHN":
            loaded = _load_svhn(root_dir)
    if loaded is not None:
        return Dataset(name, *loaded)
    if not allow_synthetic:
        raise FileNotFoundError(
            f"no {name} data under {root_dir!r} and allow_synthetic=False"
        )
    return make_synthetic(name, train_size=synthetic_train_size)
