"""Low-overhead host-side span tracer for the train and serve ticks.

Design constraints (the whole point — observability must not perturb
the observed):

- ZERO host syncs by construction: a span reads ``time.perf_counter()``
  twice and appends a dict to a bounded ring. This module never imports
  device-touching APIs — no ``jax.device_get``, no ``block_until_ready``
  — and pslint's PSL004 patrols the whole ``obs/`` tree in strict mode
  (every function is a hot-path loop body by contract, and
  ``block_until_ready`` is flagged here even though it is the blessed
  barrier primitive elsewhere), so a future edit cannot sneak one in.
- Tracer OFF is a shared no-op: ``NULL_TRACER.span(...)`` returns one
  reusable null context manager; instrumented call sites stay
  unconditional and pay ~a method call per phase per step.
- Spans buffer in an in-memory ring (``deque(maxlen=ring)``) and flush
  to the per-process trace file only at the call sites that already
  sync (the trainer's log window, every Nth serve tick) — tracing adds
  file I/O where the host was already stalling on the device, never a
  new stall.

Each trace file is a JSONL stream: one ``run_header`` record (run id,
schema version, wall+monotonic clock base — obs/schema.py), then one
``span`` record per completed span with ``t``/``dur`` in seconds on the
header's monotonic clock. ``tools/trace_report.py`` merges any number
of per-process files into one perfetto-loadable Chrome trace via the
header wall clocks and summarizes p50/p99 per phase.

When ``annotate=True`` each span also enters a
``jax.profiler.TraceAnnotation`` scope of the same name, so the host
phases appear as named regions on the profiler timeline captured by
``--profile-dir`` (obs/profiler.py). TraceAnnotation is a TraceMe that
no-ops when no profiler session is active — safe to leave on.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Dict, List, Optional

from .schema import new_run_id, run_header, validate_event


class _NullSpan:
    """Reusable no-op context manager (the tracer-off fast path)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Tracer-off: every operation is inert; one shared instance
    (NULL_TRACER) keeps instrumented call sites unconditional."""

    enabled = False
    run_id = None

    def span(self, name, cat="phase", **attrs):
        return _NULL_SPAN

    def add(self, name, t0, dur, cat="phase", **attrs):
        return None

    def instant(self, name, cat="instant", **attrs):
        return None

    def now(self) -> float:
        return 0.0

    def drain(self) -> List[dict]:
        return []

    def flush(self) -> int:
        return 0


NULL_TRACER = NullTracer()


class _Span:
    __slots__ = ("_tracer", "_name", "_cat", "_attrs", "_t0", "_depth",
                 "_ann")

    def __init__(self, tracer: "Tracer", name: str, cat: str, attrs: dict):
        self._tracer, self._name, self._cat = tracer, name, cat
        self._attrs = attrs
        self._ann = None

    def __enter__(self):
        tr = self._tracer
        self._depth = len(tr._stack)
        tr._stack.append(self._name)
        if tr._ann_cls is not None:
            self._ann = tr._ann_cls(self._name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        end = time.perf_counter()
        tr = self._tracer
        if self._ann is not None:
            self._ann.__exit__(*exc)
        tr._stack.pop()
        tr._append(
            self._name, self._t0 - tr._base, end - self._t0, self._cat,
            self._depth, self._attrs,
        )
        return False


class Tracer:
    """One component's span stream (train loop, serve loop, bench leg).

    ``path=None`` keeps spans in memory only (``drain()`` them — the
    bench legs do); with a path, ``flush()`` appends the drained spans
    as JSONL after writing the run_header once."""

    enabled = True

    def __init__(
        self,
        component: str,
        path: Optional[str] = None,
        run_id: Optional[str] = None,
        ring: int = 65536,
        annotate: bool = False,
        geometry: Optional[dict] = None,
        pid: int = 0,
    ):
        self.component = component
        self.path = path
        self.run_id = run_id or new_run_id()
        self.pid = int(pid)
        self.header = run_header(
            component, run_id=self.run_id, geometry=geometry, pid=pid
        )
        # span t/dur are seconds on THIS clock base (the header's t_mono)
        self._base = self.header["t_mono"]
        self._buf: collections.deque = collections.deque(maxlen=max(ring, 1))
        self._stack: List[str] = []
        self.dropped = 0  # ring overflow count (oldest spans evicted)
        self._dropped_reported = 0  # watermark already flushed as a marker
        self._header_written = False
        self._ann_cls = None
        if annotate:
            try:
                from jax.profiler import TraceAnnotation

                self._ann_cls = TraceAnnotation
            except Exception:  # profiler unavailable: spans still record
                self._ann_cls = None

    # ------------------------------------------------------------ recording
    def span(self, name: str, cat: str = "phase", **attrs):
        """Context manager timing one phase; nesting depth is recorded
        from the live span stack."""
        return _Span(self, name, cat, attrs)

    def now(self) -> float:
        """Seconds on this tracer's clock (for explicit add() spans)."""
        return time.perf_counter() - self._base

    def add(self, name: str, t0: float, dur: float, cat: str = "phase",
            **attrs) -> None:
        """Record an already-measured span (``t0`` from ``now()``) — for
        intervals that start and end in different calls, e.g. a serve
        rollover drain (staged in one tick, swapped several ticks later)
        or a request lifecycle. Marked ``async``: these intervals
        overlap the synchronous span stack without nesting in it, so
        the nesting validator skips them and the Chrome export gives
        them their own thread lane."""
        attrs = dict(attrs)
        attrs["async"] = True
        self._append(name, t0, dur, cat, len(self._stack), attrs)

    def instant(self, name: str, cat: str = "instant", **attrs) -> None:
        self._append(name, self.now(), 0.0, cat, len(self._stack), attrs)

    def _append(self, name, t, dur, cat, depth, attrs) -> None:
        if len(self._buf) == self._buf.maxlen:
            self.dropped += 1  # deque evicts the OLDEST span silently
        rec = {
            "kind": "span",
            "name": name,
            "cat": cat,
            "t": round(t, 6),
            "dur": round(max(dur, 0.0), 6),
            "depth": depth,
        }
        if attrs:
            rec.update(attrs)
        self._buf.append(rec)

    # -------------------------------------------------------------- output
    def drain(self) -> List[dict]:
        """Remove and return every buffered span record."""
        out = list(self._buf)
        self._buf.clear()
        return out

    def flush(self) -> int:
        """Append drained spans (validated) to the trace file; writes the
        run_header first on the first flush. Call from sites that already
        sync (log windows), never per step. Returns spans written.

        A pathless (in-memory) tracer is a no-op here — the ring keeps
        its spans for a later ``drain()``: the serve engine flushes
        periodically by contract, and the bench leg's memory tracer must
        not lose its measurement to those flushes."""
        if self.path is None:
            return 0
        spans = self.drain()
        if self.dropped > self._dropped_reported:
            # surface ring truncation IN the stream: trace_report's
            # per-phase summary then shows a spans_dropped marker
            # instead of a silently incomplete timeline
            spans.append({
                "kind": "span", "name": "spans_dropped", "cat": "meta",
                "t": round(self.now(), 6), "dur": 0.0, "depth": 0,
                "async": True, "dropped_total": self.dropped,
            })
            self._dropped_reported = self.dropped
        if not self._header_written and not spans:
            return 0
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            if not self._header_written:
                f.write(json.dumps(validate_event(dict(self.header))) + "\n")
                self._header_written = True
            for rec in spans:
                f.write(json.dumps(validate_event(rec)) + "\n")
        return len(spans)


# ------------------------------------------------------------------ reports

def summarize_spans(spans: List[dict]) -> Dict[str, dict]:
    """Per-phase duration stats from span records: count, total, p50/p99
    seconds. Shared by the bench legs (in-memory drain) and
    tools/trace_report.py (merged files)."""
    by_name: Dict[str, List[float]] = {}
    for s in spans:
        if s.get("kind") == "span":
            by_name.setdefault(s["name"], []).append(float(s["dur"]))
    out: Dict[str, dict] = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "total_s": round(sum(durs), 6),
            "p50_s": round(_pct_sorted(durs, 50.0), 6),
            "p99_s": round(_pct_sorted(durs, 99.0), 6),
        }
    return out


def _pct_sorted(xs: List[float], q: float) -> float:
    """Nearest-rank-with-interpolation percentile of a SORTED list
    (numpy-free: obs stays importable without the array stack)."""
    if not xs:
        return 0.0
    if len(xs) == 1:
        return xs[0]
    pos = (q / 100.0) * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] * (1.0 - frac) + xs[hi] * frac


def chrome_trace_events(
    header: dict, spans: List[dict], pid: Optional[int] = None,
    t0_wall: float = 0.0,
) -> List[dict]:
    """Convert one stream (header + span records) to Chrome trace_event
    dicts. ``ts`` is microseconds of (header wall base + span monotonic
    offset − ``t0_wall``) — the multihost merge rule: every process's
    spans land on one wall-clock timeline, durations stay monotonic-
    clock-accurate."""
    p = int(header.get("pid", 0)) if pid is None else pid
    base = float(header.get("t_wall", 0.0)) - t0_wall
    out: List[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": p,
            "tid": 0,
            "args": {
                "name": f"{header.get('component', '?')} "
                        f"p{header.get('pid', 0)} "
                        f"[{header.get('run_id', '?')}]"
            },
        }
    ]
    for s in spans:
        if s.get("kind") != "span":
            continue
        # async intervals (request lifecycles, rollover drains) overlap
        # the synchronous stack arbitrarily; per-slot thread lanes keep
        # each track properly nested (one slot serves one request at a
        # time, so a slot's lane never self-overlaps)
        tid = 0
        if s.get("async"):
            tid = 10 + int(s.get("slot", -1)) + 1
        ev = {
            "name": s["name"],
            "cat": s.get("cat", "phase"),
            "ph": "X",
            "ts": round((base + float(s["t"])) * 1e6, 3),
            "dur": round(float(s["dur"]) * 1e6, 3),
            "pid": p,
            "tid": tid,
        }
        args = {
            k: v for k, v in s.items()
            if k not in ("kind", "name", "cat", "t", "dur")
        }
        if args:
            ev["args"] = args
        out.append(ev)
    return out
