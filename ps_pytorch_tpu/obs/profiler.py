"""Bounded jax.profiler capture windows for the train/serve drivers.

``--profile-dir`` captures a device+host profiler trace for the step
window ``[start, start + n)`` — on TPU an op-level device timeline, on
CPU host events (both render in xprof/tensorboard, and the host spans
from obs/trace.py appear as named TraceAnnotation regions when the
driver's tracer runs with ``annotate=True``).

The ONE deliberate host sync lives here: stopping a trace must wait for
the in-flight window to retire or the file ends mid-step. It runs
exactly once per capture (never per step) and carries the ``psl:
sync-ok`` pragma — pslint's strict PSL004 sweep over ``obs/`` flags any
other sync in this tree.
"""

from __future__ import annotations

from typing import Optional

from ..utils import get_logger

logger = get_logger()


class ProfileWindow:
    """Start/stop ``jax.profiler`` around steps ``[start, start+n)``.

    Drive it with ``before_step(step, sync=...)`` immediately before
    dispatching ``step``; ``close(sync)`` (idempotent) from a finally
    block so a run that ends or raises inside the window still writes a
    valid trace. ``sync`` is any pytree to block on before stopping —
    the trainer passes its params so the captured window contains
    retired device work, not just dispatch."""

    def __init__(self, profile_dir: Optional[str], start_step: int,
                 num_steps: int = 10):
        # validate only when profiling is actually requested: the trainer
        # constructs this unconditionally, and a stray --profile-steps 0
        # without --profile-dir must not abort the run it doesn't affect
        if profile_dir is not None and num_steps < 1:
            raise ValueError(f"profile window needs >= 1 step, got {num_steps}")
        self.dir = profile_dir
        self.start = int(start_step)
        self.stop = int(start_step) + int(num_steps)
        self.active = False

    def before_step(self, step: int, sync=None) -> None:
        if self.dir is None:
            return
        if not self.active and self.start <= step < self.stop:
            import jax

            jax.profiler.start_trace(self.dir)
            self.active = True
            logger.info(
                "profiler capture started: steps [%d, %d) -> %s",
                self.start, self.stop, self.dir,
            )
        elif self.active and step >= self.stop:
            self._finish(sync)

    def close(self, sync=None) -> None:
        """Stop an open capture (run ended or raised inside the window)."""
        if self.active:
            self._finish(sync)

    def _finish(self, sync) -> None:
        import jax

        if sync is not None:
            # once per CAPTURE, not per step: the trace must contain the
            # window's retired device work, so this barrier is the point
            jax.block_until_ready(sync)  # psl: sync-ok
        jax.profiler.stop_trace()
        self.active = False
        logger.info("profiler trace written to %s", self.dir)
