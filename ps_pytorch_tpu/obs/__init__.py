"""Observability: structured tracing, event schema, profiler windows.

Three layers (ARCHITECTURE §7g):

- ``obs.schema`` — the unified JSONL event registry (kind -> required
  fields + int contract), ``run_header`` records, run ids;
- ``obs.trace`` — the host-side span tracer (ring-buffered, flushed at
  existing sync points, Chrome-trace exportable) and NULL_TRACER, the
  zero-cost off switch;
- ``obs.profiler`` — bounded ``jax.profiler`` capture windows for
  ``--profile-dir``.

Contract: tracer-off adds zero host syncs, tracer-on reuses the
driver's existing per-window sync points — pslint PSL004 patrols this
tree in strict mode (tests/test_obs.py pins it).
"""

from .profiler import ProfileWindow
from .schema import (
    EVENT_KINDS,
    SCHEMA_VERSION,
    new_run_id,
    run_header,
    validate_event,
)
from .trace import (
    NULL_TRACER,
    NullTracer,
    Tracer,
    chrome_trace_events,
    summarize_spans,
)

__all__ = [
    "EVENT_KINDS",
    "NULL_TRACER",
    "NullTracer",
    "ProfileWindow",
    "SCHEMA_VERSION",
    "Tracer",
    "chrome_trace_events",
    "new_run_id",
    "run_header",
    "summarize_spans",
    "validate_event",
]
