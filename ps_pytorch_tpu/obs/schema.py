"""The unified event schema for every metrics/trace JSONL stream.

Before this module, the metrics JSONL was a bag of ad-hoc record shapes:
each subsystem invented its own ``kind`` and field names as it grew
(``step`` everywhere, but typed float in one emitter and int in another;
counters serialized as floats by the trainer's blanket ``float(v)``
sweep). The registry below is the single source of truth: every kind the
framework emits, with its required fields and the fields that are
integers BY CONTRACT — ``validate_event`` rejects unknown kinds and
missing fields, and coerces the declared int fields so a record means
the same thing no matter which emitter produced it.

Compatibility note: JSONL files written before the registry existed may
carry float-typed counters (``skipped_steps: 3.0``) and no ``t_wall``
stamp. Readers should ``int()`` counters defensively on old files; new
files are normalized at the write choke points (``trainer.
append_metrics_line`` and ``obs.trace.Tracer.flush``).

A stream begins with one ``run_header`` record carrying the run's
identity and clock base:

- ``run_id``: random id shared by every stream of one run (metrics
  JSONL, per-process trace files), so a multihost merge can group them;
- ``schema_version``: this module's ``SCHEMA_VERSION``;
- ``t_wall`` / ``t_mono``: ``time.time()`` and ``time.perf_counter()``
  read together at header time. Span records carry monotonic offsets
  (drift-free durations); the header's wall clock maps them onto one
  cross-process timeline (tools/trace_report.py's merge rule —
  multihost wall clocks are NTP-aligned to well under a log window).

This module is deliberately host-pure: no jax import, no device access —
it can never add a sync to the paths it observes.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Dict, Optional, Tuple

SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class EventSpec:
    """One registered event kind: required fields plus the fields that
    are integers by contract (coerced, not just checked — the trainer's
    metrics sweep floats every device scalar it fetches)."""

    required: Tuple[str, ...]
    int_fields: Tuple[str, ...] = ()
    doc: str = ""


# kind -> spec. Extra fields are always allowed (records are open:
# workload-specific metrics ride along), but a registered kind's
# required core is guaranteed present and its counters int-typed.
EVENT_KINDS: Dict[str, EventSpec] = {
    "run_header": EventSpec(
        required=("run_id", "schema_version", "component", "t_mono"),
        int_fields=("schema_version", "pid"),
        doc="stream identity + clock base; first record of every stream",
    ),
    "train": EventSpec(
        required=("step", "loss", "time_cost"),
        int_fields=("step", "epoch", "skipped_steps", "skip_streak"),
        doc="one per log window: window-averaged step walltime + metrics",
    ),
    "eval": EventSpec(
        required=("step", "loss"),
        int_fields=("step",),
        doc="full-test-split validation pass",
    ),
    "train_lm": EventSpec(
        required=("step", "loss", "time_cost"),
        int_fields=("step",),
        doc="LM trainer log window (cli/train_lm.py)",
    ),
    "grad_skip": EventSpec(
        required=("step", "skipped_steps", "skip_streak"),
        int_fields=("step", "skipped_steps", "skip_streak"),
        doc="non-finite guard skipped >=1 step since the last window",
    ),
    "straggler": EventSpec(
        required=("step", "time_cost", "threshold"),
        int_fields=("step",),
        doc="one slow step (watchdog armed, below storm escalation)",
    ),
    "straggler_storm": EventSpec(
        required=("step", "start_step", "consecutive", "threshold"),
        int_fields=("step", "start_step", "consecutive"),
        doc="N consecutive slow steps escalated into one condition",
    ),
    "straggler_storm_end": EventSpec(
        required=("step", "start_step", "consecutive"),
        int_fields=("step", "start_step", "consecutive"),
        doc="storm closed; carries the true span length",
    ),
    "mask_adapt": EventSpec(
        required=("step", "from", "to", "window_start", "slow_steps",
                  "window_steps"),
        int_fields=("step", "from", "to", "window_start", "slow_steps",
                    "window_steps"),
        doc="adaptive partial-aggregation count change at a window close",
    ),
    "precision_adapt": EventSpec(
        required=("step", "window_start", "changed", "n_skip", "n_4bit",
                  "n_int8", "n_hi", "effective_bytes", "budget_bytes"),
        int_fields=("step", "window_start", "changed", "n_skip", "n_4bit",
                    "n_int8", "n_hi", "effective_bytes", "budget_bytes"),
        doc="adaptive per-bucket precision retag at a window close: the "
            "tag histogram plus the effective wire bytes it prices "
            "(budget_bytes 0 = no --wire-budget-bytes cap)",
    ),
    "resume_reshape": EventSpec(
        required=("step", "from", "to"),
        int_fields=("step",),
        doc="elastic resume re-carved the checkpoint onto a new geometry",
    ),
    "ckpt_quarantined": EventSpec(
        required=("step", "path"),
        int_fields=("step",),
        doc="corrupt checkpoint renamed *.corrupt during resume fallback",
    ),
    "ckpt_write_failed": EventSpec(
        required=("step", "path", "error"),
        int_fields=("step",),
        doc="checkpoint write failed (reported at failure time)",
    ),
    "autotune": EventSpec(
        required=("run", "model", "network", "grid", "n_candidates",
                  "n_pruned", "gate"),
        int_fields=("n_points", "n_candidates", "n_pruned"),
        doc="one ranked knob-search evidence record (tune/search.py); "
            "carries its own nested run_header under 'run'",
    ),
    "span": EventSpec(
        required=("name", "t", "dur"),
        int_fields=("depth", "step", "tick", "slot", "rid",
                    "new_tokens", "weights_step", "from_step", "to_step"),
        doc="one traced host-side phase: t/dur are seconds on the "
            "stream header's monotonic clock",
    ),
    # ---- serving request lifecycle (ARCHITECTURE §7i): every submitted
    # request terminates in EXACTLY one of request_done | request_shed |
    # deadline_expired — the zero-silent-drops contract the chaos drill
    # asserts by partitioning rids over these three kinds
    "request_done": EventSpec(
        required=("rid", "new_tokens", "weights_step"),
        int_fields=("rid", "new_tokens", "weights_step"),
        doc="one request completed (its new-token budget reached); "
            "met_deadline rides along when the request carried one",
    ),
    "request_shed": EventSpec(
        required=("rid", "projected_wait_s", "queue_depth", "slo_budget_s"),
        int_fields=("rid", "queue_depth"),
        doc="admission controller refused the arrival at submit time: "
            "projected queue wait exceeded the SLO budget (the evidence "
            "rides in the record)",
    ),
    "deadline_expired": EventSpec(
        required=("rid", "where", "deadline_s"),
        int_fields=("rid", "tokens_done"),
        doc="request deadline passed before completion; 'where' is "
            "submit (dead on arrival) | queue (expired before "
            "admission) | decode (evicted mid-decode, partial tokens)",
    ),
    "rollover_abort": EventSpec(
        required=("from_step", "staged_step", "reason"),
        int_fields=("from_step", "staged_step"),
        doc="a staged rollover was abandoned (corrupt/unreadable staged "
            "checkpoint at swap time, or the drain watchdog expired); "
            "service continues on from_step",
    ),
    "admission_adapt": EventSpec(
        required=("state", "projected_wait_s", "queue_depth",
                  "window_submits", "window_sheds"),
        int_fields=("queue_depth", "window_submits", "window_sheds",
                    "windows"),
        doc="admission controller state change (admitting <-> shedding) "
            "with the window evidence that drove it",
    ),
}


def new_run_id() -> str:
    """Random 12-hex run id — shared across one run's streams."""
    return uuid.uuid4().hex[:12]


def validate_event(record: dict) -> dict:
    """Validate (and normalize, in place) one JSONL record against the
    registry. Raises ValueError on a missing/unknown ``kind`` or a
    missing required field; coerces the kind's declared int fields.
    Returns the record for call-site chaining."""
    kind = record.get("kind")
    if kind is None:
        raise ValueError(f"event record has no 'kind': {record!r}")
    spec = EVENT_KINDS.get(kind)
    if spec is None:
        raise ValueError(
            f"unknown event kind {kind!r} — register it in "
            f"obs/schema.EVENT_KINDS (known: {sorted(EVENT_KINDS)})"
        )
    missing = [f for f in spec.required if f not in record]
    if missing:
        raise ValueError(
            f"event kind {kind!r} is missing required field(s) "
            f"{missing}: {record!r}"
        )
    for f in spec.int_fields:
        v = record.get(f)
        if v is not None and not isinstance(v, bool) and f in record:
            record[f] = int(v)
    return record


def run_header(
    component: str,
    run_id: Optional[str] = None,
    geometry: Optional[dict] = None,
    pid: int = 0,
) -> dict:
    """Build the stream-opening run_header record (clock base read NOW:
    t_wall and t_mono are one paired sample)."""
    rec = {
        "kind": "run_header",
        "run_id": run_id or new_run_id(),
        "schema_version": SCHEMA_VERSION,
        "component": component,
        "t_wall": round(time.time(), 6),
        "t_mono": round(time.perf_counter(), 6),
        "pid": int(pid),
    }
    if geometry is not None:
        rec["geometry"] = geometry
    return rec
