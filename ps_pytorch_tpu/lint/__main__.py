"""CLI: ``python -m ps_pytorch_tpu.lint [paths] [options]``.

Exit codes: 0 = clean (every finding baselined or none), 1 = new
findings, 2 = usage error. ``--write-baseline`` rewrites the baseline
from the current findings (pruning stale entries) and exits 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .axes import discover_axes
from .core import (
    apply_baseline,
    lint_paths,
    load_baseline,
    render_text,
    scanned_files,
    to_baseline_json,
)
from .rules import RULE_IDS

DEFAULT_BASELINE = "lint_baseline.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ps_pytorch_tpu.lint",
        description="JAX/TPU-aware static analysis (rules PSL001-PSL008).",
    )
    parser.add_argument("paths", nargs="*", default=["ps_pytorch_tpu"],
                        help="files or directories to lint "
                             "(default: ps_pytorch_tpu)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: ./{DEFAULT_BASELINE} "
                             "if present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring any baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings to the baseline file "
                             "and exit 0")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to enable "
                             "(default: all)")
    parser.add_argument("--verbose", action="store_true",
                        help="also list stale baseline entries")
    args = parser.parse_args(argv)

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"pslint: no such file or directory: {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2
    not_python = [
        p for p in args.paths
        if os.path.isfile(p) and not p.endswith(".py")
    ]
    if not_python:
        print(
            "pslint: not a python file (a clean exit would mean nothing "
            f"was linted): {', '.join(not_python)}",
            file=sys.stderr,
        )
        return 2
    if args.write_baseline and args.select:
        print(
            "pslint: --write-baseline cannot be combined with --select "
            "(the baseline must cover every rule)",
            file=sys.stderr,
        )
        return 2

    findings = lint_paths(args.paths)
    if args.select:
        selected = {r.strip().upper() for r in args.select.split(",") if r.strip()}
        unknown = selected - set(RULE_IDS) - {"PSL000"}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        findings = [f for f in findings if f.rule in selected]

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None
    )

    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        with open(path, "w", encoding="utf-8") as f:
            json.dump(to_baseline_json(findings), f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"pslint: wrote {len(findings)} finding(s) to {path}")
        return 0

    baseline = []
    if baseline_path and not args.no_baseline:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"pslint: cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    if args.select:
        # out-of-scope baseline entries are neither matchable nor stale
        # under a rule filter — keep them out of the comparison entirely
        baseline = [b for b in baseline if b.rule in selected]
    # staleness is scoped to the files this run actually scanned: an
    # entry for an unscanned path is not "stale", it is out of scope
    new, matched, stale = apply_baseline(
        findings, baseline, scanned_paths=scanned_files(args.paths)
    )

    if args.format == "json":
        axes, axes_src = discover_axes(args.paths)
        print(json.dumps(
            {
                "version": 1,
                "tool": "pslint",
                "axes": axes,
                "axes_source": axes_src,
                "findings": [f.to_json() for f in findings],
                "new": [f.to_json() for f in new],
                "baselined": len(matched),
                "stale": [f.to_json() for f in stale],
            },
            indent=2,
            sort_keys=True,
        ))
    else:
        print(render_text(new, matched, stale, verbose=args.verbose))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
