"""pslint engine: file walking, pragma suppression, baseline handling.

Baseline findings are keyed on (rule, path, stripped-source-line) rather
than line numbers, so unrelated edits above a legacy finding don't
invalidate the baseline; duplicates are matched as a multiset (two
identical offending lines need two baseline entries).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .axes import discover_axes

PRAGMA_RE = re.compile(r"#\s*psl:\s*(?P<body>[^#]*)")
# tolerate a space before the bracket: without it, "ignore [PSL002]"
# would word-split to a bare "ignore" and silently blanket-suppress
_IGNORE_RULES_RE = re.compile(r"ignore\s*\[([A-Z0-9, ]+)\]")

# pragma aliases: directive -> rule ids it suppresses (None = all rules)
_PRAGMA_ALIASES = {
    "ignore": None,
    "sync-ok": ("PSL004",),
    "donate-ok": ("PSL005",),
    "diverge-ok": ("PSL006", "PSL007", "PSL008"),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # POSIX-style path as given on the command line
    line: int
    col: int
    message: str
    text: str  # stripped source line, the stable part of the identity

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.text)

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "text": self.text,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Finding":
        return cls(
            rule=d["rule"],
            path=d["path"],
            line=int(d.get("line", 0)),
            col=int(d.get("col", 0)),
            message=d.get("message", ""),
            text=d.get("text", ""),
        )


def _pragmas_for(src: str) -> Dict[int, Optional[Set[str]]]:
    """line number -> set of suppressed rule ids (None = all).

    Parsed from COMMENT tokens so a ``# psl:`` inside a string literal is
    never treated as a pragma.
    """
    out: Dict[int, Optional[Set[str]]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [
            (t.start[0], t.string) for t in tokens if t.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        # fall back to a line scan on files tokenize rejects
        comments = [
            (i + 1, line[line.index("#"):])
            for i, line in enumerate(src.splitlines())
            if "#" in line
        ]
    for lineno, comment in comments:
        m = PRAGMA_RE.search(comment)
        if not m:
            continue
        body = m.group("body").strip()
        rules: Optional[Set[str]] = set()
        for bracket in _IGNORE_RULES_RE.finditer(body):
            rules.update(r.strip() for r in bracket.group(1).split(",") if r.strip())
        for word in re.split(r"[,\s]+", _IGNORE_RULES_RE.sub("", body)):
            if not word:
                continue
            alias = _PRAGMA_ALIASES.get(word)
            if word in _PRAGMA_ALIASES and alias is None:
                rules = None  # blanket ignore
                break
            if alias:
                rules.update(alias)
        if rules is None or rules:
            prev = out.get(lineno, set())
            out[lineno] = (
                None if (rules is None or prev is None) else (prev | rules)
            )
    return out


_COMPOUND_STMTS = (
    ast.For, ast.AsyncFor, ast.While, ast.If, ast.With, ast.AsyncWith,
    ast.Try, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
)


def _simple_stmt_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    """(start, end) line spans of every non-compound statement, sorted —
    the ranges a line-level pragma extends over.

    Decorator expressions get their own spans: they hang off a compound
    statement (the decorated def/class), so without this a pragma on the
    closing line of a formatter-wrapped ``@partial(jax.jit, ...)`` would
    not reach a finding anchored to the decorator's first line."""
    spans = [
        (n.lineno, n.end_lineno or n.lineno)
        for n in ast.walk(tree)
        if isinstance(n, ast.stmt) and not isinstance(n, _COMPOUND_STMTS)
    ]
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            for dec in n.decorator_list:
                spans.append((dec.lineno, dec.end_lineno or dec.lineno))
    spans.sort()
    return spans


def _span_for(spans: List[Tuple[int, int]], lineno: int) -> Tuple[int, int]:
    """Smallest simple-statement span containing `lineno` as a half-open
    line range (falls back to the single line)."""
    best: Optional[Tuple[int, int]] = None
    for start, end in spans:
        if start <= lineno <= end and (
            best is None or (end - start) < (best[1] - best[0])
        ):
            best = (start, end)
    if best is None:
        return (lineno, lineno + 1)
    return (best[0], best[1] + 1)


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", "node_modules")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def lint_source(
    src: str,
    path: str,
    axes: Dict[str, str],
    donors: Optional[Dict[str, Tuple[int, ...]]] = None,
    tree: Optional[ast.AST] = None,
    collect_donors: bool = True,
) -> List[Finding]:
    """Run every rule over one module's source. Pragma-filtered.

    `tree`/`collect_donors` let lint_paths reuse its pre-pass parse and
    module-wide donor registry instead of re-doing both per file."""
    from .rules import RULES, collect_donor_factories

    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            line = e.lineno or 0
            lines = src.splitlines()
            text = lines[line - 1].strip() if 0 < line <= len(lines) else ""
            return [
                Finding("PSL000", path, line, e.offset or 0,
                        f"syntax error: {e.msg}", text)
            ]
    lines = src.splitlines()
    pragmas = _pragmas_for(src)
    spans = _simple_stmt_spans(tree)
    donors = dict(donors or {})
    if collect_donors:
        donors.update(collect_donor_factories(tree))

    def suppressed(rule_id: str, lineno: int) -> bool:
        # a pragma anywhere on the finding's (simple) statement applies,
        # so `# psl: sync-ok` after the closing paren of a wrapped call
        # keeps working when a formatter splits the line
        for ln in range(*_span_for(spans, lineno)):
            if ln not in pragmas:
                continue
            sup = pragmas[ln]
            if sup is None or rule_id in sup:  # None = blanket ignore
                return True
        return False

    def stmt_text(lineno: int) -> str:
        # the WHOLE (simple) statement, joined: a formatter-wrapped
        # `return jax.jit(` first line alone would alias every other
        # wrapped jit call in the file in the baseline's multiset key
        start, end_excl = _span_for(spans, lineno)
        start = max(start, 1)
        joined = " ".join(
            l.strip() for l in lines[start - 1:end_excl - 1] if l.strip()
        )
        return joined[:300]

    findings: List[Finding] = []
    for rule in RULES:
        for (lineno, col, message) in rule.check(tree, path=path, axes=axes,
                                                donors=donors):
            if suppressed(rule.rule_id, lineno):
                continue
            findings.append(
                Finding(rule.rule_id, path, lineno, col, message,
                        stmt_text(lineno))
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def scanned_files(paths: Sequence[str]) -> List[str]:
    """The deduped file list a lint run over `paths` covers — the single
    definition of 'scanned', shared by lint_paths and the CLI's
    stale-baseline scoping."""
    return list(dict.fromkeys(iter_py_files(paths)))


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    """Lint every .py file under `paths` (two passes: donor factories for
    PSL005 are collected across the whole file set first, so a test file
    calling a train-step factory defined in parallel/ is still checked)."""
    from .rules import collect_donor_factories

    axes, _ = discover_axes(paths)
    files = scanned_files(paths)
    sources: Dict[str, str] = {}
    trees: Dict[str, ast.AST] = {}
    donors: Dict[str, Tuple[int, ...]] = {}
    for fp in files:
        try:
            with open(fp, "r", encoding="utf-8") as f:
                sources[fp] = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            trees[fp] = ast.parse(sources[fp])
            donors.update(collect_donor_factories(trees[fp]))
        except SyntaxError:
            pass  # lint_source re-parses and reports PSL000
    # the engine's own package also declares donor factories (parallel/):
    # pick them up even when only tests/ is being linted
    for d in _sibling_parallel_dirs(paths):
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py"):
                continue
            try:
                with open(os.path.join(d, fname), "r", encoding="utf-8") as f:
                    donors.update(collect_donor_factories(ast.parse(f.read())))
            except (OSError, SyntaxError, UnicodeDecodeError):
                continue
    findings: List[Finding] = []
    for fp in files:
        if fp in sources:
            findings.extend(
                lint_source(sources[fp], fp, axes, donors,
                            tree=trees.get(fp), collect_donors=fp not in trees)
            )
    return findings


def _sibling_parallel_dirs(paths: Sequence[str]) -> List[str]:
    from .axes import _candidate_axis_dirs

    return list(_candidate_axis_dirs(paths))


# ------------------------------------------------------------------ baseline

def to_baseline_json(findings: Sequence[Finding]) -> dict:
    return {
        "version": 1,
        "tool": "pslint",
        "findings": [f.to_json() for f in findings],
    }


def load_baseline(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return [Finding.from_json(d) for d in data.get("findings", [])]


def baseline_counts(findings: Sequence[Finding]) -> Counter:
    return Counter(f.key for f in findings)


def apply_baseline(
    findings: Sequence[Finding],
    baseline: Sequence[Finding],
    scanned_paths: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], List[Finding], List[Finding]]:
    """Split current findings into (new, baselined); also return stale
    baseline entries that no longer match anything (safe to prune).

    `scanned_paths` (the files this run actually linted) scopes the
    staleness report: an entry for a file OUTSIDE the scanned set is
    neither matchable nor stale — linting `tools/` must not report the
    package's own baseline entries as "stale" just because their files
    were not in this run's scope. None (unit tests / full-knowledge
    callers) keeps every entry eligible."""
    budget = baseline_counts(baseline)
    new: List[Finding] = []
    matched: List[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            matched.append(f)
        else:
            new.append(f)
    scanned: Optional[Set[str]] = None
    if scanned_paths is not None:
        scanned = {os.path.normpath(p) for p in scanned_paths}
    stale: List[Finding] = []
    leftovers = Counter({k: v for k, v in budget.items() if v > 0})
    for b in baseline:
        if scanned is not None and os.path.normpath(b.path) not in scanned:
            continue
        if leftovers.get(b.key, 0) > 0:
            leftovers[b.key] -= 1
            stale.append(b)
    return new, matched, stale


def render_text(
    new: Sequence[Finding],
    baselined: Sequence[Finding],
    stale: Sequence[Finding],
    verbose: bool = False,
) -> str:
    out: List[str] = []
    for f in new:
        out.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
        if f.text:
            out.append(f"    {f.text}")
    if new:
        out.append("")
    counts = Counter(f.rule for f in new)
    summary = ", ".join(f"{r}: {n}" for r, n in sorted(counts.items()))
    out.append(
        f"pslint: {len(new)} new finding(s)"
        + (f" ({summary})" if summary else "")
        + f", {len(baselined)} baselined, {len(stale)} stale baseline entr"
        + ("y" if len(stale) == 1 else "ies")
    )
    if verbose and stale:
        out.append("stale baseline entries (prune with --write-baseline):")
        for b in stale:
            out.append(f"    {b.rule} {b.path}: {b.text}")
    return "\n".join(out)
