"""The pslint rules (PSL001-PSL008). Pure-AST: no jax import, no code
execution. PSL006-PSL008 (SPMD-divergence taint analysis) live in
diverge.py and are registered here.

Each rule is a class with `rule_id` and `check(tree, path, axes, donors)`
yielding (lineno, col, message) tuples. Shared helpers keep name
resolution (attribute-chain tails) consistent across rules.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

Finding3 = Tuple[int, int, str]


def _tail(func: ast.expr) -> Optional[str]:
    """`jax.lax.psum` -> 'psum', `psum` -> 'psum', else None."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _dotted(node: ast.expr) -> str:
    """Best-effort dotted name: `np.random.uniform` -> 'np.random.uniform'."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _str_constants(node: ast.expr) -> Iterator[ast.Constant]:
    """String constants in `node`, descending through tuples/lists."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _str_constants(elt)


def _donate_argnums(call: ast.Call) -> Tuple[int, ...]:
    """Donated positions of a ``jit(..., donate_argnums=...)`` call. The
    repo idiom is ``donate_argnums=(0, 1) if donate else ()`` — the
    enabled (IfExp body) branch is what callers get unless they opt out."""
    for k in call.keywords:
        if k.arg == "donate_argnums":
            v = k.value
            if isinstance(v, ast.IfExp):
                v = v.body
            return _const_int_tuple(v)
    return ()


def _compound_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """All statement blocks of a compound statement: body, orelse,
    finalbody, and every except-handler body."""
    bodies: List[List[ast.stmt]] = [stmt.body]
    for attr in ("orelse", "finalbody"):
        extra = getattr(stmt, attr, None)
        if extra:
            bodies.append(extra)
    for h in getattr(stmt, "handlers", []) or []:
        bodies.append(h.body)
    return bodies


def _arg(call: ast.Call, pos: int, kw: str) -> Optional[ast.expr]:
    if len(call.args) > pos and not any(
        isinstance(a, ast.Starred) for a in call.args[: pos + 1]
    ):
        return call.args[pos]
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    return None


# ------------------------------------------------------------------- PSL001

class MeshAxisRule:
    """String-literal axis names must match a declared ``*_AXIS`` constant
    — and should *be* the constant, so a rename in parallel/mesh.py can't
    silently orphan a collective (psum over a nonexistent axis fails at
    trace time at best, at run time on a different mesh at worst)."""

    rule_id = "PSL001"

    # func tail -> (positional index, keyword) of the axis-name argument
    AXIS_CALLS: Dict[str, Tuple[int, str]] = {
        "psum": (1, "axis_name"),
        "pmean": (1, "axis_name"),
        "pmax": (1, "axis_name"),
        "pmin": (1, "axis_name"),
        "ppermute": (1, "axis_name"),
        "pshuffle": (1, "axis_name"),
        "all_gather": (1, "axis_name"),
        "all_to_all": (1, "axis_name"),
        "psum_scatter": (1, "axis_name"),
        "axis_index": (0, "axis_name"),
        "axis_size": (0, "axis_name"),
    }
    SPEC_CALLS = {"PartitionSpec", "P"}

    def check(self, tree: ast.AST, path: str, axes: Dict[str, str],
              donors: Dict[str, Tuple[int, ...]]) -> Iterable[Finding3]:
        known = ", ".join(sorted(axes))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(node.func)
            literals: List[ast.Constant] = []
            if tail in self.AXIS_CALLS:
                pos, kw = self.AXIS_CALLS[tail]
                target = _arg(node, pos, kw)
                if target is not None:
                    literals.extend(_str_constants(target))
            elif tail in self.SPEC_CALLS:
                for a in list(node.args) + [k.value for k in node.keywords]:
                    literals.extend(_str_constants(a))
            elif tail == "Mesh":
                target = _arg(node, 1, "axis_names")
                if target is not None:
                    literals.extend(_str_constants(target))
            for lit in literals:
                name = lit.value
                if name in axes:
                    yield (
                        lit.lineno,
                        lit.col_offset,
                        f"axis literal '{name}' — use the {axes[name]} "
                        f"constant from ps_pytorch_tpu.parallel",
                    )
                else:
                    yield (
                        lit.lineno,
                        lit.col_offset,
                        f"unknown mesh axis '{name}' (declared axes: {known})",
                    )


# ------------------------------------------------------------------- PSL002

class RecompilationRule:
    """jit must be built once, outside the hot path. Flags jax.jit/pjit
    (a) called inside a per-iteration context — for/while loop bodies,
    while-tests, comprehension/generator element expressions (a for's
    iterable and else-bodies run once and are exempt), (b) applied to a
    lambda (a fresh callable per call never hits the jit cache), (c)
    compiled and immediately invoked inside such a context
    (``jax.jit(f)(x)`` per iteration discards the executable and
    recompiles; outside one, a single build + call is not a hazard)."""

    rule_id = "PSL002"

    JIT_TAILS = {"jit", "pjit"}

    _COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp,
                       ast.GeneratorExp)

    def check(self, tree: ast.AST, path: str, axes: Dict[str, str],
              donors: Dict[str, Tuple[int, ...]]) -> Iterable[Finding3]:
        yield from self._visit(tree, depth=0)

    def _is_jit_call(self, node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and _tail(node.func) in self.JIT_TAILS
            # plain `partial(...)`/`functools.partial(jax.jit, ...)` etc.
            # never reach here: tail must literally be jit/pjit
        )

    def _check_node(self, node: ast.AST, depth: int) -> Iterator[Finding3]:
        if self._is_jit_call(node):
            assert isinstance(node, ast.Call)
            if depth > 0:
                yield (
                    node.lineno,
                    node.col_offset,
                    "jax.jit called inside a loop — build the jitted "
                    "function once outside the hot path (each call "
                    "compiles into a fresh, unshared cache)",
                )
            if node.args and isinstance(node.args[0], ast.Lambda):
                yield (
                    node.lineno,
                    node.col_offset,
                    "jax.jit on a lambda — a fresh callable never hits "
                    "the jit cache across builders; hoist the body to a "
                    "module-level def (cache the jitted result if built "
                    "per-config)",
                )
        if (
            isinstance(node, ast.Call)
            and self._is_jit_call(node.func)
            # only certain-recompile sites: outside a loop, compiling
            # once and calling once is not a hazard (and binding the
            # callable first would change nothing)
            and depth > 0
        ):
            yield (
                node.lineno,
                node.col_offset,
                "jit(...)(...) inside a loop compiles a fresh "
                "executable every iteration and discards it — build "
                "the jitted function once, outside the loop",
            )

    def _visit(self, node: ast.AST, depth: int) -> Iterator[Finding3]:
        """Depth tracks how many per-iteration contexts enclose `node`.
        Loop HEADERS that run once (a for's iterable, else-bodies) stay at
        the enclosing depth; while-tests, loop bodies, and comprehension
        element/condition expressions are per-iteration."""
        yield from self._check_node(node, depth)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield from self._visit(node.target, depth)
            yield from self._visit(node.iter, depth)  # evaluated once
            for n in node.body:
                yield from self._visit(n, depth + 1)
            for n in node.orelse:
                yield from self._visit(n, depth)  # else: runs once
        elif isinstance(node, ast.While):
            yield from self._visit(node.test, depth + 1)  # per iteration
            for n in node.body:
                yield from self._visit(n, depth + 1)
            for n in node.orelse:
                yield from self._visit(n, depth)
        elif isinstance(node, self._COMPREHENSIONS):
            first = node.generators[0]
            yield from self._visit(first.iter, depth)  # evaluated once
            for gen in node.generators:
                yield from self._visit(gen.target, depth + 1)
                for cond in gen.ifs:
                    yield from self._visit(cond, depth + 1)
            for gen in node.generators[1:]:
                yield from self._visit(gen.iter, depth + 1)
            if isinstance(node, ast.DictComp):
                yield from self._visit(node.key, depth + 1)
                yield from self._visit(node.value, depth + 1)
            else:
                yield from self._visit(node.elt, depth + 1)
        else:
            for child in ast.iter_child_nodes(node):
                yield from self._visit(child, depth)


# ------------------------------------------------------------------- PSL003

class TracedPurityRule:
    """Side effects inside traced functions run once at trace time (or
    never again after a cache hit): prints vanish, wall-clock reads freeze,
    np.random draws become compile-time constants, and mutation of closure
    or global state desyncs across retraces. Traced = decorated with
    jit/pjit, or passed (by name or as a lambda) to jit / shard_map /
    vmap / pmap / grad / value_and_grad / checkpoint / remat / scan /
    while_loop / fori_loop — including every nested def inside one."""

    rule_id = "PSL003"

    TRACERS = {
        "jit", "pjit", "shard_map", "vmap", "pmap", "grad",
        "value_and_grad", "checkpoint", "remat", "scan", "while_loop",
        "fori_loop", "custom_vjp", "custom_jvp", "pallas_call",
    }
    CLOCK_CALLS = {
        "time.time", "time.perf_counter", "time.monotonic",
        "time.process_time", "datetime.datetime.now", "datetime.now",
    }
    MUTATORS = {"append", "extend", "insert", "pop", "remove", "clear",
                "update", "setdefault"}

    def check(self, tree: ast.AST, path: str, axes: Dict[str, str],
              donors: Dict[str, Tuple[int, ...]]) -> Iterable[Finding3]:
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        traced: List[ast.AST] = []
        seen: Set[int] = set()

        def mark(fn: ast.AST) -> None:
            if id(fn) not in seen:
                seen.add(id(fn))
                traced.append(fn)

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    if _tail(d) in ("jit", "pjit"):
                        mark(node)
                    elif (
                        isinstance(dec, ast.Call)
                        and _tail(dec.func) == "partial"
                        and dec.args
                        and _tail(dec.args[0]) in ("jit", "pjit")
                    ):
                        mark(node)
            elif isinstance(node, ast.Call) and _tail(node.func) in self.TRACERS:
                for a in node.args:
                    # unwrap functools.partial(fn, ...): fn is what traces
                    if isinstance(a, ast.Call) and _tail(a.func) == "partial" and a.args:
                        a = a.args[0]
                    if isinstance(a, ast.Lambda):
                        mark(a)
                    elif isinstance(a, ast.Name):
                        for fn in defs.get(a.id, ()):
                            mark(fn)

        for fn in traced:
            yield from self._check_traced(fn)

    def _check_traced(self, fn: ast.AST) -> Iterator[Finding3]:
        local: Set[str] = set()
        args = fn.args if hasattr(fn, "args") else None
        if args is not None:
            for a in (
                list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                local.add(a.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                local.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local.add(node.name)
                for a in (
                    list(node.args.posonlyargs) + list(node.args.args)
                    + list(node.args.kwonlyargs)
                    + ([node.args.vararg] if node.args.vararg else [])
                    + ([node.args.kwarg] if node.args.kwarg else [])
                ):
                    local.add(a.arg)

        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                yield (
                    node.lineno, node.col_offset,
                    "`global` inside a traced function — mutation happens "
                    "at trace time only and is frozen into the compiled "
                    "program",
                )
            elif isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                tail = _tail(node.func)
                if tail == "print" and isinstance(node.func, ast.Name):
                    yield (
                        node.lineno, node.col_offset,
                        "print() inside a traced function runs once at "
                        "trace time and shows tracers, not values — use "
                        "jax.debug.print",
                    )
                elif dotted in self.CLOCK_CALLS:
                    yield (
                        node.lineno, node.col_offset,
                        f"{dotted}() inside a traced function freezes to "
                        "the trace-time value — time on the host, around "
                        "the jitted call",
                    )
                elif re.match(r"^(np|numpy)\.random\.", dotted):
                    yield (
                        node.lineno, node.col_offset,
                        f"{dotted}() inside a traced function is drawn "
                        "once at trace time and baked into the program — "
                        "use jax.random with an explicit key",
                    )
            elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                # bare mutator statement (`lst.append(x)`): result unused,
                # so the call exists only for its side effect — which under
                # trace happens once. A captured result (`a, b = tx.update(
                # ...)`) is a pure functional call and is NOT flagged.
                call = node.value
                if (
                    isinstance(call.func, ast.Attribute)
                    and call.func.attr in self.MUTATORS
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id not in local
                ):
                    yield (
                        call.lineno, call.col_offset,
                        f"mutation of closure/global '{call.func.value.id}."
                        f"{call.func.attr}()' inside a traced function — "
                        "runs at trace time only; return the value instead",
                    )
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id not in local
            ):
                yield (
                    node.lineno, node.col_offset,
                    f"subscript-assignment to closure/global "
                    f"'{node.targets[0].value.id}' inside a traced function "
                    "— runs at trace time only",
                )


# ------------------------------------------------------------------- PSL004

class HostSyncRule:
    """Hot-path loop bodies in the training driver must not synchronously
    pull device values to the host every step: `.item()`, `float(metrics)`,
    `np.asarray(device)`, and `jax.device_get` all block dispatch and
    serialize the pipeline. Periodic, intentional transfers carry a
    ``# psl: sync-ok`` pragma. Scope: modules named in HOT_MODULES —
    the training driver AND the serving request loop (serve/engine.py),
    where a stray per-token fetch beyond the scheduler's one fused
    [slots] read would serialize every decode tick."""

    rule_id = "PSL004"

    # entries with a "/" match as path suffixes (pinning the rule to THE
    # serve engine, not any future module that happens to be named
    # engine.py); bare names match by basename
    HOT_MODULES = {"trainer.py", "serve/engine.py"}
    # directory trees where EVERY function is a hot-path loop body by
    # contract, scanned in STRICT mode: the observability layer runs
    # inside the training/serving tick, so any host sync it introduces
    # perturbs the run it measures. Strict mode additionally flags
    # jax.block_until_ready — elsewhere the blessed barrier primitive,
    # here a new sync the traced run would not otherwise have (the one
    # deliberate profiler-stop barrier carries `# psl: sync-ok`).
    HOT_TREES = ("ps_pytorch_tpu/obs/",)
    STEP_CALL_RE = re.compile(r"(^|[._])(train_|eval_)?step(_fn)?$")
    # a per-step entry point (the serving engine's tick()) IS a loop
    # body by contract — its caller invokes it once per decode step —
    # so its top level is scanned at loop depth 1 even though the
    # enclosing `while` lives in another function
    HOT_FN_RE = re.compile(r"^_?tick\w*$")

    _COMPOUND = (ast.For, ast.AsyncFor, ast.While, ast.If, ast.With,
                 ast.AsyncWith, ast.Try)

    def _is_hot(self, path: str) -> bool:
        norm = path.replace(os.sep, "/")
        for mod in self.HOT_MODULES:
            if "/" in mod:
                if norm == mod or norm.endswith("/" + mod):
                    return True
            elif os.path.basename(path) == mod:
                return True
        return False

    def _in_hot_tree(self, path: str) -> bool:
        norm = "/" + path.replace(os.sep, "/")
        return any("/" + tree in norm for tree in self.HOT_TREES)

    def check(self, tree: ast.AST, path: str, axes: Dict[str, str],
              donors: Dict[str, Tuple[int, ...]]) -> Iterable[Finding3]:
        strict = self._in_hot_tree(path)
        if not strict and not self._is_hot(path):
            return
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # flow-sensitive: taint follows statement order, so a
                # periodic `metrics = jax.device_get(metrics)` inside a
                # log window untaints only from that point on — per-step
                # syncs on the same name BEFORE the fetch still flag
                depth0 = (
                    1 if strict or self.HOT_FN_RE.match(node.name) else 0
                )
                yield from self._scan_block(
                    node.body, tainted=set(), loop_depth=depth0,
                    flagged=set(), strict=strict,
                )

    def _flag_stmt(
        self, stmt: ast.stmt, tainted: Set[str], loop_depth: int,
        flagged: Set[int], strict: bool = False,
    ) -> Iterator[Finding3]:
        if loop_depth == 0:
            return

        def refs_tainted(expr: ast.expr) -> bool:
            return any(
                isinstance(n, ast.Name) and n.id in tainted
                for n in ast.walk(expr)
            )

        for n in ast.walk(stmt):
            if not isinstance(n, ast.Call) or id(n) in flagged:
                continue
            tail = _tail(n.func)
            msg = None
            if tail == "device_get":
                msg = (
                    "jax.device_get in a hot-path loop blocks dispatch "
                    "every step — batch transfers behind a periodic "
                    "window or mark intentional ones `# psl: sync-ok`"
                )
            elif tail == "item" and isinstance(n.func, ast.Attribute):
                msg = (
                    ".item() in a hot-path loop forces a device->host "
                    "sync every step"
                )
            elif (
                tail in ("float", "int")
                and isinstance(n.func, ast.Name)
                and n.args
                and refs_tainted(n.args[0])
            ):
                msg = (
                    f"{tail}() on a device value in a hot-path loop "
                    "forces a sync every step — fetch metrics "
                    "periodically instead"
                )
            elif (
                tail == "asarray"
                and _dotted(n.func) in ("np.asarray", "numpy.asarray")
                and n.args
                and refs_tainted(n.args[0])
            ):
                msg = (
                    "np.asarray on a device value in a hot-path loop "
                    "copies to host synchronously every step"
                )
            elif strict and tail == "block_until_ready":
                msg = (
                    "block_until_ready in observability code adds a host "
                    "sync the traced run would not otherwise pay — the "
                    "tracer must reuse the driver's existing per-window "
                    "sync points (a deliberate once-per-capture profiler "
                    "barrier may carry `# psl: sync-ok`)"
                )
            if msg is not None:
                flagged.add(id(n))
                yield (n.lineno, n.col_offset, msg)

    def _apply_taint(self, stmt: ast.stmt, tainted: Set[str]) -> None:
        if not isinstance(stmt, ast.Assign):
            return
        calls = [n for n in ast.walk(stmt.value) if isinstance(n, ast.Call)]
        from_step = any(
            self.STEP_CALL_RE.search(_dotted(c.func) or "") for c in calls
        )
        fetched = any(
            _tail(c.func) in ("device_get", "block_until_ready")
            for c in calls
        )
        names = {
            t.id
            for tgt in stmt.targets
            for t in ast.walk(tgt)
            if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store)
        }
        if from_step and not fetched:
            tainted |= names
        else:
            # any other rebinding (a host fetch, a fresh dict, ...) kills
            # the taint from this point in the flow on
            tainted -= names

    def _scan_block(
        self, stmts: List[ast.stmt], tainted: Set[str], loop_depth: int,
        flagged: Set[int], strict: bool = False,
    ) -> Iterator[Finding3]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, self._COMPOUND):
                for attr in ("test", "iter"):
                    header = getattr(stmt, attr, None)
                    if header is not None:
                        # a while-test re-runs every iteration, so it sits
                        # INSIDE its own loop; a for's iterable (and an
                        # if-test) evaluates at the enclosing depth
                        header_depth = (
                            loop_depth + 1
                            if isinstance(stmt, ast.While) and attr == "test"
                            else loop_depth
                        )
                        yield from self._flag_stmt(
                            ast.Expr(value=header), tainted, header_depth,
                            flagged, strict,
                        )
                bodies = _compound_bodies(stmt)
                if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    # two passes: a step call at the top of the loop body
                    # re-taints names a window fetch untainted at the
                    # bottom, matching the next iteration's flow
                    for _ in range(2):
                        for b in bodies:
                            yield from self._scan_block(
                                b, tainted, loop_depth + 1, flagged, strict
                            )
                    if isinstance(stmt, ast.While):
                        # back-edge: the test re-runs with the body's taint
                        yield from self._flag_stmt(
                            ast.Expr(value=stmt.test), tainted,
                            loop_depth + 1, flagged, strict,
                        )
                else:
                    for b in bodies:
                        yield from self._scan_block(
                            b, tainted, loop_depth, flagged, strict
                        )
            else:
                yield from self._flag_stmt(
                    stmt, tainted, loop_depth, flagged, strict
                )
                self._apply_taint(stmt, tainted)


# ------------------------------------------------------------------- PSL005

def collect_donor_factories(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """Functions that return ``jax.jit(..., donate_argnums=...)``: their
    name -> the donated positions. The repo idiom is
    ``return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())`` —
    the enabled branch of the conditional is what callers get unless they
    pass ``donate=False``."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for ret in ast.walk(node):
            if not (isinstance(ret, ast.Return) and isinstance(ret.value, ast.Call)):
                continue
            call = ret.value
            if _tail(call.func) not in ("jit", "pjit"):
                continue
            nums = _donate_argnums(call)
            if nums:
                out[node.name] = nums
    return out


def _const_int_tuple(node: ast.expr) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals: List[int] = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.append(e.value)
            else:
                return ()
        return tuple(vals)
    return ()


class DonationReuseRule:
    """`donate_argnums` hands the input buffer to XLA: on TPU, reading the
    python variable afterwards touches deallocated memory (CPU only warns
    — which is why this passes in tests and dies on the pod). Flags reads
    of a variable after it was passed in a donated position without being
    rebound. Donor step functions are discovered from the linted sources
    (any factory returning jit(..., donate_argnums=...)); call sites that
    pass ``donate=False`` to the factory are exempt."""

    rule_id = "PSL005"

    _COMPOUND = (ast.For, ast.AsyncFor, ast.While, ast.If, ast.With,
                 ast.AsyncWith, ast.Try)

    def check(self, tree: ast.AST, path: str, axes: Dict[str, str],
              donors: Dict[str, Tuple[int, ...]]) -> Iterable[Finding3]:
        # step vars assigned anywhere in the module (module level or any
        # function) are visible to nested scopes via closures — collect a
        # module-wide seed so `def run(...): es_step(...)` is still checked
        seed: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                self._register(node, node.value, donors, seed)
        scopes: List[List[ast.stmt]] = [tree.body] + [
            n.body for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for body in scopes:
            yield from self._scan_block(body, donors, dict(seed), {})

    def _register(
        self,
        stmt: ast.Assign,
        call: ast.Call,
        donors: Dict[str, Tuple[int, ...]],
        step_vars: Dict[str, Tuple[int, ...]],
    ) -> None:
        """`x = <factory>(...)` or `x = jax.jit(..., donate_argnums=...)`."""
        tail = _tail(call.func)
        targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
        if not targets:
            return
        if tail in donors:
            opted_out = any(
                k.arg == "donate"
                and isinstance(k.value, ast.Constant)
                and k.value.value is False
                for k in call.keywords
            )
            for tgt in targets:
                if opted_out:
                    step_vars.pop(tgt.id, None)
                else:
                    step_vars[tgt.id] = donors[tail]
        elif tail in ("jit", "pjit"):
            nums = _donate_argnums(call)
            if nums:
                for tgt in targets:
                    step_vars[tgt.id] = nums

    def _process_exprs(
        self,
        nodes: List[ast.AST],
        step_vars: Dict[str, Tuple[int, ...]],
        consumed: Dict[str, str],
    ) -> Iterator[Finding3]:
        """Steps for one simple statement (or a compound header): report
        loads of consumed names, then apply this statement's donations.

        Lambda bodies are excluded: their execution is deferred and their
        parameters shadow enclosing names, so neither their loads nor the
        step calls inside them happen at this statement."""

        def walk_no_lambda(node: ast.AST) -> Iterator[ast.AST]:
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                return
            yield node
            for child in ast.iter_child_nodes(node):
                yield from walk_no_lambda(child)

        walked = [n for node in nodes for n in walk_no_lambda(node)]
        for n in walked:
            if (
                isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id in consumed
            ):
                yield (
                    n.lineno, n.col_offset,
                    f"'{n.id}' read after being donated to "
                    f"{consumed[n.id]} — the buffer is invalid on TPU "
                    "(CPU only warns); rebind the result or build the "
                    "step with donate=False",
                )
                consumed.pop(n.id, None)  # one report per donation
        for n in walked:
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id in step_vars
            ):
                for pos in step_vars[n.func.id]:
                    if pos < len(n.args) and isinstance(n.args[pos], ast.Name):
                        consumed[n.args[pos].id] = f"'{n.func.id}'"
        for n in walked:
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                consumed.pop(n.id, None)

    def _scan_block(
        self,
        stmts: List[ast.stmt],
        donors: Dict[str, Tuple[int, ...]],
        step_vars: Dict[str, Tuple[int, ...]],
        consumed: Dict[str, str],
    ) -> Iterator[Finding3]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes are scanned separately
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                self._register(stmt, stmt.value, donors, step_vars)
            if isinstance(stmt, self._COMPOUND):
                headers: List[ast.AST] = []
                for attr in ("test", "iter", "target"):
                    v = getattr(stmt, attr, None)
                    if v is not None:
                        headers.append(v)
                for item in getattr(stmt, "items", []) or []:
                    headers.append(item.context_expr)
                    if item.optional_vars is not None:
                        headers.append(item.optional_vars)
                yield from self._process_exprs(headers, step_vars, consumed)
                bodies = _compound_bodies(stmt)
                if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    # two passes: a donation at the bottom of the loop body
                    # reaches a load at the top on the next iteration
                    shadow = dict(consumed)
                    for b in bodies:
                        for _ in self._scan_block(b, donors, step_vars, shadow):
                            pass
                    consumed.update(shadow)
                for b in bodies:
                    yield from self._scan_block(b, donors, step_vars, consumed)
            else:
                yield from self._process_exprs([stmt], step_vars, consumed)


# Imported at the bottom so diverge.py can reuse this module's helpers
# (STEP_CALL_RE, _dotted, _tail) without a circular import at load time.
from .diverge import (  # noqa: E402
    DivergentGuardRule,
    DivergentOrderRule,
    DivergentTracedRule,
)

RULES = [
    MeshAxisRule(),
    RecompilationRule(),
    TracedPurityRule(),
    HostSyncRule(),
    DonationReuseRule(),
    DivergentGuardRule(),
    DivergentTracedRule(),
    DivergentOrderRule(),
]
RULE_IDS = tuple(r.rule_id for r in RULES)
