"""pslint — JAX/TPU-aware static analysis for the hot path.

The framework's wins (compression, overlap, hierarchical aggregation) are
erased by a single silent recompilation, a stray host sync in the trainer
loop, or a mistyped mesh-axis name — failure modes XLA accepts without
complaint and code review rarely catches. pslint guards them with AST
rules, each with a stable ID:

  PSL001  mesh-axis consistency: string-literal axis names passed to
          collectives/PartitionSpec must match the ``*_AXIS`` constants
          declared in ``parallel/`` (and should BE the constants).
  PSL002  recompilation hazards: ``jax.jit`` inside loops, jit on a fresh
          lambda, jit compiled-and-discarded at the call site.
  PSL003  impure traced functions: ``print``, wall-clock reads,
          ``np.random.*``, closure/global mutation inside functions that
          jax traces (jit / shard_map / scan / grad / vmap bodies).
  PSL004  hidden host syncs in hot paths: ``.item()``, ``float(device)``,
          ``np.asarray(device)``, ``jax.device_get`` inside trainer-loop
          bodies without an explicit ``# psl: sync-ok`` pragma.
  PSL005  donated-buffer reuse: reading a variable after it was passed in
          a ``donate_argnums`` position (invalid buffer on TPU; CPU only
          warns, so tests pass locally and crash on the pod).
  PSL006  divergent-collective guard: process-divergent host state
          (process_index, clocks, RNG, fs listings, env vars, caught
          exceptions) guards a branch/loop that runs a collective on one
          path but not the other, or raises out from under divergent
          control while a later collective still expects this process.
  PSL007  divergent traced value: a process-divergent value flows into a
          traced step call, checkpoint restore, shared artifact, or run
          identity that must be bit-identical on every host.
  PSL008  divergent collective order: both paths of a tainted branch run
          collectives, but in different orders — cross-matched rendezvous.

PSL006-PSL008 (the psdiverge pass, diverge.py) only analyze modules that
reference the multihost machinery; ``jax.process_count()`` compares are
deployment constants, and ``broadcast_one_to_all``/``process_allgather``
launder taint, so the blessed rank-0-then-broadcast idiom never fires.

Usage:
    python -m ps_pytorch_tpu.lint [paths] [--format json] \
        [--baseline lint_baseline.json] [--write-baseline]

Suppression: ``# psl: ignore`` (all rules on that line),
``# psl: ignore[PSL001,PSL004]`` (specific rules), ``# psl: sync-ok``
(alias for ignore[PSL004]), ``# psl: donate-ok`` (alias for
ignore[PSL005]), ``# psl: diverge-ok`` (alias for
ignore[PSL006,PSL007,PSL008]). Legacy findings live in a checked-in baseline
(``lint_baseline.json``) so they don't block CI; new findings fail tier-1
via tests/test_lint.py.
"""

from .core import (
    Finding,
    apply_baseline,
    baseline_counts,
    lint_paths,
    load_baseline,
    render_text,
    to_baseline_json,
)
from .rules import RULE_IDS, RULES

__all__ = [
    "Finding",
    "RULES",
    "RULE_IDS",
    "apply_baseline",
    "baseline_counts",
    "lint_paths",
    "load_baseline",
    "render_text",
    "to_baseline_json",
]
