"""pslint — JAX/TPU-aware static analysis for the hot path.

The framework's wins (compression, overlap, hierarchical aggregation) are
erased by a single silent recompilation, a stray host sync in the trainer
loop, or a mistyped mesh-axis name — failure modes XLA accepts without
complaint and code review rarely catches. pslint guards them with AST
rules, each with a stable ID:

  PSL001  mesh-axis consistency: string-literal axis names passed to
          collectives/PartitionSpec must match the ``*_AXIS`` constants
          declared in ``parallel/`` (and should BE the constants).
  PSL002  recompilation hazards: ``jax.jit`` inside loops, jit on a fresh
          lambda, jit compiled-and-discarded at the call site.
  PSL003  impure traced functions: ``print``, wall-clock reads,
          ``np.random.*``, closure/global mutation inside functions that
          jax traces (jit / shard_map / scan / grad / vmap bodies).
  PSL004  hidden host syncs in hot paths: ``.item()``, ``float(device)``,
          ``np.asarray(device)``, ``jax.device_get`` inside trainer-loop
          bodies without an explicit ``# psl: sync-ok`` pragma.
  PSL005  donated-buffer reuse: reading a variable after it was passed in
          a ``donate_argnums`` position (invalid buffer on TPU; CPU only
          warns, so tests pass locally and crash on the pod).

Usage:
    python -m ps_pytorch_tpu.lint [paths] [--format json] \
        [--baseline lint_baseline.json] [--write-baseline]

Suppression: ``# psl: ignore`` (all rules on that line),
``# psl: ignore[PSL001,PSL004]`` (specific rules), ``# psl: sync-ok``
(alias for ignore[PSL004]), ``# psl: donate-ok`` (alias for
ignore[PSL005]). Legacy findings live in a checked-in baseline
(``lint_baseline.json``) so they don't block CI; new findings fail tier-1
via tests/test_lint.py.
"""

from .core import (
    Finding,
    apply_baseline,
    baseline_counts,
    lint_paths,
    load_baseline,
    render_text,
    to_baseline_json,
)
from .rules import RULE_IDS, RULES

__all__ = [
    "Finding",
    "RULES",
    "RULE_IDS",
    "apply_baseline",
    "baseline_counts",
    "lint_paths",
    "load_baseline",
    "render_text",
    "to_baseline_json",
]
