"""psdiverge: SPMD-divergence taint analysis (PSL006-PSL008).

Multihost JAX programs are SPMD at the *host* level too: every process
runs the same Python loop, and any cross-process operation (a
``broadcast_one_to_all``, a ``sync_global_devices`` barrier, a
``save_checkpoint`` that gathers sharded state) is a rendezvous that
every process must reach, in the same order, with bit-identical control
decisions. Host state that differs between processes — the process
index itself, wall clocks, unseeded RNG, filesystem listings, env vars,
caught-exception state — must therefore never decide *whether*, *when*,
or *with what values* a rendezvous runs, unless it is first laundered
through a consensus collective.

This module implements a flow-sensitive, interprocedural-within-module
taint analysis over exactly that invariant, shipping three rules that
ride the existing pslint CLI/pragma/baseline machinery:

PSL006  divergent-collective guard — process-divergent state guards a
        branch/loop that contains a collective on one path but not the
        other, or raises out from under divergent control while later
        collectives still expect this process (PR 3's ``save_checkpoint``
        stranded ranks 1..N-1 in exactly this shape).
PSL007  divergent traced value — a process-divergent value flows into a
        traced step call, a checkpoint-restore path, a shared artifact
        write, or run-identity metadata that must be bit-identical on
        every host (PR 7's per-host ``agg_count`` and torn-replica
        resume).
PSL008  divergent collective order — both branches of a tainted
        condition run collectives, but in different orders, so processes
        taking different branches rendezvous cross-matched and deadlock.

The blessed idiom is sanctioned by construction, not special-cased: a
``jax.process_index() == 0`` branch with no collectives inside and a
``broadcast_one_to_all``/``process_allgather`` rejoin afterwards never
fires, because consensus collectives launder taint and a collective-free
branch pair is symmetric. ``jax.process_count()`` compares are treated
as deployment constants (every process agrees on the count), so
``if jax.process_count() <= 1: return ...`` early-exits flip the
analysis into single-process context instead of poisoning the tail.

Only modules that actually engage the multihost machinery are analyzed:
a file with no ``process_index``/``process_count``/``multihost_utils``
identifier in its AST (string/docstring mentions do not count) has no
rendezvous to strand and is skipped entirely.

``consensus_inventory()`` at the bottom is the pscheck companion: it
walks the package for consensus-shaped functions (a consensus collective
whose result is returned) so PSC110 can verify that registry configs'
declared host-consensus points actually exist.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .rules import HostSyncRule, _dotted, _tail

# One source of truth for "is this call a traced step": PSL004's notion of
# the hot path and PSL007's notion of a traced-knob sink must agree.
STEP_CALL_RE = HostSyncRule.STEP_CALL_RE

# --------------------------------------------------------------------------
# Taint sources: calls whose results differ between processes.

_CLOCK_TAILS = {
    "time",
    "time_ns",
    "monotonic",
    "monotonic_ns",
    "perf_counter",
    "perf_counter_ns",
    "process_time",
    "process_time_ns",
}
_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
_FS_DOTTED = {
    "os.listdir",
    "os.scandir",
    "os.walk",
    "os.stat",
    "glob.glob",
    "glob.iglob",
    "os.path.getmtime",
    "os.path.getctime",
    "os.path.getatime",
}
# Curated module-local/cross-module helpers whose return is known to be
# assembled from per-process filesystem or RNG state.
_DIVERGENT_RETURN_TAILS = {
    "available_steps",
    "latest_step",
    "latest_valid_step",
    "load_latest_valid",
    "new_run_id",
}

# Consensus collectives launder taint (their result is identical on all
# processes by construction); barriers are rendezvous but return nothing
# useful. Both count as collectives for guard/order analysis.
_CONSENSUS_TAILS = {"broadcast_one_to_all", "process_allgather"}
_BARRIER_TAILS = {"sync_global_devices", "assert_equal"}
_COLLECTIVE_TAILS = _CONSENSUS_TAILS | _BARRIER_TAILS | {"save_checkpoint"}

# Sinks: traced-knob restore paths, shared-artifact writers, run identity.
_RESTORE_TAILS = {"load_checkpoint", "restore_from_raw", "restore_sharded"}
_ARTIFACT_TAILS = {"save_geometry", "write_contract"}
_RUN_IDENTITY_TAILS = {"run_header", "Tracer"}

# Sentinel reason marking taint that flowed in from a function parameter
# (used during summary construction only; never shown to users).
_PARAM = "\x00param"


def _source_reason(call: ast.Call) -> Optional[str]:
    """Why the result of this call differs between processes, or None."""
    tail = _tail(call.func)
    dotted = _dotted(call.func)
    if tail == "process_index":
        return "jax.process_index()"
    if tail in _CLOCK_TAILS and (
        dotted.startswith("time.") or dotted in _CLOCK_TAILS
    ):
        return f"wall/monotonic clock {dotted or tail}()"
    if "datetime" in dotted and tail in {"now", "utcnow", "today"}:
        return f"wall clock {dotted}()"
    if dotted == "os.urandom" or dotted in {"uuid.uuid1", "uuid.uuid4"}:
        return f"unseeded RNG {dotted}()"
    if dotted.startswith(_RNG_PREFIXES):
        return f"unseeded RNG {dotted}()"
    if dotted in _FS_DOTTED or tail == "iterdir":
        return f"filesystem state {dotted or tail}()"
    if dotted == "os.getenv" or dotted.startswith("os.environ"):
        return f"environment variable {dotted}()"
    if tail in _DIVERGENT_RETURN_TAILS:
        return f"per-process value {tail}()"
    return None


def _is_env_subscript(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Subscript)
        and _dotted(node.value).startswith("os.environ")
    )


def _count_gate(test: ast.AST) -> Optional[str]:
    """Detect a pure ``jax.process_count() <cmp> <int>`` compare.

    Returns "body-multi" if the body executes in the multi-process
    deployment, "body-single" if the body executes only single-process,
    or None if the test is not an exact count gate. A gate is valid when
    its truth value is the same for any count >= 2 (so the analysis may
    treat it as a deployment constant, not a divergent branch).
    """
    if not (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and len(test.comparators) == 1
    ):
        return None
    lhs, op, rhs = test.left, test.ops[0], test.comparators[0]

    def is_count(node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and _tail(node.func) == "process_count"

    def const_int(node: ast.AST) -> Optional[int]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return node.value
        return None

    if is_count(lhs) and const_int(rhs) is not None:
        count_left, k = True, const_int(rhs)
    elif is_count(rhs) and const_int(lhs) is not None:
        count_left, k = False, const_int(lhs)
    else:
        return None

    def truth(count: int) -> bool:
        a, b = (count, k) if count_left else (k, count)
        if isinstance(op, ast.Gt):
            return a > b
        if isinstance(op, ast.GtE):
            return a >= b
        if isinstance(op, ast.Lt):
            return a < b
        if isinstance(op, ast.LtE):
            return a <= b
        if isinstance(op, ast.Eq):
            return a == b
        if isinstance(op, ast.NotEq):
            return a != b
        raise _NotAGate()

    try:
        at1, at2, at_big = truth(1), truth(2), truth(2 ** 30)
    except _NotAGate:
        return None
    if at2 != at_big or at1 == at2:
        return None  # not a clean single-vs-multi split
    return "body-multi" if at2 else "body-single"


class _NotAGate(Exception):
    pass


def _boolop_count_gate(test: ast.AST) -> Optional[str]:
    """A count gate embedded in an ``and`` chain refines the body context
    (e.g. ``if jax.process_count() > 1 and devices is None:``)."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for value in test.values:
            gate = _count_gate(value)
            if gate is not None:
                return gate
    return None


def _assigned_names(stmts: List[ast.stmt]) -> Set[str]:
    """Names (incl. self.attr pseudo-names) assigned anywhere in stmts."""
    names: Set[str] = set()
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    for leaf in ast.walk(t):
                        name = _target_name(leaf)
                        if name:
                            names.add(name)
    return names


def _target_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        if node.value.id == "self":
            return f"self.{node.attr}"
    return None


def _terminates(stmts: List[ast.stmt]) -> bool:
    """Does this branch unconditionally leave the function?"""
    for stmt in stmts:
        if isinstance(stmt, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
            return True
    return False


def _collective_tails(stmts: List[ast.stmt]) -> List[str]:
    """Ordered collective-call tails anywhere under stmts (incl. nested)."""
    tails: List[str] = []
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                tail = _tail(node.func)
                if tail in _COLLECTIVE_TAILS:
                    tails.append(tail)
    return tails


# --------------------------------------------------------------------------
# Function summaries (interprocedural within one module).


class _Summary:
    __slots__ = ("returns_taint", "propagates", "param_sink", "has_collective")

    def __init__(self) -> None:
        self.returns_taint: Optional[str] = None  # reason, if any
        self.propagates = False  # param taint can reach the return value
        self.param_sink = False  # param taint can reach a sink
        self.has_collective = False

    def merge(self, other: "_Summary") -> bool:
        changed = False
        if other.returns_taint and not self.returns_taint:
            self.returns_taint = other.returns_taint
            changed = True
        for attr in ("propagates", "param_sink", "has_collective"):
            if getattr(other, attr) and not getattr(self, attr):
                setattr(self, attr, True)
                changed = True
        return changed


class _Finding:
    __slots__ = ("rule", "lineno", "col", "message")

    def __init__(self, rule: str, lineno: int, col: int, message: str) -> None:
        self.rule = rule
        self.lineno = lineno
        self.col = col
        self.message = message


class _Analysis:
    """One shared pass over a module; rule classes read `findings`."""

    def __init__(self, tree: ast.Module) -> None:
        self.findings: List[_Finding] = []
        self._summaries: Dict[str, _Summary] = {}
        self._class_taint: Dict[str, Dict[str, str]] = {}
        self._flagged: Set[Tuple[str, int]] = set()
        if not _module_is_multihost(tree):
            return
        self._build_summaries(tree)
        self._class_attr_fixed_point(tree)
        self._emit(tree)

    # -- summaries ---------------------------------------------------------

    def _build_summaries(self, tree: ast.Module) -> None:
        funcs = _module_functions(tree)
        # Bottom-seed every module-local function BEFORE the first scan:
        # a call to a not-yet-summarized local function must read as
        # "bottom, refined later", not as an unknown library call, or the
        # conservative unknown-call assumption from iteration 1 sticks
        # forever (merge only widens).
        for name, _node in funcs:
            self._summaries.setdefault(name, _Summary())
        for _ in range(5):  # fixed point over local call graph
            changed = False
            for name, node in funcs:
                summary = _Summary()
                walker = _FlowWalker(
                    self, summary_mode=True, summary=summary, fn=node
                )
                walker.run()
                summary.has_collective = self._fn_has_collective(node, funcs)
                if self._summaries[name].merge(summary):
                    changed = True
            if not changed:
                break

    def _fn_has_collective(
        self, node: ast.AST, funcs: List[Tuple[str, ast.AST]]
    ) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                tail = _tail(sub.func)
                if tail in _COLLECTIVE_TAILS:
                    return True
                summary = self._summaries.get(tail or "")
                if summary is not None and summary.has_collective:
                    return True
        return False

    # -- class-level self.attr taint --------------------------------------

    def _class_attr_fixed_point(self, tree: ast.Module) -> None:
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            tainted: Dict[str, str] = {}
            for _ in range(3):
                changed = False
                for method in node.body:
                    if not isinstance(
                        method, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    walker = _FlowWalker(
                        self,
                        summary_mode=True,
                        summary=_Summary(),
                        fn=method,
                        seed_env=dict(tainted),
                        taint_params=False,
                    )
                    walker.run()
                    for name, reason in walker.self_attr_taint.items():
                        if name not in tainted:
                            tainted[name] = reason
                            changed = True
                if not changed:
                    break
            self._class_taint[node.name] = tainted

    # -- emission ----------------------------------------------------------

    def _emit(self, tree: ast.Module) -> None:
        # Module-level statements (rare, ctx unknown -> treated as multi).
        top = [
            s
            for s in tree.body
            if not isinstance(
                s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            )
        ]
        if top:
            _FlowWalker(self, body=top).run()
        for name, node, cls in _module_functions_with_class(tree):
            seed = dict(self._class_taint.get(cls, {})) if cls else {}
            _FlowWalker(self, fn=node, seed_env=seed).run()

    def flag(self, rule: str, lineno: int, col: int, message: str) -> None:
        key = (rule, lineno)
        if key in self._flagged:
            return
        self._flagged.add(key)
        self.findings.append(_Finding(rule, lineno, col, message))

    def summary_for(self, name: Optional[str]) -> Optional[_Summary]:
        if not name:
            return None
        return self._summaries.get(name)


def _module_is_multihost(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in {
            "process_index",
            "process_count",
            "multihost_utils",
        }:
            return True
        if isinstance(node, ast.Attribute) and node.attr in {
            "process_index",
            "process_count",
        }:
            return True
        if isinstance(node, ast.ImportFrom) and node.module and (
            "multihost_utils" in node.module
        ):
            return True
        if isinstance(node, (ast.Import,)):
            for alias in node.names:
                if "multihost_utils" in alias.name:
                    return True
    return False


def _module_functions(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    return [(n, f) for n, f, _c in _module_functions_with_class(tree)]


def _module_functions_with_class(
    tree: ast.Module,
) -> List[Tuple[str, ast.AST, Optional[str]]]:
    out: List[Tuple[str, ast.AST, Optional[str]]] = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node, None))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append((sub.name, sub, node.name))
    return out


# --------------------------------------------------------------------------
# The flow walker: one function (or the module top level) at a time.


class _FlowWalker:
    def __init__(
        self,
        analysis: _Analysis,
        fn: Optional[ast.AST] = None,
        body: Optional[List[ast.stmt]] = None,
        summary_mode: bool = False,
        summary: Optional[_Summary] = None,
        seed_env: Optional[Dict[str, str]] = None,
        taint_params: bool = True,
    ) -> None:
        self.analysis = analysis
        self.fn = fn
        self.body = body if body is not None else (fn.body if fn else [])
        self.summary_mode = summary_mode
        self.summary = summary
        self.env: Dict[str, str] = dict(seed_env or {})
        self.self_attr_taint: Dict[str, str] = {}
        # ctx: "multi" | "single" | None (unknown, treated as maybe-multi)
        self.ctx: Optional[str] = None
        self.control: List[str] = []  # reasons for enclosing tainted control
        self.events: List[Tuple[str, int, object]] = []  # (kind, lineno, data)
        if summary_mode and taint_params and fn is not None:
            for arg in _fn_args(fn):
                self.env[arg] = _PARAM

    # -- entry -------------------------------------------------------------

    def run(self) -> None:
        self._scan(self.body)
        if not self.summary_mode:
            self._check_stranded_raises()

    # -- taint evaluation --------------------------------------------------

    def taint_of(self, node: ast.AST) -> Optional[str]:
        """Reason this expression is process-divergent, or None."""
        if node is None:
            return None
        if isinstance(node, ast.Call):
            tail = _tail(node.func)
            if tail in _CONSENSUS_TAILS or tail in _BARRIER_TAILS:
                return None  # consensus launders taint
            reason = _source_reason(node)
            if reason is not None:
                return reason
            summary = self.analysis.summary_for(tail)
            arg_taint = self._args_taint(node)
            if summary is not None:
                out = None
                if summary.returns_taint:
                    out = summary.returns_taint
                if summary.propagates and arg_taint:
                    out = out or arg_taint
                return out
            # Constructors (capitalized by convention): building an object
            # from per-process config (a trace path, a pid) is normal and
            # the object's identity is not a cross-process value — only
            # specific fields are, and those are checked at the sinks
            # (e.g. Tracer(run_id=...)). Propagating object taint here
            # cascades through every method touching the object.
            if tail and tail[0].isupper():
                return None
            # Unknown call: conservatively propagate arg/receiver taint.
            recv = (
                self.taint_of(node.func.value)
                if isinstance(node.func, ast.Attribute)
                else None
            )
            return arg_taint or recv
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Attribute):
            name = _target_name(node)
            if name and name in self.env:
                return self.env[name]
            # Attribute access itself (e.g. d.process_index) is not a call
            # and not a source; propagate the base object's taint.
            return self.taint_of(node.value)
        if _is_env_subscript(node):
            return "environment variable os.environ[...]"
        if isinstance(node, ast.Subscript):
            return self.taint_of(node.value) or self.taint_of(node.slice)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                t = self.taint_of(v)
                if t:
                    return t
            return None
        if isinstance(node, ast.BinOp):
            return self.taint_of(node.left) or self.taint_of(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.taint_of(node.operand)
        if isinstance(node, ast.Compare):
            t = self.taint_of(node.left)
            if t:
                return t
            for c in node.comparators:
                t = self.taint_of(c)
                if t:
                    return t
            return None
        if isinstance(node, ast.IfExp):
            return (
                self.taint_of(node.test)
                or self.taint_of(node.body)
                or self.taint_of(node.orelse)
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                t = self.taint_of(e)
                if t:
                    return t
            return None
        if isinstance(node, ast.Dict):
            for e in list(node.keys) + list(node.values):
                t = self.taint_of(e)
                if t:
                    return t
            return None
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                t = self.taint_of(v)
                if t:
                    return t
            return None
        if isinstance(node, ast.FormattedValue):
            return self.taint_of(node.value)
        if isinstance(node, ast.Starred):
            return self.taint_of(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            t = None
            for gen in node.generators:
                t = t or self.taint_of(gen.iter)
            return t or self.taint_of(node.elt)
        if isinstance(node, ast.DictComp):
            t = None
            for gen in node.generators:
                t = t or self.taint_of(gen.iter)
            return t or self.taint_of(node.key) or self.taint_of(node.value)
        if isinstance(node, ast.Await):
            return self.taint_of(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.taint_of(node.value)
        return None

    def _args_taint(self, call: ast.Call) -> Optional[str]:
        for arg in call.args:
            t = self.taint_of(arg)
            if t:
                return t
        for kw in call.keywords:
            t = self.taint_of(kw.value)
            if t:
                return t
        return None

    # -- statement scan ----------------------------------------------------

    def _scan(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs are analyzed via their own summaries
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value)
            self._expr_effects(stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign([stmt.target], stmt.value)
                self._expr_effects(stmt.value)
            return
        if isinstance(stmt, ast.AugAssign):
            taint = self.taint_of(stmt.value) or self.taint_of(stmt.target)
            self._bind(stmt.target, taint)
            self._expr_effects(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self._expr_effects(stmt.value)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                taint = self.taint_of(stmt.value)
                self._expr_effects(stmt.value)
                if taint and self.summary is not None and self.ctx != "single":
                    if taint == _PARAM:
                        self.summary.propagates = True
                    else:
                        self.summary.returns_taint = (
                            self.summary.returns_taint or taint
                        )
            return
        if isinstance(stmt, ast.Raise):
            self.events.append(("raise", stmt.lineno, list(self.control)))
            if stmt.exc is not None:
                self._expr_effects(stmt.exc)
            return
        if isinstance(stmt, ast.If):
            self._if(stmt)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._for(stmt)
            return
        if isinstance(stmt, ast.While):
            self._while(stmt)
            return
        if isinstance(stmt, ast.Try):
            self._try(stmt)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr_effects(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(
                        item.optional_vars, self.taint_of(item.context_expr)
                    )
            self._scan(stmt.body)
            return
        if isinstance(stmt, ast.Assert):
            self._expr_effects(stmt.test)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                name = _target_name(t)
                if name:
                    self.env.pop(name, None)
            return
        # Import, Global, Pass, Break, Continue, etc.: no taint effect.

    def _assign(self, targets: List[ast.expr], value: ast.expr) -> None:
        # Tuple-to-Tuple assigns bind elementwise.
        for target in targets:
            if (
                isinstance(target, ast.Tuple)
                and isinstance(value, ast.Tuple)
                and len(target.elts) == len(value.elts)
            ):
                for t, v in zip(target.elts, value.elts):
                    self._bind(t, self.taint_of(v))
                continue
            self._bind(target, self.taint_of(value))

    def _bind(self, target: ast.expr, taint: Optional[str]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, taint)
            return
        if isinstance(target, ast.Subscript):
            # A tainted value stored into a container taints the container;
            # a clean store does not clean it.
            if taint:
                name = _target_name(target.value)
                if name:
                    self.env[name] = taint
            return
        if isinstance(target, ast.Starred):
            self._bind(target.value, taint)
            return
        name = _target_name(target)
        if name is None:
            return
        if taint:
            self.env[name] = taint
            # Class-level attr taint only matters in multi-process context;
            # a single-process tail (after a count-gate early return) may
            # hold per-process state without poisoning every other method.
            if name.startswith("self.") and self.ctx != "single":
                self.self_attr_taint[name] = taint
        else:
            self.env.pop(name, None)  # clean assignment kills taint

    # -- expression effects (collectives + sinks inside any expression) ----

    def _expr_effects(self, node: ast.expr) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            tail = _tail(sub.func)
            if tail in _COLLECTIVE_TAILS:
                self.events.append(("collective", sub.lineno, tail))
            else:
                summary = self.analysis.summary_for(tail)
                if summary is not None and summary.has_collective:
                    self.events.append(("collective", sub.lineno, tail))
            self._check_sink(sub, tail)

    def _check_sink(self, call: ast.Call, tail: Optional[str]) -> None:
        if self.ctx == "single":
            return
        arg_taint = self._args_taint(call)
        if not arg_taint:
            return
        if self.summary_mode:
            if arg_taint == _PARAM and self.summary is not None:
                if self._is_sink_call(call, tail):
                    self.summary.param_sink = True
            return
        if arg_taint == _PARAM:
            return
        sink = self._is_sink_call(call, tail)
        if not sink:
            return
        kind, reason = sink
        self._flag(
            "PSL007",
            call.lineno,
            call.col_offset,
            f"{kind} receives a process-divergent value "
            f"({reason or arg_taint}); this must be bit-identical on every "
            "host — launder it through broadcast_one_to_all/"
            "process_allgather first",
        )

    def _is_sink_call(
        self, call: ast.Call, tail: Optional[str]
    ) -> Optional[Tuple[str, Optional[str]]]:
        if tail and STEP_CALL_RE.search(tail):
            return f"traced step call {tail}()", None
        if tail in _RESTORE_TAILS:
            return f"checkpoint restore {tail}()", None
        summary = self.analysis.summary_for(tail)
        if summary is not None and summary.param_sink:
            return f"call into {tail}() (reaches a divergence-sensitive sink)", None
        if tail in _ARTIFACT_TAILS:
            return f"shared artifact write {tail}()", None
        if tail == "dump" and _dotted(call.func).startswith("json."):
            taint = self.taint_of(call.args[0]) if call.args else None
            if taint and taint != _PARAM:
                return "shared artifact write json.dump()", taint
            return None
        if tail in _RUN_IDENTITY_TAILS:
            # Only the run_id kwarg must agree across processes; other
            # args (e.g. a per-process trace path) are intentionally
            # process-local.
            for kw in call.keywords:
                if kw.arg == "run_id":
                    taint = self.taint_of(kw.value)
                    if taint and taint != _PARAM:
                        return f"run identity {tail}(run_id=...)", taint
            return None
        return None

    # -- control flow ------------------------------------------------------

    def _if(self, stmt: ast.If) -> None:
        self._expr_effects(stmt.test)
        gate = _count_gate(stmt.test)
        if gate is not None:
            self._exact_count_gate(stmt, gate)
            return
        embedded = _boolop_count_gate(stmt.test)
        taint = self.taint_of(stmt.test)
        body_tails = _collective_tails(stmt.body)
        else_tails = _collective_tails(stmt.orelse)
        if taint and self.ctx != "single":
            if bool(body_tails) != bool(else_tails):
                self._flag(
                    "PSL006",
                    stmt.lineno,
                    stmt.col_offset,
                    f"branch on process-divergent state ({taint}) runs a "
                    "collective on one path but not the other — processes "
                    "taking different paths strand each other at the "
                    "rendezvous; hoist the collective out of the branch or "
                    "reach consensus first",
                )
            elif body_tails and else_tails and body_tails != else_tails:
                self._flag(
                    "PSL008",
                    stmt.lineno,
                    stmt.col_offset,
                    f"branch on process-divergent state ({taint}) orders "
                    f"collectives differently per path ({body_tails} vs "
                    f"{else_tails}) — processes taking different paths "
                    "rendezvous cross-matched and deadlock",
                )
        # Scan both branches, then join.
        before = dict(self.env)
        before_ctx = self.ctx
        pushed = bool(taint) and self.ctx != "single"
        if pushed:
            self.control.append(taint)
        if embedded == "body-multi":
            self.ctx = "multi" if before_ctx != "single" else "single"
        elif embedded == "body-single":
            self.ctx = "single"
        self._scan(stmt.body)
        body_env = self.env
        self.env = dict(before)
        self.ctx = before_ctx
        self._scan(stmt.orelse)
        else_env = self.env
        if pushed:
            self.control.pop()
        self.ctx = before_ctx
        # May-union join + implicit flow: anything assigned in either
        # branch of a tainted condition is control-dependent on it,
        # regardless of the branch-local value's own taint.
        joined: Dict[str, str] = {}
        for env in (body_env, else_env):
            for k, v in env.items():
                joined.setdefault(k, v)
        if taint and self.ctx != "single":
            for name in _assigned_names(stmt.body) | _assigned_names(stmt.orelse):
                if joined.get(name) != _PARAM:
                    joined[name] = taint
                if name.startswith("self."):
                    self.self_attr_taint.setdefault(name, taint)
        self.env = joined

    def _exact_count_gate(self, stmt: ast.If, gate: str) -> None:
        """``if jax.process_count() <cmp> k:`` — a deployment constant.

        The multi side's env is authoritative (divergence only matters
        when there are multiple processes); a terminating side flips the
        ambient ctx for the remainder of the function.
        """
        before = dict(self.env)
        before_ctx = self.ctx
        multi_body = gate == "body-multi"

        # body side
        self.ctx = ("multi" if multi_body else "single") if before_ctx != "single" else "single"
        self._scan(stmt.body)
        body_env = self.env
        body_terminates = _terminates(stmt.body)

        # else side
        self.env = dict(before)
        self.ctx = ("single" if multi_body else "multi") if before_ctx != "single" else "single"
        self._scan(stmt.orelse)
        else_env = self.env
        else_terminates = _terminates(stmt.orelse) if stmt.orelse else False

        multi_env = body_env if multi_body else else_env
        single_env = else_env if multi_body else body_env
        multi_terminates = body_terminates if multi_body else else_terminates
        single_terminates = else_terminates if multi_body else body_terminates

        if multi_terminates and not single_terminates:
            self.env = single_env
            self.ctx = "single"
        elif single_terminates and not multi_terminates:
            self.env = multi_env
            self.ctx = "multi" if before_ctx != "single" else "single"
        else:
            self.env = multi_env
            self.ctx = before_ctx

    def _for(self, stmt: ast.stmt) -> None:
        self._expr_effects(stmt.iter)
        taint = self.taint_of(stmt.iter)
        self._bind(stmt.target, taint)
        if taint and self.ctx != "single":
            tails = _collective_tails(stmt.body)
            if tails:
                self._flag(
                    "PSL006",
                    stmt.lineno,
                    stmt.col_offset,
                    f"loop over process-divergent state ({taint}) contains a "
                    f"collective ({tails[0]}) — iteration counts differ per "
                    "process, so some processes wait at a rendezvous others "
                    "never reach; agree on the iteration space first",
                )
        pushed = bool(taint) and self.ctx != "single"
        if pushed:
            self.control.append(taint)
        # Two passes propagate taint around the back edge; the emission
        # dedup set keeps findings single-shot.
        self._scan(stmt.body)
        self._scan(stmt.body)
        if pushed:
            self.control.pop()
        self._scan(stmt.orelse)

    def _while(self, stmt: ast.While) -> None:
        self._expr_effects(stmt.test)

        def check_once() -> None:
            taint = self.taint_of(stmt.test)
            if taint and self.ctx != "single":
                tails = _collective_tails(stmt.body)
                if tails:
                    self._flag(
                        "PSL006",
                        stmt.lineno,
                        stmt.col_offset,
                        f"while-loop guarded by process-divergent state "
                        f"({taint}) contains a collective ({tails[0]}) — "
                        "iteration counts differ per process, stranding the "
                        "rendezvous; use a consensus (all-reduce the "
                        "predicate) loop guard",
                    )

        check_once()
        taint = self.taint_of(stmt.test)
        pushed = bool(taint) and self.ctx != "single"
        if pushed:
            self.control.append(taint)
        self._scan(stmt.body)
        check_once()  # back edge may have tainted the predicate
        self._scan(stmt.body)
        taint2 = self.taint_of(stmt.test)
        if taint2 and not taint and self.ctx != "single":
            tails = _collective_tails(stmt.body)
            if tails:
                self._flag(
                    "PSL006",
                    stmt.lineno,
                    stmt.col_offset,
                    f"while-loop guarded by process-divergent state "
                    f"({taint2}) contains a collective ({tails[0]}) — "
                    "iteration counts differ per process, stranding the "
                    "rendezvous; use a consensus (all-reduce the "
                    "predicate) loop guard",
                )
        if pushed:
            self.control.pop()
        self._scan(stmt.orelse)

    def _try(self, stmt: ast.Try) -> None:
        before = dict(self.env)
        self._scan(stmt.body)
        after_body = dict(self.env)
        handler_envs: List[Dict[str, str]] = []
        for handler in stmt.handlers:
            self.env = dict(before)
            if handler.name:
                self.env[handler.name] = (
                    "caught-exception state (exceptions are per-process)"
                )
            self._scan(handler.body)
            handler_envs.append(self.env)
        # May-union join.
        joined = dict(after_body)
        for env in handler_envs:
            for k, v in env.items():
                joined.setdefault(k, v)
        self.env = joined
        self._scan(stmt.orelse)
        self._scan(stmt.finalbody)

    # -- PSL006(c): raise under divergent control, collective later --------

    def _check_stranded_raises(self) -> None:
        for i, (kind, lineno, data) in enumerate(self.events):
            if kind != "raise" or not data:
                continue
            later = [
                e for e in self.events[i + 1 :] if e[0] == "collective"
            ]
            if later:
                self._flag(
                    "PSL006",
                    lineno,
                    0,
                    f"raise under process-divergent control ({data[0]}) with "
                    f"a later collective ({later[0][2]} at line "
                    f"{later[0][1]}) still expecting this process — the "
                    "other processes block forever at the rendezvous; hold "
                    "the error, reach the collective, re-raise after "
                    "(see checkpoint.save_checkpoint for the pattern)",
                )

    def _flag(self, rule: str, lineno: int, col: int, message: str) -> None:
        if self.summary_mode:
            return
        self.analysis.flag(rule, lineno, col, message)


def _fn_args(fn: ast.AST) -> List[str]:
    args = fn.args
    names = [
        a.arg
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        )
        if a.arg != "self"
    ]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


# --------------------------------------------------------------------------
# Rule classes (registered in rules.RULES; share one analysis per tree).


def _shared_analysis(tree: ast.Module) -> _Analysis:
    cached = getattr(tree, "_psdiverge", None)
    if cached is None:
        cached = _Analysis(tree)
        tree._psdiverge = cached
    return cached


class _DivergeRuleBase:
    rule_id = ""

    def check(
        self, tree: ast.Module, path: str, axes, donors=None
    ) -> Iterator[Tuple[int, int, str]]:
        for f in _shared_analysis(tree).findings:
            if f.rule == self.rule_id:
                yield f.lineno, f.col, f.message


class DivergentGuardRule(_DivergeRuleBase):
    """PSL006: process-divergent state guards/strands a collective."""

    rule_id = "PSL006"


class DivergentTracedRule(_DivergeRuleBase):
    """PSL007: process-divergent value reaches a must-be-identical sink."""

    rule_id = "PSL007"


class DivergentOrderRule(_DivergeRuleBase):
    """PSL008: tainted branch orders collectives differently per path."""

    rule_id = "PSL008"


# --------------------------------------------------------------------------
# PSC110 companion: the package's consensus-point inventory.

_INVENTORY_CACHE: Optional[Dict[str, Tuple[str, int]]] = None


def consensus_inventory(package_root: Optional[str] = None) -> Dict[str, Tuple[str, int]]:
    """Map of consensus-shaped functions in the package.

    Keys are package-relative dotted paths (``trainer.Trainer._count_consensus``);
    values are (file path, line number). A function is consensus-shaped when
    its body calls a consensus collective (broadcast_one_to_all /
    process_allgather) at some line L and returns at a line >= L — i.e. its
    result can carry the agreed value back to every caller.
    """
    global _INVENTORY_CACHE
    if package_root is None and _INVENTORY_CACHE is not None:
        return _INVENTORY_CACHE
    root = package_root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    inventory: Dict[str, Tuple[str, int]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if not d.startswith(("_", "."))]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            fpath = os.path.join(dirpath, fname)
            rel = os.path.relpath(fpath, root)
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            try:
                with open(fpath, "r", encoding="utf-8") as fh:
                    tree = ast.parse(fh.read())
            except (OSError, SyntaxError):
                continue
            for name, node, cls in _module_functions_with_class(tree):
                qual = f"{mod}.{cls}.{name}" if cls else f"{mod}.{name}"
                if _is_consensus_shaped(node):
                    inventory[qual] = (fpath, node.lineno)
    if package_root is None:
        _INVENTORY_CACHE = inventory
    return inventory


def _is_consensus_shaped(fn: ast.AST) -> bool:
    consensus_line = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _tail(node.func) in _CONSENSUS_TAILS:
            if consensus_line is None or node.lineno < consensus_line:
                consensus_line = node.lineno
    if consensus_line is None:
        return False
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.lineno >= consensus_line:
            return True
    return False
