"""Mesh-axis constant discovery for PSL001.

The source of truth for axis names is the set of module-level
``<NAME>_AXIS = "<literal>"`` assignments in the ``parallel/`` package
(``parallel/mesh.py`` declares WORKER_AXIS/DCN_AXIS; tp/pp/moe/
ring_attention declare theirs next to the scheme they belong to). The
linter re-reads those declarations from source rather than importing the
package, so it runs anywhere python runs — no jax install required.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, Tuple

# fallback when no parallel/ package is reachable from the linted paths
# (e.g. linting a single file copied out of the tree). Mirrors
# parallel/{mesh,tp,pp,moe,ring_attention}.py.
DEFAULT_AXES: Dict[str, str] = {
    "workers": "WORKER_AXIS",
    "dcn": "DCN_AXIS",
    "model": "TP_AXIS",
    "stage": "PP_AXIS",
    "expert": "EP_AXIS",
    "seq": "SEQ_AXIS",
}

_AXIS_SUFFIX = "_AXIS"


def _axes_in_source(src: str) -> Dict[str, str]:
    """Top-level ``X_AXIS = "name"`` assignments of one module."""
    out: Dict[str, str] = {}
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return out
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Constant) and isinstance(node.value.value, str)):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id.endswith(_AXIS_SUFFIX):
                out[node.value.value] = tgt.id
    return out


def _candidate_axis_dirs(paths: Iterable[str]) -> Iterable[str]:
    seen = set()
    for p in paths:
        d = os.path.abspath(p if os.path.isdir(p) else os.path.dirname(p) or ".")
        for _ in range(5):  # walk up a few levels looking for parallel/
            for cand in (
                os.path.join(d, "parallel"),
                os.path.join(d, "ps_pytorch_tpu", "parallel"),
            ):
                if cand not in seen and os.path.isfile(os.path.join(cand, "mesh.py")):
                    seen.add(cand)
                    yield cand
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent


def discover_axes(paths: Iterable[str]) -> Tuple[Dict[str, str], str]:
    """Map axis value -> constant name, plus a human-readable provenance.

    Declared constants win over the built-in defaults; defaults are kept
    as a floor so PSL001 still distinguishes "known axis spelled as a
    literal" from "axis name that exists nowhere" on partial checkouts.
    """
    axes = dict(DEFAULT_AXES)
    source = "builtin defaults"
    for d in _candidate_axis_dirs(paths):
        found: Dict[str, str] = {}
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py"):
                continue
            try:
                with open(os.path.join(d, fname), "r", encoding="utf-8") as f:
                    found.update(_axes_in_source(f.read()))
            except OSError:
                continue
        if found:
            axes.update(found)
            source = os.path.relpath(d)
            break
    return axes, source
