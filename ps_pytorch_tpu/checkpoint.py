"""Step-tagged single-writer checkpoints with real resume.

Capability parity with the reference's checkpoint story — `torch.save` of a
state_dict to `train_dir/model_step_{N}` every eval_freq steps
(/root/reference/src/sync_replicas_master_nn.py:264-270,194-196;
distributed_worker.py:301-307) consumed by a polling evaluator
(distributed_evaluator.py:79-88) — minus its two defects, deliberately:

- The reference has EVERY worker write the same NFS path for ResNet/VGG (an
  write race, distributed_worker.py:175-177). Here exactly one host process
  writes, atomically (tmp file + os.replace), so a polling reader can never
  observe a torn file.
- The reference cannot resume (training always restarts at step 1,
  sync_replicas_master_nn.py:102). `latest_step` + `load_checkpoint` make
  resume a first-class operation (see trainer.PSTrainer.resume).

Format: flax.serialization msgpack bytes of the full PSTrainState (params,
optimizer state, BN stats, step) — accelerator-agnostic host arrays —
optionally wrapped in the native C++ codec (ops/codec.py, the Blosc-role
equivalent: reference compression.py w_compress wraps checkpointed weights
too). Compressed files carry a 'PSCK' magic; load auto-detects either form.
"""

from __future__ import annotations

import os
import re
import time
from typing import Iterator, Optional

import jax
from flax import serialization

CKPT_RE = re.compile(r"^model_step_(\d+)$")
COMPRESSED_MAGIC = b"PSCK"


def checkpoint_path(model_dir: str, step: int) -> str:
    # name parity with the reference's _generate_model_path
    return os.path.join(model_dir, f"model_step_{step}")


def _gather_host_state(state):
    """Bring `state` to full host arrays on every process.

    Single-process: plain device_get. Multi-host (process_count > 1):
    ONLY leaves that are jax.Arrays with non-addressable shards get the
    multihost_utils gather (a collective — every process must call this,
    and every process holds the same pytree structure, so the per-leaf
    collectives line up). Host-local leaves (numpy arrays, scalars,
    metadata strings) pass through untouched — handing them to
    process_allgather would stack/concat them per-process. The writer
    side then keeps exactly one process writing (see save_checkpoint)."""
    if jax.process_count() <= 1:
        return jax.device_get(state)
    from jax.experimental import multihost_utils

    def leaf(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return multihost_utils.process_allgather(x, tiled=True)
        if isinstance(x, jax.Array):
            return jax.device_get(x)
        return x

    return jax.tree.map(leaf, state)


def save_checkpoint(state, model_dir: str, step: int, compress: bool = False) -> str:
    """Atomically write `state` (any flax-serializable pytree) for `step`.

    Multi-host: collective (all processes must call it — the gather is a
    collective op); only process 0 writes the file, preserving the
    single-writer guarantee, and a barrier after the write means the
    write has COMPLETED before any process returns. The path is on
    process 0's filesystem: reading it from other processes (e.g.
    --resume after preemption) requires `model_dir` to be on storage all
    hosts share — a gcsfuse bucket (tools/tpu_cluster.py mount) or NFS,
    exactly like the reference's NFS train_dir (README.md:23)."""
    host_state = _gather_host_state(state)
    path = checkpoint_path(model_dir, step)
    if jax.process_index() == 0:
        _write_host_state(host_state, model_dir, step, compress)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_save_{step}")
    return path


def _write_host_state(state, model_dir: str, step: int, compress: bool) -> str:
    """Host-side half of a save (state already device_get). Runs on the
    async writer thread; everything here is pure host CPU + disk."""
    os.makedirs(model_dir, exist_ok=True)
    path = checkpoint_path(model_dir, step)
    data = serialization.to_bytes(state)
    if compress:
        from .ops import codec

        # itemsize 4: the payload is dominated by f32 leaves, so a 4-byte
        # shuffle feeds the LZ stage well; correctness is itemsize-agnostic
        data = COMPRESSED_MAGIC + codec.compress_bytes(data, itemsize=4)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


class AsyncCheckpointer:
    """Overlap checkpoint serialization + disk IO with training.

    The device->host transfer happens on the caller's thread (it must
    observe a consistent step boundary); msgpack serialization, codec
    compression, and the atomic write run on one background thread, so the
    train loop never blocks on disk. `wait()` drains pending writes —
    Trainer.train calls it before returning, keeping the reference's
    synchronous visible behavior (a checkpoint exists when training is
    done) without its per-step stall. Single writer by construction
    (one thread), preserving the no-torn-reads guarantee."""

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt")
        self._pending = None

    def save(self, state, model_dir: str, step: int, compress: bool = False):
        if jax.process_count() > 1:
            # multi-host: degrade to the synchronous collective save — its
            # barrier gives every process a durable-write guarantee, which
            # an async submit on process 0 alone cannot (the other
            # processes' wait() would be a no-op on an unwritten file)
            save_checkpoint(state, model_dir, step, compress)
            return
        host_state = _gather_host_state(state)
        self.wait()  # keep at most one write in flight
        self._pending = self._pool.submit(
            _write_host_state, host_state, model_dir, step, compress
        )

    def wait(self):
        if self._pending is not None:
            self._pending.result()
            self._pending = None


def _read_bytes(model_dir: str, step: int) -> bytes:
    with open(checkpoint_path(model_dir, step), "rb") as f:
        data = f.read()
    if data[:4] == COMPRESSED_MAGIC:
        from .ops import codec

        data = codec.decompress_bytes(data[4:])
    return data


def load_checkpoint(target, model_dir: str, step: int):
    """Load step N into the structure of `target` (an initialized state).
    Auto-detects codec-compressed checkpoints.

    Forward-compat: a top-level field that exists in `target` with value
    None but is absent from the stored dict (a field added to the state
    AFTER the checkpoint was written, e.g. PSTrainState.comm_state) is
    filled with None instead of hard-erroring — old checkpoints stay
    resumable as long as the new feature is off. A non-None target field
    still errors loudly (its state genuinely cannot be reconstructed).
    The converse mismatch — the checkpoint CARRIES state for a feature
    the target has off (stored comm_state, target None) — also errors
    loudly: flax would otherwise pass the raw arrays through a None
    target silently, and dropping accumulated EF residuals would quietly
    change the training math."""
    raw = serialization.msgpack_restore(_read_bytes(model_dir, step))
    tgt_dict = serialization.to_state_dict(target)
    if isinstance(raw, dict) and isinstance(tgt_dict, dict):
        for k, v in tgt_dict.items():
            if k not in raw and v is None:
                raw[k] = None
            elif v is None and raw.get(k) is not None:
                raise ValueError(
                    f"checkpoint step {step} carries state for field {k!r} "
                    f"but the target state has it disabled (None). Enable "
                    f"the matching feature (e.g. --error-feedback for "
                    f"comm_state) to resume this checkpoint, or rebuild it "
                    f"without that state."
                )
    return serialization.from_state_dict(target, raw)


def restore_sharded(target, model_dir: str, step: int, mesh, specs):
    """Load step N and place every leaf on `mesh` with its PartitionSpec
    from `specs` (a pytree of PartitionSpecs, e.g. parallel.tp_param_specs
    output or an opt_state_specs tree).

    save_checkpoint gathers sharded arrays to full host arrays
    (device_get single-process; multihost_utils.process_allgather when
    process_count > 1), so a checkpoint written from a tp/pp/moe-sharded
    state restores onto ANY mesh shape whose specs divide the shapes —
    resharding across different device counts (and host counts) is free.
    """
    from .parallel.mesh import place_on_mesh

    return place_on_mesh(load_checkpoint(target, model_dir, step), mesh, specs)


def load_checkpoint_raw(model_dir: str, step: int) -> dict:
    """Load step N as raw nested dicts, no target structure required.

    This is what lets the evaluator stay ignorant of the trainer's optimizer
    and placement config: it only consumes params/batch_stats/step and never
    needs to reconstruct the opt_state pytree (whose structure varies by
    --optimizer/--opt-placement)."""
    return serialization.msgpack_restore(_read_bytes(model_dir, step))


def available_steps(model_dir: str):
    if not os.path.isdir(model_dir):
        return []
    steps = []
    for name in os.listdir(model_dir):
        m = CKPT_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(model_dir: str) -> Optional[int]:
    steps = available_steps(model_dir)
    return steps[-1] if steps else None


def poll_checkpoints(
    model_dir: str,
    start_after: int = 0,
    interval_s: float = 10.0,
    timeout_s: Optional[float] = None,
) -> Iterator[int]:
    """Yield new checkpoint steps as they appear (evaluator's consume loop;
    parity: distributed_evaluator.py:79-88 polls every 10s). Stops when
    `timeout_s` elapses with no new checkpoint (None = poll forever)."""
    seen = start_after
    waited = 0.0
    while True:
        fresh = [s for s in available_steps(model_dir) if s > seen]
        if fresh:
            waited = 0.0
            for s in fresh:
                seen = s
                yield s
            continue
        if timeout_s is not None and waited >= timeout_s:
            return
        time.sleep(interval_s)
        waited += interval_s
