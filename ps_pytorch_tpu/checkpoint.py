"""Step-tagged single-writer checkpoints with real resume.

Capability parity with the reference's checkpoint story — `torch.save` of a
state_dict to `train_dir/model_step_{N}` every eval_freq steps
(/root/reference/src/sync_replicas_master_nn.py:264-270,194-196;
distributed_worker.py:301-307) consumed by a polling evaluator
(distributed_evaluator.py:79-88) — minus its two defects, deliberately:

- The reference has EVERY worker write the same NFS path for ResNet/VGG (an
  write race, distributed_worker.py:175-177). Here exactly one host process
  writes, atomically (tmp file + os.replace), so a polling reader can never
  observe a torn file.
- The reference cannot resume (training always restarts at step 1,
  sync_replicas_master_nn.py:102). `latest_step` + `load_checkpoint` make
  resume a first-class operation (see trainer.PSTrainer.resume).

Format: flax.serialization msgpack bytes of the full PSTrainState (params,
optimizer state, BN stats, step) — accelerator-agnostic host arrays —
optionally wrapped in the native C++ codec (ops/codec.py, the Blosc-role
equivalent: reference compression.py w_compress wraps checkpointed weights
too). Compressed files carry a 'PSCK' magic; load auto-detects either form.

Layout neutrality: checkpoints are TREE-SHAPED at this boundary even when
the live state is flat (PSConfig.state_layout="flat" — params/moments as
padded flat vectors). parallel.buckets.FlatVector registers serialization
handlers that convert at the edge, so a flat-state run's checkpoint is
byte-compatible with a tree-state run's, pre-flat-state checkpoints load
unchanged, and nothing in THIS module knows which layout produced a file.

Integrity (resilience layer): every file ends with an 8-byte CRC32
trailer — b'PSC1' + crc32(everything before it) — written inside the same
atomic write, so on-disk corruption (bit rot, torn NFS replication, a
fault-injected truncation) is detected at read time instead of surfacing
as a cryptic msgpack error mid-resume. Trailer-less files written before
this layer existed still load (the trailer is recognized, never
required), so existing runs/ artifacts and in-flight --resume dirs stay
valid. `latest_valid_step` + `quarantine_checkpoint` turn a corrupt
newest checkpoint into a fall-back instead of a crash, and all file I/O
retries transient OSErrors with bounded exponential backoff
(resilience/retry.py — the shared-NFS evaluator is where transient EIO
lives).
"""

from __future__ import annotations

import logging
import os
import re
import struct
import time
import zlib
from typing import Iterator, Optional

import jax
from flax import serialization

from .resilience import retry_io
from .resilience.guard import reconcile_guard_state

logger = logging.getLogger("ps_pytorch_tpu")

CKPT_RE = re.compile(r"^model_step_(\d+)$")
COMPRESSED_MAGIC = b"PSCK"
# integrity trailer: magic + little-endian crc32 of all preceding bytes
TRAILER_MAGIC = b"PSC1"
TRAILER_LEN = len(TRAILER_MAGIC) + 4
# suffix a quarantined (corrupt) checkpoint is renamed to; CKPT_RE no
# longer matches it, so available_steps/resume stop seeing it
QUARANTINE_SUFFIX = ".corrupt"
# top-level PSTrainState fields that are observability, not math: when a
# checkpoint predates the field, loading resets it to the target's fresh
# value instead of erroring (unlike comm_state, whose silent loss would
# change the training trajectory — see load_checkpoint). Each maps to the
# owning module's reconcile hook — (stored_dict, fresh_dict) -> merged —
# so this layer never learns the field's internals
RESETTABLE_FIELDS = {"guard_state": reconcile_guard_state}


class CheckpointError(Exception):
    """Base for checkpoint integrity/IO failures."""


class CheckpointCorruptError(CheckpointError):
    """The on-disk bytes are damaged (CRC mismatch, truncation, codec or
    msgpack failure) — retrying will not help; quarantine + fall back."""


class CheckpointWriteError(CheckpointError):
    """A (possibly background) checkpoint write failed; carries the step
    and path so the failure is actionable when it surfaces at wait()."""

    def __init__(self, step: int, path: str, cause: BaseException):
        super().__init__(
            f"checkpoint write failed for step {step} at {path}: {cause}"
        )
        self.step = step
        self.path = path


def checkpoint_path(model_dir: str, step: int) -> str:
    # name parity with the reference's _generate_model_path
    return os.path.join(model_dir, f"model_step_{step}")


def _gather_host_state(state):
    """Bring `state` to full host arrays on every process.

    Single-process: plain device_get. Multi-host (process_count > 1):
    ONLY leaves that are jax.Arrays with non-addressable shards get the
    multihost_utils gather (a collective — every process must call this,
    and every process holds the same pytree structure, so the per-leaf
    collectives line up). Host-local leaves (numpy arrays, scalars,
    metadata strings) pass through untouched — handing them to
    process_allgather would stack/concat them per-process. The writer
    side then keeps exactly one process writing (see save_checkpoint)."""
    if jax.process_count() <= 1:
        return jax.device_get(state)
    from jax.experimental import multihost_utils

    def leaf(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return multihost_utils.process_allgather(x, tiled=True)
        if isinstance(x, jax.Array):
            return jax.device_get(x)
        return x

    return jax.tree.map(leaf, state)


def save_checkpoint(state, model_dir: str, step: int, compress: bool = False,
                    faults=None) -> str:
    """Atomically write `state` (any flax-serializable pytree) for `step`.

    Multi-host: collective (all processes must call it — the gather is a
    collective op); only process 0 writes the file, preserving the
    single-writer guarantee, and a barrier after the write means the
    write has COMPLETED before any process returns. A write FAILURE on
    process 0 must reach that barrier too — raising before it would
    strand processes 1..N-1 in the collective forever — so the error is
    held across an ok/fail broadcast and then raised on every process
    (a failed checkpoint is a collective outcome, not a process-0
    secret). The path is on process 0's filesystem: reading it from
    other processes (e.g. --resume after preemption) requires
    `model_dir` to be on storage all hosts share — a gcsfuse bucket
    (tools/tpu_cluster.py mount) or NFS, exactly like the reference's
    NFS train_dir (README.md:23).

    This hold-then-broadcast shape is the sanctioned error idiom psdiverge
    (PSL006, ARCHITECTURE §7b) checks against: raising inside the
    ``process_index() == 0`` branch BEFORE the barrier is exactly the
    stranded-collective bug this function once shipped, and is now a
    regression fixture in tests/test_lint.py."""
    host_state = _gather_host_state(state)
    path = checkpoint_path(model_dir, step)
    err = None
    if jax.process_index() == 0:
        try:
            _write_host_state(host_state, model_dir, step, compress,
                              faults=faults)
        except BaseException as e:
            err = e
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        ok = multihost_utils.broadcast_one_to_all(
            np.int32(0 if err is not None else 1)
        )
        multihost_utils.sync_global_devices(f"ckpt_save_{step}")
        if err is None and not int(ok):
            raise CheckpointWriteError(
                step, path,
                RuntimeError("checkpoint write failed on process 0"),
            )
    if err is not None:
        raise err
    return path


def _write_host_state(state, model_dir: str, step: int, compress: bool,
                      faults=None) -> str:
    """Host-side half of a save (state already device_get). Runs on the
    async writer thread; everything here is pure host CPU + disk.

    The CRC trailer is computed over the final on-disk bytes (after the
    codec, if any) and written inside the same atomic tmp+replace, so a
    reader can never observe a trailer that does not match its payload.
    Disk I/O retries transient OSErrors; an injected write fault
    (resilience.FaultPlan.ckpt_write_fail) fails every attempt so the
    failure genuinely surfaces."""
    os.makedirs(model_dir, exist_ok=True)
    path = checkpoint_path(model_dir, step)
    data = serialization.to_bytes(state)
    if compress:
        from .ops import codec

        # itemsize 4: the payload is dominated by f32 leaves, so a 4-byte
        # shuffle feeds the LZ stage well; correctness is itemsize-agnostic
        data = COMPRESSED_MAGIC + codec.compress_bytes(data, itemsize=4)
    data += TRAILER_MAGIC + struct.pack("<I", zlib.crc32(data))

    def write():
        if faults is not None:
            faults.maybe_fail_ckpt_write(step)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    retry_io(write, desc=f"checkpoint write step {step}")
    if faults is not None:
        faults.maybe_corrupt_ckpt(path, step)
    return path


class AsyncCheckpointer:
    """Overlap checkpoint serialization + disk IO with training.

    The device->host transfer happens on the caller's thread (it must
    observe a consistent step boundary); msgpack serialization, codec
    compression, and the atomic write run on one background thread, so the
    train loop never blocks on disk. `wait()` drains pending writes —
    Trainer.train calls it before returning, keeping the reference's
    synchronous visible behavior (a checkpoint exists when training is
    done) without its per-step stall. Single writer by construction
    (one thread), preserving the no-torn-reads guarantee.

    Failure handling: a background write that fails is logged — and
    reported through `event_sink` as a structured ``ckpt_write_failed``
    record — AT FAILURE TIME on the writer thread, then re-raised from
    the next `save()`/`wait()` wrapped in CheckpointWriteError carrying
    the step and path it was writing (the bare future exception said
    neither)."""

    def __init__(self, event_sink=None, faults=None):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt")
        self._pending = None
        self._event_sink = event_sink
        self._faults = faults

    def save(self, state, model_dir: str, step: int, compress: bool = False):
        if jax.process_count() > 1:
            # multi-host: degrade to the synchronous collective save — its
            # barrier gives every process a durable-write guarantee, which
            # an async submit on process 0 alone cannot (the other
            # processes' wait() would be a no-op on an unwritten file).
            # Same failure wrapper as the async path: the event + the
            # step/path context are promised unconditionally.
            self._logged(
                lambda: save_checkpoint(state, model_dir, step, compress,
                                        faults=self._faults),
                model_dir, step,
            )
            return
        host_state = _gather_host_state(state)
        self.wait()  # keep at most one write in flight
        self._pending = self._pool.submit(
            self._write_logged, host_state, model_dir, step, compress
        )

    def _write_logged(self, host_state, model_dir: str, step: int,
                      compress: bool):
        return self._logged(
            lambda: _write_host_state(
                host_state, model_dir, step, compress, faults=self._faults
            ),
            model_dir, step,
        )

    def _logged(self, write, model_dir: str, step: int):
        path = checkpoint_path(model_dir, step)
        try:
            return write()
        except CheckpointWriteError:
            # already wrapped: save_checkpoint's collective-outcome raise
            # on processes 1..N-1. Process 0 owns the log line and the
            # structured event — re-wrapping would nest the message and
            # duplicate the JSONL record once per process.
            raise
        except Exception as e:
            # report NOW (on the async path: the writer thread), not at
            # the next wait() — by then the loop is steps ahead and the
            # context is gone. Exception, not BaseException: a
            # KeyboardInterrupt landing in the synchronous multi-host
            # save must not masquerade as a ckpt_write_failed event.
            logger.error(
                "checkpoint write failed (step %d, %s): %s",
                step, path, e,
            )
            if self._event_sink is not None:
                try:
                    self._event_sink({
                        "kind": "ckpt_write_failed",
                        "step": step,
                        "path": path,
                        "error": str(e),
                    })
                except Exception:
                    logger.exception("ckpt_write_failed event sink raised")
            raise CheckpointWriteError(step, path, e) from e

    def wait(self):
        if self._pending is not None:
            try:
                self._pending.result()
            finally:
                self._pending = None


def _read_payload(model_dir: str, step: int, read_attempts: int = 3):
    """Read one checkpoint file and verify+strip its CRC trailer.

    Returns (payload, had_trailer); the payload is still codec-compressed
    if it was written that way. A file without the trailer is a
    pre-resilience checkpoint and is accepted as-is — the trailer is
    detected, never demanded, so seed-era runs/ artifacts keep loading."""
    path = checkpoint_path(model_dir, step)

    def read():
        with open(path, "rb") as f:
            return f.read()

    data = retry_io(
        read, desc=f"checkpoint read step {step}", attempts=read_attempts
    )
    if len(data) >= TRAILER_LEN and data[-TRAILER_LEN:-4] == TRAILER_MAGIC:
        payload, (crc,) = data[:-TRAILER_LEN], struct.unpack(
            "<I", data[-4:]
        )
        if zlib.crc32(payload) != crc:
            raise CheckpointCorruptError(
                f"CRC mismatch in {path}: stored {crc:#010x}, computed "
                f"{zlib.crc32(payload):#010x} — the file is damaged"
            )
        return payload, True
    return data, False


def _decode_payload(data: bytes, path: str):
    """Trailer-stripped bytes -> raw nested dicts (codec, then msgpack),
    with decode failures (the signature of a damaged trailer-less file)
    classified as corruption. Structure mismatches AFTER a clean restore
    are config errors, not corruption, and propagate unchanged from the
    from_state_dict side."""
    if data[:4] == COMPRESSED_MAGIC:
        from .ops import codec

        try:
            data = codec.decompress_bytes(data[4:])
        except Exception as e:
            raise CheckpointCorruptError(
                f"codec decompression failed for {path}: {e}"
            ) from e
    try:
        return serialization.msgpack_restore(data)
    except (ValueError, EOFError, struct.error) as e:
        raise CheckpointCorruptError(
            f"cannot deserialize {path}: {e}"
        ) from e
    except Exception as e:
        # msgpack's unpack exceptions don't all subclass ValueError;
        # anything raised from its module is a damaged-bytes signature
        if type(e).__module__.partition(".")[0] == "msgpack":
            raise CheckpointCorruptError(
                f"cannot deserialize {path}: {e}"
            ) from e
        raise


def _restore_raw(model_dir: str, step: int, read_attempts: int = 3):
    data, _ = _read_payload(model_dir, step, read_attempts)
    return _decode_payload(data, checkpoint_path(model_dir, step))


def verify_checkpoint(
    model_dir: str, step: int, read_attempts: int = 3
) -> None:
    """Raise CheckpointCorruptError (damaged) or OSError (unreadable) if
    checkpoint `step` cannot be restored; return None when valid.

    A CRC trailer, when present, certifies every byte of the file, so
    the (possibly large) codec + msgpack decode is skipped — validation
    on the evaluator's poll path must not double each checkpoint's load
    cost. Legacy trailer-less files get the full decode: a restore is
    the only way to detect their truncation. Callers that wrap this in
    their own retry loop (_await_readable) pass read_attempts=1 so the
    two backoff schedules don't multiply."""
    data, had_trailer = _read_payload(model_dir, step, read_attempts)
    if not had_trailer:
        _decode_payload(data, checkpoint_path(model_dir, step))


def quarantine_checkpoint(model_dir: str, step: int) -> str:
    """Rename a damaged checkpoint out of the `model_step_N` namespace so
    resume/eval stop considering it, but the bytes stay for forensics."""
    path = checkpoint_path(model_dir, step)
    target = path + QUARANTINE_SUFFIX
    os.replace(path, target)
    logger.warning("quarantined corrupt checkpoint %s -> %s", path, target)
    return target


def latest_valid_step(model_dir: str) -> Optional[int]:
    """Newest step whose file passes a full integrity check, skipping
    (but not touching) corrupt/truncated ones — read-only, so a polling
    evaluator can call it while racing the trainer's writer."""
    for step in reversed(available_steps(model_dir)):
        try:
            verify_checkpoint(model_dir, step)
            return step
        except (CheckpointCorruptError, OSError) as e:
            logger.warning(
                "checkpoint step %d is not loadable (%s); trying older",
                step, e,
            )
    return None


def listify_raw(tree):
    """msgpack restores list-typed pytree nodes as dicts {'0': ...}; undo.

    Shared by every raw-dict consumer (cli/evaluate_lm, serve/engine):
    the LM checkpoint's params carry a ``blocks`` LIST, and a consumer
    rebuilding model structure from the raw dict needs it back."""
    if isinstance(tree, dict):
        if tree and all(k.isdigit() for k in tree):
            return [listify_raw(tree[str(i)]) for i in range(len(tree))]
        return {k: listify_raw(v) for k, v in tree.items()}
    return tree


def load_latest_valid(model_dir: str, after_step: Optional[int] = None):
    """Read-only fast path for a polling consumer (the serving engine's
    hot-rollover poll): newest checkpoint strictly newer than
    ``after_step`` (None = any), loaded as raw nested dicts in ONE read.

    ``latest_valid_step`` + ``load_checkpoint_raw`` pay two reads per
    file (verify, then load — inherent to checking before yielding a
    step to an arbitrary consumer). Here the consumer is this function's
    own caller, so the CRC check and the decode run on the SAME in-memory
    bytes: one read per candidate, corrupt/unreadable files are skipped
    (never touched — the writer may still be racing us), and the result
    is ``(step, raw_dict)`` or None when nothing newer loads."""
    for step in reversed(available_steps(model_dir)):
        if after_step is not None and step <= after_step:
            return None
        try:
            data, _ = _read_payload(model_dir, step, read_attempts=1)
            return step, _decode_payload(data, checkpoint_path(model_dir, step))
        except (CheckpointCorruptError, OSError) as e:
            logger.warning(
                "checkpoint step %d is not loadable (%s); trying older",
                step, e,
            )
    return None


def load_checkpoint(target, model_dir: str, step: int):
    """Load step N into the structure of `target` (an initialized state).
    Auto-detects codec-compressed checkpoints.

    Forward-compat: a top-level field that exists in `target` with value
    None but is absent from the stored dict (a field added to the state
    AFTER the checkpoint was written, e.g. PSTrainState.comm_state) is
    filled with None instead of hard-erroring — old checkpoints stay
    resumable as long as the new feature is off. A non-None target field
    still errors loudly (its state genuinely cannot be reconstructed).
    The converse mismatch — the checkpoint CARRIES state for a feature
    the target has off (stored comm_state, target None) — also errors
    loudly: flax would otherwise pass the raw arrays through a None
    target silently, and dropping accumulated EF residuals would quietly
    change the training math.

    RESETTABLE_FIELDS (guard_state) get softer treatment in BOTH
    directions: absent from the checkpoint -> restored as the target's
    fresh value (counters re-zeroed); present but disabled in the target
    -> dropped. They are observability, and must never strand a
    checkpoint the way lost EF residuals would."""
    return restore_from_raw(target, _restore_raw(model_dir, step), step)


def restore_from_raw(target, raw, step: int):
    """The merge half of ``load_checkpoint``: raw nested dicts (already
    read and decoded — or transformed, e.g. by the elastic resume-reshape
    in resilience/elastic.py) into the structure of ``target``, with the
    same forward-compat and RESETTABLE_FIELDS rules documented there."""
    tgt_dict = serialization.to_state_dict(target)
    if isinstance(raw, dict) and isinstance(tgt_dict, dict):
        for k, v in tgt_dict.items():
            if k in RESETTABLE_FIELDS:
                if v is None:
                    # guard off now: drop whatever was stored — and fill
                    # the key in when a pre-guard checkpoint lacks it, or
                    # from_state_dict errors on the missing field
                    raw[k] = None
                elif raw.get(k) is None:
                    # pre-guard checkpoint (key absent) or guard-off run
                    # (stored None): restore the target's fresh counters
                    raw[k] = v
                elif isinstance(raw[k], dict) and isinstance(v, dict):
                    # both sides carry state: the owning module decides
                    # what survives the config change (e.g. the guard's
                    # dyn-flag/loss-scale rules live in resilience/guard)
                    raw[k] = RESETTABLE_FIELDS[k](raw[k], v)
                continue
            if k not in raw and v is None:
                raw[k] = None
            elif v is None and raw.get(k) is not None:
                raise ValueError(
                    f"checkpoint step {step} carries state for field {k!r} "
                    f"but the target state has it disabled (None). Enable "
                    f"the matching feature (e.g. --error-feedback for "
                    f"comm_state) to resume this checkpoint, or rebuild it "
                    f"without that state."
                )
    return serialization.from_state_dict(target, raw)


def restore_sharded(target, model_dir: str, step: int, mesh, specs):
    """Load step N and place every leaf on `mesh` with its PartitionSpec
    from `specs` (a pytree of PartitionSpecs, e.g. parallel.tp_param_specs
    output or an opt_state_specs tree).

    save_checkpoint gathers sharded arrays to full host arrays
    (device_get single-process; multihost_utils.process_allgather when
    process_count > 1), so a checkpoint written from a tp/pp/moe-sharded
    state restores onto ANY mesh shape whose specs divide the shapes —
    resharding across different device counts (and host counts) is free.
    """
    from .parallel.mesh import place_on_mesh

    return place_on_mesh(load_checkpoint(target, model_dir, step), mesh, specs)


def load_checkpoint_raw(model_dir: str, step: int,
                        read_attempts: int = 3) -> dict:
    """Load step N as raw nested dicts, no target structure required.

    This is what lets the evaluator stay ignorant of the trainer's optimizer
    and placement config: it only consumes params/batch_stats/step and never
    needs to reconstruct the opt_state pytree (whose structure varies by
    --optimizer/--opt-placement).

    ``read_attempts=1`` disables the I/O retry backoff — the serving
    engine's swap-time re-read (serve/engine._try_swap) wants a fast
    verdict on the staged file, not a multi-attempt stall inside the
    request loop; its caller treats OSError as an abort, not a retry."""
    return _restore_raw(model_dir, step, read_attempts)


def available_steps(model_dir: str):
    if not os.path.isdir(model_dir):
        return []
    steps = []
    for name in os.listdir(model_dir):
        m = CKPT_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(model_dir: str) -> Optional[int]:
    steps = available_steps(model_dir)
    return steps[-1] if steps else None


def _await_readable(model_dir: str, step: int, attempts: int,
                    base_delay_s: float) -> bool:
    """True once checkpoint `step` fully restores; retries both OSError
    (NFS close-to-open visibility: listed but not yet openable) and
    corruption (a replica still propagating) with backoff. False = gave
    up — the caller skips the step instead of dying downstream."""
    try:
        retry_io(
            # read_attempts=1: this outer loop IS the retry schedule;
            # _read_payload's internal retry would multiply it (5 outer
            # x 3 inner = 15 reads with compounded backoff)
            lambda: verify_checkpoint(model_dir, step, read_attempts=1),
            desc=f"checkpoint step {step} readability",
            attempts=attempts,
            base_delay_s=base_delay_s,
            retry_on=(OSError, CheckpointCorruptError),
        )
        return True
    except (OSError, CheckpointCorruptError) as e:
        logger.warning(
            "checkpoint step %d never became readable (%s): skipping it",
            step, e,
        )
        return False


def poll_checkpoints(
    model_dir: str,
    start_after: int = 0,
    interval_s: float = 10.0,
    timeout_s: Optional[float] = None,
    validate: bool = True,
    validate_attempts: int = 5,
    validate_delay_s: float = 0.2,
) -> Iterator[int]:
    """Yield new checkpoint steps as they appear (evaluator's consume loop;
    parity: distributed_evaluator.py:79-88 polls every 10s). Stops when
    `timeout_s` elapses with no new checkpoint (None = poll forever).

    With `validate` (default), a step visible in the directory listing
    but not yet fully readable — slow NFS visibility, or a corrupt file —
    is retried with backoff and then SKIPPED rather than yielded once and
    left to crash the consumer (the reference evaluator's torch.load
    simply died there)."""
    seen = start_after
    waited = 0.0
    while True:
        fresh = [s for s in available_steps(model_dir) if s > seen]
        if fresh:
            waited = 0.0
            for s in fresh:
                seen = s
                if validate and not _await_readable(
                    model_dir, s, validate_attempts, validate_delay_s
                ):
                    continue
                yield s
            continue
        if timeout_s is not None and waited >= timeout_s:
            return
        time.sleep(interval_s)
        waited += interval_s
