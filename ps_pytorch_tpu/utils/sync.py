"""Honest device synchronization for timing code.

Two traps this helper exists to avoid (both observed on the tunneled
single-chip platform):

1. `jax.block_until_ready` returning before the computation retires —
   timing loops built on it silently measure dispatch rate, not compute.
   A HOST read (`float(...)`) cannot lie: the value must exist.
2. Per-buffer readiness: reading a step's *loss* does not serialize the
   same step's parameter update, because in every train step here the
   metrics outputs are produced by the forward/backward pass while the
   gradient aggregation + optimizer apply feed only the params outputs.

`host_sync(*trees)` dispatches one tiny jitted reduction that consumes one
element of EVERY array leaf of every tree passed, then host-reads the
scalar — so it returns only after every buffer in those trees has retired.
Cost: one element per leaf + one scalar transfer.
"""

from __future__ import annotations

from functools import reduce

import jax
import jax.numpy as jnp


@jax.jit
def _probe(leaves):
    return reduce(
        lambda acc, x: acc + x.ravel()[0].astype(jnp.float32),
        leaves,
        jnp.float32(0.0),
    )


def host_sync(*trees) -> float:
    """Block until every array leaf of every tree has actually been
    computed, via a host read that depends on all of them. Returns the
    (meaningless) probe scalar so callers can keep a data dependency."""
    leaves = [
        x
        for t in trees
        for x in jax.tree_util.tree_leaves(t)
        if hasattr(x, "dtype") and getattr(x, "size", 0)
    ]
    if not leaves:
        return 0.0
    return float(_probe(leaves))
