"""Per-iteration structured logging, format-compatible with the reference.

The reference emits one log line per worker iteration
(/root/reference/src/distributed_worker.py:169-173):

  Worker: R, Step: S, Epoch: E [seen/total (p%)], Loss: L, Time Cost: T,
  FetchWeight: t1, Forward: t2, Backward: t3, Comm Cost: t4

and its downstream analysis layer regex-parses exactly that shape
(src/tiny_tuning_parser.py:14-27; analysis/*.ipynb cell 2). We keep the
format so those parsers carry over unchanged — with TPU-native semantics
for the phase fields, documented in format_iter_line:

- FetchWeight: host->device batch transfer ("fetch" on TPU is the input
  pipeline; weights never move — they live replicated on the mesh).
- Forward: the fused jitted train step (forward+backward+aggregate+update
  execute as ONE XLA program; there is no separable backward wall time).
- Backward / Comm Cost: 0.0 by construction — XLA fuses backprop and
  overlaps the psum collectives inside the step. Reported as zero rather
  than fabricated splits.
"""

from __future__ import annotations

import logging
import re
import time
from typing import Dict, Optional

_FMT = (
    "Worker: {rank}, Step: {step}, Epoch: {epoch} [{seen}/{total} ({pct:.0f}%)], "
    "Loss: {loss:.4f}, Time Cost: {time_cost:.4f}, FetchWeight: {fetch:.4f}, "
    "Forward: {forward:.4f}, Backward: {backward:.4f}, Comm Cost: {comm:.4f}"
)

# Matches both our lines and the reference's (whose Epoch bracket payload
# varies); keep group order aligned with tiny_tuning_parser.py:17.
ITER_LOG_RE = re.compile(
    r"Worker: (?P<rank>\S+), Step: (?P<step>\d+), Epoch: (?P<epoch>\d+) "
    r"\[(?P<seen>\d+)/(?P<total>\d+) \((?P<pct>[\d.]+)%\)\], "
    r"Loss: (?P<loss>[\d.eE+-]+|-?nan|-?inf), Time Cost: (?P<time_cost>[\d.eE+-]+), "
    r"FetchWeight: (?P<fetch>[\d.eE+-]+), Forward: (?P<forward>[\d.eE+-]+), "
    r"Backward: (?P<backward>[\d.eE+-]+), Comm Cost: (?P<comm>[\d.eE+-]+)"
)


def get_logger(name: str = "ps_pytorch_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter("INFO: %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def format_iter_line(
    rank,
    step: int,
    epoch: int,
    seen: int,
    total: int,
    loss: float,
    time_cost: float,
    fetch: float = 0.0,
    forward: float = 0.0,
    backward: float = 0.0,
    comm: float = 0.0,
) -> str:
    pct = 100.0 * seen / total if total else 0.0
    return _FMT.format(
        rank=rank,
        step=step,
        epoch=epoch,
        seen=seen,
        total=total,
        pct=pct,
        loss=loss,
        time_cost=time_cost,
        fetch=fetch,
        forward=forward,
        backward=backward,
        comm=comm,
    )


def parse_iter_line(line: str) -> Optional[Dict[str, float]]:
    """Parse one iteration line -> dict of floats (None if no match).
    The analysis layer (analysis/speedup.py) builds on this."""
    m = ITER_LOG_RE.search(line)
    if not m:
        return None
    out: Dict[str, float] = {}
    for k, v in m.groupdict().items():
        try:
            out[k] = float(v)
        except ValueError:
            out[k] = v  # rank may be non-numeric
    return out


def format_eval_line(step: int, loss: float, prec1: float, prec5: float) -> str:
    """Evaluator report (parity: distributed_evaluator.py:90-106 prints
    test loss / Prec@1 / Prec@5 per evaluated checkpoint)."""
    return (
        f"Validation Step: {step}, Loss: {loss:.4f}, "
        f"Prec@1: {prec1:.2f}, Prec@5: {prec5:.2f}"
    )


class PhaseTimer:
    """Wall-clock phase spans for the per-iteration line.

    Usage:
        t = PhaseTimer()
        with t.phase("fetch"): ...
        with t.phase("forward"): ...
        t.total  # since construction/reset
    """

    def __init__(self):
        self.reset()

    def reset(self):
        self._start = time.perf_counter()
        self.durations: Dict[str, float] = {}

    def phase(self, name: str):
        return _Span(self, name)

    @property
    def total(self) -> float:
        return time.perf_counter() - self._start


class _Span:
    def __init__(self, timer: PhaseTimer, name: str):
        self._timer, self._name = timer, name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.durations[self._name] = (
            self._timer.durations.get(self._name, 0.0)
            + time.perf_counter()
            - self._t0
        )
        return False
