"""Utilities: structured iteration logging (reference-parseable), phase
timing, and profiler hooks."""

from .compile_cache import enable_persistent_compile_cache
from .sync import host_sync
from .logging import (
    ITER_LOG_RE,
    PhaseTimer,
    format_eval_line,
    format_iter_line,
    get_logger,
    parse_iter_line,
)
