"""Persistent XLA compile cache — shared by bench.py and cli/tune.py.

First compile of a big train step is ~20-40s on TPU; the disk cache makes
every later process with the same HLO skip straight to steady state. Note
the cache keys on the HLO hash: a sweep whose candidates differ in a baked
constant (e.g. tune's lr grid — each lr is folded into the optimizer
transform) still compiles each DISTINCT candidate once, but re-running the
same sweep (the common tuning workflow) compiles nothing.
"""

from __future__ import annotations

import os


def enable_persistent_compile_cache(
    cache_dir: str | None = None,
) -> None:
    import jax

    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            cache_dir
            or os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/ps_tpu_jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without these options
